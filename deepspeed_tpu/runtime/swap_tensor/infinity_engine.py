"""ZeRO-Infinity layer-streaming executor.

Role parity: the reference's ZeRO-Infinity path — ``zero/stage3.py`` +
``swap_tensor/*`` + ``zero/partitioned_param_coordinator.py`` prefetch
machinery (SURVEY §2.1) — which lets params + optimizer state exceed device
(and with NVMe, host) memory.

TPU-first shape (SURVEY §7 hard-part 3): the training step cannot be one
jitted program when params don't fit HBM, so the step is a *Python pipeline
over per-layer jitted programs* with double-buffered transfers:

    fwd:  h2d(layer i+1) ‖ compute(layer i)           [read-ahead]
    bwd:  h2d(layer i-1) ‖ vjp(layer i) ; d2h grads → C++ Adam → NVMe
                                                      [write-behind]

Peak HBM = 2 layers of wire params + the activation stack; peak host RAM =
all layers (cpu tier) or ``buffer_count`` layers (nvme tier).  The embed /
final-norm / head ("resident") params stay on device with a normal optax
update — they are O(vocab·H), small next to the trunk.

The model contract is three pure fns (``LlamaModel`` implements it):
``embed_fwd(params, ids)``, ``decoder_layer(lp, x) -> (x, aux)``,
``head_loss(params, x, batch)``.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ...telemetry.perf import get_compile_tracker, tracked_jit
from ...utils.logging import log_dist, logger
from .partitioned_param_swapper import PartitionedParamSwapper


def _jit(fn, site: str, **jit_kwargs):
    """Every streaming-engine program rides the compile tracker — the
    per-layer fwd/bwd programs are exactly the kind of high-count jit
    sites whose recompiles (a new layer shape bucket) must be named."""
    return tracked_jit(fn, site=site, tracker=get_compile_tracker(),
                       **jit_kwargs)


class LayerStreamingEngine:
    """Train-step executor for models whose trunk params live off-device.

    With ``mesh``/``base_specs`` (round 3), streaming composes with
    DP/TP/SP: each layer's wire params land h2d directly in their TP
    sharding (replicated over DP), activations ride the DP axes, and the
    per-layer programs are ordinary SPMD jits — the reference's Infinity
    likewise runs under full data parallelism (``zero/stage3.py`` +
    ``swap_tensor/*``, SURVEY §2.1).

    MULTI-CONTROLLER (``jax.process_count() > 1``): host planes are
    PER-PROCESS — each process owns 1/world of every layer's flat
    master/moments/wire plane (the reference's partitioned optimizer
    state).  The wire chunk rides a device-sharded global array and is
    all-gathered IN-GRAPH into the layer's compute shardings (XLA
    collectives over ICI/DCN); gradients reduce-scatter back the same way
    and each process d2h's only its addressable slice.  Host RAM and nvme
    bytes per process: O(layer/world)."""

    def __init__(self, model: Any, params: Any, config: Any,
                 schedule: Callable[[int], float], mesh: Any = None,
                 base_specs: Any = None):
        c = model.config
        self.model = model
        self.config = config
        self.schedule = schedule
        self.mesh = mesh
        self.L = int(c.num_layers)
        self.compute_dtype = config.dtype()
        # fp16 loss scaling (reference fp16 + Infinity coexist): the
        # scaler state lives HOST-SIDE — the streamed step is a Python
        # pipeline, so the skip/backoff decision is eager.  fp16 routes
        # through the STASH path (updates deferred until the overflow
        # vote), never the fused write-behind.  Scaler counters are not
        # persisted across checkpoint resume (the scale re-warms).
        self.fp16 = config.fp16.enabled is True
        if self.fp16:
            from ..precision import DynamicLossScaler

            self.scaler = DynamicLossScaler.from_config(config.fp16)
            self.scale_state = self.scaler.init_state()
        wire_dtype = (self.compute_dtype
                      if self.compute_dtype != jnp.float32 else jnp.float32)

        opt_cfg = config.optimizer
        hp: Dict[str, Any] = {}
        if opt_cfg is not None:
            name = opt_cfg.type.lower()
            if name not in ("adam", "adamw", "cpu_adam"):
                raise NotImplementedError(
                    f"layer streaming drives the fused C++ Adam(W) kernel; "
                    f"optimizer '{opt_cfg.type}' is not supported here "
                    "(supported: Adam, AdamW)")
            p = dict(opt_cfg.params.model_dump())
            p.update(opt_cfg.params.model_extra or {})
            for k in ("lr", "betas", "eps", "weight_decay"):
                if k in p and not isinstance(p[k], str):
                    hp[k] = p[k]
            hp["adamw_mode"] = name != "adam"
        self._base_lr = float(hp.get("lr", 1e-3))
        #: router load-balancing weight (MoE models); aux grads flow through
        #: the per-layer vjp cotangent so streaming matches the fused path
        self.aux_coef = float(getattr(model, "aux_loss_coef", 0.0))

        zcfg = config.zero_optimization
        pcfg = zcfg.offload_param
        nvme_path = None
        if pcfg is not None and getattr(pcfg, "device", None) is not None:
            from ..zero.config import OffloadDeviceEnum

            if pcfg.device == OffloadDeviceEnum.nvme:
                if not pcfg.nvme_path:
                    raise ValueError(
                        "offload_param.device=nvme requires nvme_path")
                nvme_path = pcfg.nvme_path

        # split: trunk layers → swapper; everything else resident on device.
        # one() keeps the SOURCE dtype: for numpy inputs these are views
        # (no copy) — the swapper's plane fill does the fp32 cast per
        # layer, so peak host memory is planes + the original tree, not
        # planes + a second fp32 copy of the whole trunk (an 8B trunk is
        # 28 GB per copy)
        layers = params["layers"]
        resident = {k: v for k, v in params.items() if k != "layers"}
        one = lambda leaf, i: np.asarray(leaf[i])
        layer_trees = [jax.tree.map(functools.partial(one, i=i), layers)
                       for i in range(self.L)]

        self.proc_world = jax.process_count()
        if self.proc_world > 1 and mesh is None:
            raise ValueError(
                "multi-controller ZeRO-Infinity needs a mesh (pass mesh= "
                "to initialize, or build the model with one)")

        layer_specs = None
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            if isinstance(base_specs, dict) and "layers" in base_specs:
                # per-layer specs = stacked specs minus the leading
                # (pipe/stack) dim
                layer_specs = jax.tree.map(
                    lambda s: P(*tuple(s)[1:]), base_specs["layers"],
                    is_leaf=lambda x: isinstance(x, P))

        placement = None
        shard = None
        if self.proc_world > 1:
            # per-process host planes: each process owns the flat-plane
            # segments its devices cover; device assembly is the in-graph
            # all-gather built in _build_flat_fns below
            placement, shard = self._build_flat_fns(
                layer_trees[0], layer_specs)
        elif mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ...parallel.mesh import strip_manual_axes

            def placement(views, _specs=layer_specs):
                if _specs is None:
                    return jax.tree.map(
                        lambda v: jax.device_put(
                            np.array(v), NamedSharding(mesh, P())), views)
                return jax.tree.map(
                    lambda v, s: jax.device_put(
                        np.array(v),
                        NamedSharding(mesh, strip_manual_axes(*s))),
                    views, _specs)

        # Pipelined optimizer swapping (reference
        # pipelined_optimizer_swapper.py) is the PRODUCTION DEFAULT: the
        # host Adam runs in a worker thread behind device compute.  The
        # reference gates it behind offload_optimizer.pipeline_read/write;
        # here an explicitly-false pair opts out (and
        # DS_INFINITY_SERIAL_OPT=1 is the debugging kill switch).
        ocfg = zcfg.offload_optimizer
        pipeline = True
        if ocfg is not None and {
                "pipeline_read", "pipeline_write"} & ocfg.model_fields_set:
            pipeline = bool(ocfg.pipeline_read or ocfg.pipeline_write)
        if os.environ.get("DS_INFINITY_SERIAL_OPT", "0") == "1":
            pipeline = False  # the debugging kill switch beats any config
        self.swapper = PartitionedParamSwapper(
            layer_trees, wire_dtype=wire_dtype, nvme_path=nvme_path,
            buffer_count=int(getattr(pcfg, "buffer_count", 4) or 4),
            aio_config=config.aio, adam_hparams=hp, placement=placement,
            shard=shard, pipeline=pipeline)
        del layer_trees, layers

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ...parallel.mesh import strip_manual_axes

            res_specs = (base_specs if isinstance(base_specs, dict) else {})

            from ...parallel.mesh import global_put

            def _place(v, s):
                sh = NamedSharding(mesh, strip_manual_axes(*s)
                                   if isinstance(s, P) else P())
                return global_put(np.asarray(v, dtype=np.float32), sh)

            self.resident = {
                k: (jax.tree.map(lambda a: _place(a, None), v)
                    if k not in res_specs
                    else jax.tree.map(_place, v, res_specs[k]))
                for k, v in resident.items()}
        else:
            self.resident = jax.tree.map(
                lambda x: jnp.asarray(np.asarray(x), jnp.float32), resident)
        self.res_tx = optax.adamw(
            learning_rate=lambda s: jnp.asarray(schedule(s), jnp.float32),
            b1=float(hp.get("betas", (0.9, 0.999))[0]),
            b2=float(hp.get("betas", (0.9, 0.999))[1]),
            eps=float(hp.get("eps", 1e-8)),
            weight_decay=float(hp.get("weight_decay", 0.0)))
        self.res_opt_state = self.res_tx.init(self.resident)

        gas = config.gradient_accumulation_steps
        self.gas = int(gas) if isinstance(gas, int) else 1
        clip = config.gradient_clipping
        self.clip = 0.0 if isinstance(clip, str) else float(clip or 0.0)

        self.global_steps = 0
        self.last_metrics: Dict[str, Any] = {}
        self._jits: Dict[str, Any] = {}
        n_trunk = self.swapper.n_elems * self.L
        n_res = sum(int(np.prod(np.shape(x)))
                    for x in jax.tree.leaves(self.resident))
        log_dist(f"ZeRO-Infinity streaming engine: {self.L} layers, "
                 f"{n_trunk:,} trunk params off-device "
                 f"({'nvme' if nvme_path else 'cpu'} tier), "
                 f"{n_res:,} resident on device")

    # ------------------------------------------------------------------
    # multi-controller flat-plane machinery
    # ------------------------------------------------------------------

    def _build_flat_fns(self, layer_tree: Any, layer_specs: Any):
        """Build the in-graph gather/scatter pair for per-process planes.

        Returns ``(placement, shard)``: the placement fn maps the local
        flat wire plane → device layer pytree in its compute shardings
        (XLA all-gathers over the mesh); ``shard`` is the swapper's
        segment table.  Segments come from the ACTUAL device sharding of
        the flat plane (``devices_indices_map``), so permuted mesh device
        orders — ICI-topology meshes — map host bytes to the right global
        offsets.  Also installs ``self._scatter_flat``: device grad pytree
        → this process's local flat fp32 plane (in-graph layout + d2h of
        only the addressable shards)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ...parallel.mesh import strip_manual_axes
        from .partitioned_param_swapper import _leaf_layout

        mesh = self.mesh
        treedef, layout = _leaf_layout(layer_tree)
        n_elems = sum(int(np.prod(s)) if s else 1 for s, _ in layout)
        n_dev = int(mesh.devices.size)
        n_pad = -(-n_elems // n_dev) * n_dev
        flat_sh = NamedSharding(mesh, P(tuple(mesh.axis_names)))

        # global index segments, grouped by owning process, sorted by start
        dev_map = flat_sh.devices_indices_map((n_pad,))
        by_proc: Dict[int, list] = {}
        for d, idx in dev_map.items():
            sl = idx[0]
            by_proc.setdefault(d.process_index, []).append(
                (int(sl.start or 0), int(sl.stop or n_pad)))
        gather_map = [sorted(by_proc.get(p, []))
                      for p in range(self.proc_world)]
        me = jax.process_index()
        segments = gather_map[me]
        # device → plane offset of its slice (plane = segments in order)
        plane_off = {}
        off = 0
        for a, b in segments:
            plane_off[a] = off
            off += b - a
        local_devs = sorted(
            [(int(idx[0].start or 0), d) for d, idx in dev_map.items()
             if d.process_index == me])

        if layer_specs is None:
            out_sh = jax.tree.unflatten(
                treedef, [NamedSharding(mesh, P())] * len(layout))
        else:
            out_sh = jax.tree.map(
                lambda s: NamedSharding(mesh, strip_manual_axes(*s)),
                layer_specs, is_leaf=lambda x: isinstance(x, P))

        def assemble(flat):
            views = [flat[off:off + (int(np.prod(s)) if s else 1)]
                     .reshape(s) for s, off in layout]
            return jax.tree.unflatten(treedef, views)

        assemble_jit = _jit(assemble, "infinity/assemble", out_shardings=out_sh)

        def scatter(tree):
            leaves = jax.tree.leaves(tree)
            flat = jnp.concatenate(
                [l.reshape(-1).astype(jnp.float32) for l in leaves])
            return jnp.pad(flat, (0, n_pad - n_elems))

        scatter_jit = _jit(scatter, "infinity/scatter", out_shardings=flat_sh)

        def local_chunk(garr) -> np.ndarray:
            # shards land in the plane at their segment's offset — the
            # same global-order rule the swapper's planes use
            out = np.empty((off,), np.float32)
            for s in garr.addressable_shards:
                a = int(s.index[0].start or 0)
                o = plane_off[a]
                out[o:o + (int(s.index[0].stop or n_pad) - a)] = \
                    np.asarray(s.data)
            return out

        def placement(local_wire: np.ndarray):
            # one single-device array per local device, each a view into
            # the plane at that device's segment
            arrs = [
                jax.device_put(
                    local_wire[plane_off[a]:plane_off[a]
                               + (int(dev_map[d][0].stop or n_pad) - a)],
                    d)
                for a, d in local_devs]
            garr = jax.make_array_from_single_device_arrays(
                (n_pad,), flat_sh, arrs)
            return assemble_jit(garr)

        self._scatter_flat = lambda tree: local_chunk(scatter_jit(tree))
        shard = {"rank": me, "world": self.proc_world, "n_pad": n_pad,
                 "segments": segments, "gather_map": gather_map}
        return placement, shard

    def _trunk_grads(self, dlp: Any) -> Any:
        """What the swapper's update path consumes for one layer's grads:
        the tree itself (single-controller) or this process's local flat
        chunk (multi-controller)."""
        if self.proc_world > 1:
            return self._scatter_flat(dlp)
        return dlp

    def _host_sum(self, x: float) -> float:
        """Sum a per-process host scalar across processes (no-op single)."""
        if self.proc_world == 1:
            return float(x)
        from jax.experimental import multihost_utils

        return float(np.sum(multihost_utils.process_allgather(
            np.asarray(x, np.float32))))

    # ------------------------------------------------------------------
    # jitted pieces (compiled once; shared across layers)
    # ------------------------------------------------------------------

    def _fn(self, name: str):
        if name in self._jits:
            return self._jits[name]
        model = self.model
        dtype = self.compute_dtype

        def cast_res(res):
            return jax.tree.map(
                lambda p: p.astype(dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, res)

        if name == "embed":
            fn = _jit(lambda res, ids: model.embed_fwd(cast_res(res), ids),
                      "infinity/embed")
        elif name == "layer_fwd":
            fn = _jit(lambda lp, x: model.decoder_layer(lp, x),
                      "infinity/layer_fwd")
        elif name == "layer_bwd":
            aux_coef = self.aux_coef

            def bwd(lp, x, dx, ls):
                # cotangents: dx from downstream + d(total_loss)/d(aux) =
                # aux_coef·ls — this is how the router balancing loss
                # reaches the layer params without a second pass (ls = the
                # fp16 loss scale riding every cotangent; 1 otherwise)
                (out, aux), vjp = jax.vjp(model.decoder_layer, lp, x)
                del out, aux
                dlp, dx_prev = vjp((dx, jnp.float32(aux_coef) * ls))
                return dx_prev, dlp
            fn = _jit(bwd, "infinity/layer_bwd")
        elif name == "head_grad":
            def head(res, x, batch, ls):
                # fp16: the SCALED loss is what gets differentiated, so
                # cotangents stay in fp16 range through every layer
                return model.head_loss(cast_res(res), x, batch) * ls
            fn = _jit(jax.value_and_grad(head, argnums=(0, 1)),
                      "infinity/head_grad")
        elif name == "embed_grad":
            # static by design: vocab size is fixed for a model's life
            V = int(self.model.config.vocab_size)

            def embed_grad(ids, dx):
                flat_ids = ids.reshape(-1)
                flat_dx = dx.reshape(-1, dx.shape[-1]).astype(jnp.float32)
                return jnp.zeros((V, dx.shape[-1]),  # dslint: disable=recompile-hazard
                                 jnp.float32).at[flat_ids].add(flat_dx)
            fn = _jit(embed_grad, "infinity/embed_grad")
        elif name == "res_update":
            tx = self.res_tx

            def res_update(res, opt_state, grads, scale):
                grads = jax.tree.map(
                    lambda g: g.astype(jnp.float32) * scale, grads)
                updates, new_state = tx.update(grads, opt_state, res)
                return optax.apply_updates(res, updates), new_state
            fn = _jit(res_update, "infinity/res_update",
                      donate_argnums=(0, 1))
        elif name == "sq_norm":
            def sq_norm(tree):
                leaves = jax.tree.leaves(tree)
                return sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                           for l in leaves)
            fn = _jit(sq_norm, "infinity/sq_norm")
        else:
            raise KeyError(name)
        self._jits[name] = fn
        return fn

    # ------------------------------------------------------------------
    # the streamed train step
    # ------------------------------------------------------------------

    def _place_batch(self, batch: Any) -> Any:
        """DP-shard the batch over the mesh (no-op single-chip).  Arrays
        the engine already assembled globally pass through; multi-process
        host leaves are this process's LOCAL rows."""
        if self.mesh is None:
            return batch
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ...parallel.mesh import DP_AXES, global_feed

        sh = NamedSharding(self.mesh, P(DP_AXES))
        return jax.tree.map(lambda x: global_feed(x, sh), batch)

    def train_step(self, batch: Any) -> Dict[str, Any]:
        model = self.model
        L, sw = self.L, self.swapper
        gas = self.gas
        layer_fwd = self._fn("layer_fwd")
        layer_bwd = self._fn("layer_bwd")
        sq_norm = self._fn("sq_norm")
        # fused mode: update each layer during backward (write-behind).
        # gas > 1, global clipping, AND fp16 all need the full gradient
        # before any update (fp16: the overflow vote must precede every
        # apply), so they stash grad planes and run a second (update) pass
        # — the reference separates backward and optimizer.step() the same
        # way.
        fused = (gas == 1 and self.clip <= 0.0 and not self.fp16)
        ls = float(self.scale_state.scale) if self.fp16 else 1.0
        ls_dev = jnp.float32(ls)

        lr = float(self.schedule(self.global_steps))
        sw.begin_step()

        if gas > 1:
            rows = int(np.shape(jax.tree.leaves(batch)[0])[0])
            if rows % gas:
                raise ValueError(
                    f"batch rows {rows} not divisible by "
                    f"gradient_accumulation_steps {gas}")

            def split(x, k):
                n = np.shape(x)[0] // gas
                return x[k * n:(k + 1) * n]
            micros = [jax.tree.map(functools.partial(split, k=k), batch)
                      for k in range(gas)]
        else:
            micros = [batch]

        loss_sum = jnp.float32(0.0)
        norm_sq_dev = jnp.float32(0.0)
        g_res_acc = None
        for k, mb in enumerate(micros):
            mb = self._place_batch(mb)
            ids, _ = model.batch_labels(mb)

            # ---- forward: read-ahead one layer ----------------------------
            x = self._fn("embed")(self.resident, ids)
            acts: List[Any] = []
            aux_sum = jnp.float32(0.0)
            sw.prefetch(0)
            for i in range(L):
                lp = sw.get_device(i)
                sw.prefetch(i + 1)
                acts.append(x)
                x, aux = layer_fwd(lp, x)
                aux_sum = aux_sum + aux
                sw.release(i)

            loss, (g_res, dx) = self._fn("head_grad")(self.resident, x,
                                                      mb, ls_dev)
            loss_sum = loss_sum + loss / ls_dev + self.aux_coef * aux_sum

            # ---- backward: stream in reverse, update/stash behind ---------
            sw.prefetch(L - 1, full=fused)
            for i in reversed(range(L)):
                lp = sw.get_device(i)
                sw.prefetch(i - 1, full=fused)
                dx, dlp = layer_bwd(lp, acts[i], dx, ls_dev)
                acts[i] = None  # free the activation once consumed
                if fused:
                    norm_sq_dev = norm_sq_dev + sq_norm(dlp)
                    # pipelined: the worker's d2h + C++ Adam hide behind
                    # the remaining layers' backward on the device
                    sw.step_layer_async(i, self._trunk_grads(dlp), lr=lr)
                else:
                    sw.stash_grads(i, self._trunk_grads(dlp),
                                   accumulate=(k > 0))
                sw.release(i)

            # ---- resident grads: embed grad from dx + head grads ----------
            g_emb = self._fn("embed_grad")(ids, dx)
            g_res = dict(g_res)
            g_res["embed"] = g_res["embed"].astype(jnp.float32) + g_emb
            g_res_acc = (g_res if g_res_acc is None else
                         jax.tree.map(lambda a, b: a + b, g_res_acc, g_res))

        # ---- global grad norm, clip scale, deferred update pass -----------
        res_sq = float(sq_norm(g_res_acc))
        overflow = False
        if fused:
            grad_norm = float(np.sqrt(float(norm_sq_dev) + res_sq))
            scale = 1.0
        else:
            # gplanes/g_res_acc hold SUMS over micros scaled by ls; the
            # mean-loss grad is that sum / (gas·ls), so the norm divides
            # by gas·ls once.  Sharded planes are disjoint chunks → the
            # global norm is the cross-process sum of local dots
            trunk_sq = self._host_sum(sw.stashed_sq_norm())
            grad_norm = float(np.sqrt(trunk_sq + res_sq)) / (gas * ls)
            # fp16 overflow vote: any non-finite stashed/resident grad
            # poisons the norm — skip EVERY update, drop the stashed
            # planes, roll back the Adam step counter, back the scaler off
            overflow = self.fp16 and not np.isfinite(grad_norm)
            scale = 1.0 / (gas * ls)
            if not overflow:
                if self.clip > 0.0 and grad_norm > self.clip:
                    scale *= self.clip / grad_norm
                sw.prefetch(0, full=True)
                for i in range(L):
                    sw.prefetch(i + 1, full=True)
                    # pipelined: layer i's C++ Adam overlaps layer i+1's
                    # read-ahead (and, nvme tier, i-1's write-behind)
                    sw.apply_stashed_async(i, lr=lr, scale=scale)
            else:
                sw.discard_stashed()
                sw.cancel_step()

        if not overflow:
            self.resident, self.res_opt_state = self._fn("res_update")(
                self.resident, self.res_opt_state, g_res_acc,
                jnp.float32(scale))
            self.global_steps += 1
        if self.fp16:
            self.scale_state = self.scaler.update(self.scale_state,
                                                  jnp.bool_(overflow))

        sw.flush()
        metrics = {"loss": jnp.asarray(loss_sum) / gas,
                   "lr": jnp.float32(lr),
                   "grad_norm": jnp.float32(grad_norm),
                   "loss_scale": jnp.float32(ls),
                   "overflow": jnp.bool_(overflow)}
        self.last_metrics = metrics
        return metrics

    def sp_program_evidence(self, batch: Any) -> Dict[str, Any]:
        """Evidence that Ulysses SP is live INSIDE the streamed per-layer
        program: compiles layer 0's forward against a real embedded batch
        and reports whether its HLO contains the all-to-all and how the
        inter-layer activations are sharded.  Shared by the config-5
        composition test and the ``infinity_sp`` dryrun layout so the
        proof can't drift between the two."""
        ids, _ = self.model.batch_labels(self._place_batch(batch))
        x = self._fn("embed")(self.resident, ids)
        sw = self.swapper
        sw.prefetch(0)
        lp = sw.get_device(0)
        hlo = self._fn("layer_fwd").lower(lp, x).compile().as_text()
        sw.release(0)
        return {"all_to_all_in_layer_program": "all-to-all" in hlo,
                "activation_spec": str(x.sharding.spec)}

    def eval_loss(self, batch: Any) -> jnp.ndarray:
        """Streamed forward-only loss (no grads, no update)."""
        sw = self.swapper
        batch = self._place_batch(batch)
        ids, _ = self.model.batch_labels(batch)
        layer_fwd = self._fn("layer_fwd")
        x = self._fn("embed")(self.resident, ids)
        aux_sum = jnp.float32(0.0)
        sw.prefetch(0)
        for i in range(self.L):
            lp = sw.get_device(i)
            sw.prefetch(i + 1)
            x, aux = layer_fwd(lp, x)
            aux_sum = aux_sum + aux
            sw.release(i)
        if "head_loss_only" not in self._jits:
            model, dtype = self.model, self.compute_dtype
            self._jits["head_loss_only"] = _jit(
                lambda res, x_, b: model.head_loss(
                    jax.tree.map(lambda p: p.astype(dtype)
                                 if jnp.issubdtype(p.dtype, jnp.floating)
                                 else p, res), x_, b),
                "infinity/head_loss_only")
        loss = self._jits["head_loss_only"](self.resident, x, batch)
        return loss + self.aux_coef * aux_sum

    # ------------------------------------------------------------------
    # introspection / checkpoint hooks for the engine wrapper
    # ------------------------------------------------------------------

    def peak_device_param_bytes(self) -> int:
        """Wire bytes resident on device at the deepest point (2 layers)."""
        return 2 * self.swapper.n_elems * self.swapper.wire_np_dtype.itemsize

    def total_param_count(self) -> int:
        n_res = sum(int(np.prod(np.shape(x)))
                    for x in jax.tree.leaves(self.resident))
        return self.swapper.n_elems * self.L + n_res
