"""AutoTP training entry — ``tp_model_init`` [L HF-DS:464-473].

Reference: ``deepspeed/runtime/tensor_parallel/`` + ``module_inject/auto_tp``
[K] — walk the module graph, split linears row/col-wise, insert allreduce.
TPU-first: the "policy" is the model's ``param_specs()`` (tensor-axis
PartitionSpecs) and the "inserted allreduce" is GSPMD; so tp init reduces to
building/adopting a mesh with the requested tp degree and binding the model
to it.
"""

from __future__ import annotations

import re
from typing import Any, Optional

import jax
from jax.sharding import PartitionSpec

from ..parallel.mesh import AXIS_TENSOR, MeshLayout
from ..utils import groups as groups_mod
from ..utils.logging import log_dist

P = PartitionSpec

# AutoTP name policy (reference ``module_inject/auto_tp.py`` knowledge):
# COLUMN-split linears (output dim sharded): attention q/k/v and the MLP
# up/gate family; ROW-split (input dim sharded): attention output and the
# MLP down family.  Names cover this zoo + the common HF/Megatron spellings.
_COLUMN_PAT = re.compile(
    r"(^|[._])(wq|wk|wv|q_proj|k_proj|v_proj|query|key|value|qkv"
    r"|w_gate|w_up|gate_proj|up_proj|w_in|wi|fc1|intermediate"
    r"|dense_h_to_4h|lm_head)($|[._])")
_ROW_PAT = re.compile(
    r"(^|[._])(wo|o_proj|out_proj|w_down|down_proj|w_out|wo_proj|fc2"
    r"|dense_4h_to_h|attention_output)($|[._])")


def infer_tp_specs(params: Any, tp_axis: str = AXIS_TENSOR) -> Any:
    """AutoTP for arbitrary param pytrees: infer tensor-axis PartitionSpecs
    from leaf NAMES (reference role: ``AutoTP`` module-graph analysis —
    here the pytree paths are the graph).

    Convention: matmul leaves are ``[..., in, out]`` (this zoo's layout).
    Column-split names shard the last (output) dim, row-split names the
    second-to-last (input) dim; attention leaves with an explicit head dim
    ``[..., H, heads, hd]``/``[..., heads, hd, H]`` shard the heads dim.
    Everything unmatched (embeddings, norms, biases, 1-D) replicates —
    GSPMD keeps any placement numerically correct, so inference is purely
    a performance policy and safe by construction.
    """
    def leaf(path, p) -> PartitionSpec:
        ndim = getattr(p, "ndim", len(getattr(p, "shape", ())))
        if ndim < 2:
            return P()
        # match on the FULL joined path, not just the last key: Flax nests
        # {'q_proj': {'kernel': ...}} and torch-style dotted names put the
        # informative segment one level up
        keys = [(e.key if hasattr(e, "key") else str(e)) for e in path]
        name = ".".join(keys).lower()
        last = keys[-1].lower()
        none = (None,) * ndim
        if _COLUMN_PAT.search(name):
            if last in ("wq", "wk", "wv") and ndim >= 3:
                # [..., H, heads, hd] → shard the heads dim
                return P(*none[:-2], tp_axis, None)
            return P(*none[:-1], tp_axis)
        if _ROW_PAT.search(name):
            if last == "wo" and ndim >= 3:
                # [..., heads, hd, H] → shard the heads dim
                return P(*none[:-3], tp_axis, None, None)
            return P(*none[:-2], tp_axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(leaf, params)


def tp_model_init(model: Any = None, tp_size: int = 1, dtype: Any = None,
                  config: Any = None, mesh: Any = None) -> Any:
    """Bind ``model`` to a tp_size-way mesh; params created afterwards (or
    device_put by the engine) land column/row-sharded per the model's
    ``param_specs``."""
    if mesh is None:
        try:
            mesh = groups_mod.get_mesh()
            if int(mesh.shape.get("tensor", 1)) != tp_size:
                mesh = None
        except Exception:
            mesh = None
    if mesh is None:
        layout = MeshLayout.infer(jax.device_count(), tp=tp_size)
        mesh = groups_mod.initialize_mesh(layout)
    if hasattr(model, "mesh"):
        model.mesh = mesh
    if dtype is not None and hasattr(model, "config") and hasattr(
            model.config, "dtype"):
        try:
            object.__setattr__(model.config, "dtype", dtype)
        except Exception:
            import dataclasses

            model.config = dataclasses.replace(model.config, dtype=dtype)
    log_dist(f"tp_model_init: tp={tp_size} mesh={dict(mesh.shape)}")
    return model
