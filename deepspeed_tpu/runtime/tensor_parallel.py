"""AutoTP training entry — ``tp_model_init`` [L HF-DS:464-473].

Reference: ``deepspeed/runtime/tensor_parallel/`` + ``module_inject/auto_tp``
[K] — walk the module graph, split linears row/col-wise, insert allreduce.
TPU-first: the "policy" is the model's ``param_specs()`` (tensor-axis
PartitionSpecs) and the "inserted allreduce" is GSPMD; so tp init reduces to
building/adopting a mesh with the requested tp degree and binding the model
to it.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from ..parallel.mesh import MeshLayout
from ..utils import groups as groups_mod
from ..utils.logging import log_dist


def tp_model_init(model: Any = None, tp_size: int = 1, dtype: Any = None,
                  config: Any = None, mesh: Any = None) -> Any:
    """Bind ``model`` to a tp_size-way mesh; params created afterwards (or
    device_put by the engine) land column/row-sharded per the model's
    ``param_specs``."""
    if mesh is None:
        try:
            mesh = groups_mod.get_mesh()
            if int(mesh.shape.get("tensor", 1)) != tp_size:
                mesh = None
        except Exception:
            mesh = None
    if mesh is None:
        layout = MeshLayout.infer(jax.device_count(), tp=tp_size)
        mesh = groups_mod.initialize_mesh(layout)
    if hasattr(model, "mesh"):
        model.mesh = mesh
    if dtype is not None and hasattr(model, "config") and hasattr(
            model.config, "dtype"):
        try:
            object.__setattr__(model.config, "dtype", dtype)
        except Exception:
            import dataclasses

            model.config = dataclasses.replace(model.config, dtype=dtype)
    log_dist(f"tp_model_init: tp={tp_size} mesh={dict(mesh.shape)}")
    return model
