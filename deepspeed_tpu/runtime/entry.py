"""deepspeed.initialize() — the public factory.

Signature parity with the reference ``deepspeed/__init__.py:initialize``
[L ACC:2358-2439]: returns ``(engine, optimizer, training_dataloader,
lr_scheduler)``; accepts ``config`` | ``config_params`` (dict, path, or
base64), ``model_parameters``, user ``optimizer`` / ``lr_scheduler``, and
``mpu``.  Routes to PipelineEngine when the model is a PipelineModule
(reference behavior), else DeepSpeedEngine.

TPU adaptation of the model argument: the reference takes a torch
``nn.Module`` whose loss the USER computes eagerly.  Here ``model`` is one of
  * a pure loss function ``loss_fn(params, batch) -> scalar``        (JAX-natural)
  * an object exposing ``.loss(params, batch)`` (e.g. our model wrappers)
  * a ``PipelineModule`` (pipeline-parallel path)
with ``model_parameters`` the parameter pytree (or an abstract init thunk —
see ``zero.Init``).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax

from ..parallel.mesh import MeshLayout
from ..utils import groups as groups_mod
from ..utils.logging import log_dist
from .config import DeepSpeedConfig
from .engine import DeepSpeedEngine


def _resolve_config(config, config_params) -> DeepSpeedConfig:
    payload = config if config is not None else config_params
    if payload is None:
        raise ValueError("deepspeed_tpu.initialize needs config or config_params")
    if isinstance(payload, DeepSpeedConfig):
        return payload
    if not isinstance(payload, dict):
        from .config import _load_config_payload

        payload = _load_config_payload(payload)
    override = os.environ.get("DS_AUTOTUNING_CONFIG_OVERRIDE")
    if override:
        # the launcher's --autotuning orchestration hands each candidate
        # run its dotted-key overrides through the environment (the
        # reference's exp-config rewrite, deepspeed/autotuning/)
        import json as _json

        payload = dict(payload)
        for dotted, value in _json.loads(override).items():
            node = payload
            parts = dotted.split(".")
            for p in parts[:-1]:
                cur = node.get(p)
                if cur is not None and not isinstance(cur, dict):
                    # a dotted path must traverse objects; walking through
                    # e.g. a string would die later in an opaque TypeError
                    # that aborts the whole candidate run
                    raise ValueError(
                        f"DS_AUTOTUNING_CONFIG_OVERRIDE key {dotted!r}: "
                        f"config node {p!r} holds the non-object value "
                        f"{cur!r} ({type(cur).__name__}) — cannot set a "
                        f"nested key under it")
                nxt = dict(cur or {})
                node[p] = nxt
                node = nxt
            node[parts[-1]] = value
    # batch sizes resolved below, once the parallel dims are known
    return DeepSpeedConfig.model_validate(payload)


def _apply_moe_config(cfg, model: Any, mesh: Any = None) -> None:
    """Push the ``moe.*`` config group onto the model's MOELayer/TopKGate.

    Models build their MoE block at construction time (before
    ``initialize`` sees the config), so the engine applies the dispatch /
    capacity knobs here.  Works for any model exposing ``_moe_layer``
    (MixtralModel) or ``moe_layer`` (the reference-shaped ``MoE`` block).
    """
    layer = getattr(model, "_moe_layer", None) or getattr(
        model, "moe_layer", None)
    if layer is None:
        return
    moe = cfg.moe
    if layer.mesh is None and mesh is not None:
        layer.mesh = mesh
    if layer.gate.mesh is None and mesh is not None:
        layer.gate.mesh = mesh
    if moe.dispatch_impl != "auto":
        layer.dispatch_impl = moe.dispatch_impl
    gate = layer.gate
    gate.pad_to_ep = bool(moe.pad_capacity_to_ep)
    if moe.use_rts:
        gate.use_rts = True
    if moe.capacity_factor and moe.capacity_factor > 0:
        gate.capacity_factor = float(moe.capacity_factor)
        gate.eval_capacity_factor = float(moe.capacity_factor)


def initialize(args: Any = None,
               model: Any = None,
               optimizer: Any = None,
               model_parameters: Any = None,
               training_data: Any = None,
               lr_scheduler: Any = None,
               distributed_port: Optional[int] = None,
               mpu: Any = None,
               dist_init_required: Optional[bool] = None,
               collate_fn: Any = None,
               config: Any = None,
               config_params: Any = None,
               mesh: Any = None) -> Tuple[DeepSpeedEngine, Any, Any, Any]:
    from .. import comm

    if dist_init_required is not False:
        comm.init_distributed()

    cfg = _resolve_config(config, config_params)

    # Build/adopt the mesh from the parallel dims in config (+ mpu hints).
    if mesh is None:
        tp = int(cfg.tensor_parallel.autotp_size or 1)
        sp = int(cfg.sequence_parallel.sp_size or 1)
        pp = int(cfg.pipeline.stages or 1)
        ep = int(cfg.moe.expert_parallel_size or 1)
        if mpu is not None and hasattr(mpu, "get_sequence_parallel_world_size"):
            sp = int(mpu.get_sequence_parallel_world_size())
        dp = None
        mics = int(cfg.zero_optimization.mics_shard_size or -1)
        if mics > 0 and ep > 1:
            # MiCS repurposes the expert axis as its replica axis — it
            # cannot coexist with a real expert-parallel degree
            raise ValueError(
                f"moe.expert_parallel_size={ep} is incompatible with "
                f"mics_shard_size={mics}: MiCS uses the expert mesh axis "
                "as its replica axis; disable one of the two")
        if ep > 1:
            total_dp = jax.device_count() // (tp * pp * sp)
            if total_dp % ep:
                raise ValueError(
                    f"moe.expert_parallel_size={ep} must divide the DP "
                    f"world {total_dp} (= world/(tp·pp·sp))")
            dp = total_dp // ep
        if mics > 0:
            # MiCS: factor the DP world into (data=shard-group,
            # expert=replica-groups) so the sharder's data-axis-only
            # sharding realizes the sub-group partition.  The expert axis
            # doubles as the replica axis — MoE EP and MiCS can't share it.
            total_dp = jax.device_count() // (tp * pp * sp)
            if total_dp % mics:
                raise ValueError(
                    f"mics_shard_size={mics} must divide the DP world "
                    f"{total_dp}")
            dp, ep = mics, total_dp // mics
        layout = MeshLayout.infer(jax.device_count(), tp=tp, pp=pp, sp=sp,
                                  ep=ep, dp=dp)
        mesh = groups_mod.initialize_mesh(layout)
        world = jax.device_count()
    else:
        # an explicit mesh is authoritative for every parallel dim
        groups_mod.initialize_mesh(mesh=mesh)
        tp = int(mesh.shape.get("tensor", 1))
        sp = int(mesh.shape.get("seq", 1))
        pp = int(mesh.shape.get("pipe", 1))
        world = int(mesh.devices.size)

    # --- telemetry-driven autotuning (tuning/ — ISSUE 9) -----------------
    # consult the best-known-config store BEFORE resolve_batch_sizes:
    # resolution assigns the batch triple (pydantic marks assigned fields
    # as set), so the pinned-knob check must see the USER's fields only.
    # Promoted entries apply; pinned knobs always win; what happened is
    # stamped into every debug bundle (context.tuning) and readable via
    # tuning.autoapply for bench artifacts (tuned_config_source).
    if cfg.tuning.enabled and cfg.tuning.auto_apply:
        from ..tuning.autoapply import maybe_apply_tuned_config

        maybe_apply_tuned_config(cfg, model=model,
                                 model_parameters=model_parameters,
                                 mesh=mesh)
    else:
        # skipping the consult must also clear a PREVIOUS initialize()'s
        # hit — bundles/bench would otherwise report that engine's tuned
        # config for this untuned one
        from ..tuning.autoapply import reset_applied

        reset_applied()

    cfg.resolve_batch_sizes(world_size=world, tp=tp, pp=pp, sp=sp)
    cfg.resolve_auto_precision()

    if cfg.comms_logger.enabled:
        comm.comms_logger.configure(
            enabled=True, verbose=cfg.comms_logger.verbose,
            exec_counts=cfg.comms_logger.exec_counts)

    if cfg.telemetry.enabled:
        # configure the hub BEFORE engine construction so state-placement /
        # compile spans of the build itself are captured
        from ..telemetry import configure_from_config

        configure_from_config(cfg.telemetry)

    # flight recorder BEFORE engine construction: a crash during state
    # placement / first compile still gets a debug bundle, and the
    # fatal-signal + unhandled-exception hooks cover the whole run
    from ..telemetry.flight_recorder import recorder_from_config

    recorder = recorder_from_config(cfg.telemetry)
    if recorder is not None and cfg.telemetry.flight_recorder.install_handlers:
        recorder.install()

    # cross-host observability plane (telemetry/{aggregator,
    # collective_ledger}.py): the ledger hooks into the comms logger
    # BEFORE engine construction so state-placement / first-compile
    # collectives are in the sequence; the publisher is the process-global
    # service the elastic agent's heartbeat loop drives
    if cfg.telemetry.aggregation.enabled:
        from ..telemetry.aggregator import publisher_from_config

        publisher = publisher_from_config(cfg.telemetry)
        # subprocess deployments: THIS (worker) process owns the recorder
        # and ledger, but the elastic agent heartbeats in its own process
        # where get_publisher() is None — so the worker services the
        # store itself through the endpoint the agent exported
        rdzv_endpoint = os.environ.get("DS_RDZV_ENDPOINT")
        if publisher is not None and rdzv_endpoint:
            publisher.start_daemon(rdzv_endpoint)
        if cfg.telemetry.aggregation.ledger_enabled:
            from ..telemetry import configure_collective_ledger

            configure_collective_ledger(
                max_entries=cfg.telemetry.aggregation.ledger_max_entries,
                tail=cfg.telemetry.aggregation.ledger_tail,
                exec_feed=cfg.telemetry.aggregation.ledger_exec_feed,
                recorder=recorder)
        # cross-process telemetry plane (telemetry/rollup.py): compact
        # StepRecords buffer in a bounded ring and ship to rank 0's
        # rollup on the publisher tick (with the registry snapshot)
        from ..telemetry import configure_step_stream

        configure_step_stream(
            enabled=(cfg.telemetry.aggregation.metrics_rollup
                     and cfg.telemetry.aggregation.step_stream),
            maxlen=cfg.telemetry.aggregation.step_stream_len)
        # fleet-synchronized profiler capture plane (telemetry/profiler):
        # the publisher tick polls the store for `telemetry profile`
        # commands, the engine feeds on_step, the window's device lanes
        # publish back through the store
        pcfg = cfg.telemetry.profiler
        if pcfg.enabled:
            from ..telemetry.profiler import configure_profiler_plane

            plane = configure_profiler_plane(
                node_id=os.environ.get("DS_ELASTIC_NODE_ID",
                                       f"node-{os.getpid()}"),
                out_dir=pcfg.out_dir or None,
                ring=pcfg.ring, lead=pcfg.lead,
                duty_cycle_pct=pcfg.duty_cycle_pct,
                duty_period_steps=pcfg.duty_period_steps)
            if recorder is not None:
                plane.register_bundle_context(recorder)
    else:
        # a previous initialize() may have enabled the stream — this
        # engine's config says no aggregation, so stop buffering
        from ..telemetry import configure_step_stream

        configure_step_stream(enabled=False)

    # --- MoE plane: push the moe.* group onto the model's MOELayer -------
    _apply_moe_config(cfg, model, mesh)

    # --- resolve the model into a loss_fn --------------------------------
    from .pipe.module import PipelineModule  # noqa: avoid cycle at import time

    if isinstance(model, PipelineModule):
        from .pipe.engine import PipelineEngine

        engine = PipelineEngine(module=model, config=cfg, mesh=mesh,
                                optimizer=optimizer, lr_schedule=lr_scheduler)
    else:
        if callable(getattr(model, "loss", None)):
            loss_fn = model.loss
            if model_parameters is None and hasattr(model, "init_params"):
                model_parameters = model.init_params(jax.random.PRNGKey(cfg.seed))
        elif callable(model):
            loss_fn = model
        else:
            raise TypeError(
                "model must be a loss function, an object with .loss(), or a "
                f"PipelineModule; got {type(model)}")
        if model_parameters is None:
            raise ValueError("model_parameters (a param pytree) is required")
        engine = DeepSpeedEngine(loss_fn=loss_fn, params=model_parameters,
                                 config=cfg, optimizer=optimizer,
                                 lr_schedule=lr_scheduler
                                 if callable(lr_scheduler) else None,
                                 module=model, mesh=mesh)

    # --- monitor ----------------------------------------------------------
    from ..monitor.monitor import MonitorMaster

    monitor = MonitorMaster(cfg)
    if monitor.enabled:
        engine.monitor = monitor

    if cfg.hybrid_engine.enabled:
        from .hybrid_engine import DeepSpeedHybridEngine

        engine = DeepSpeedHybridEngine(
            engine, max_out_tokens=cfg.hybrid_engine.max_out_tokens)

    dataloader = None
    if training_data is not None:
        from .dataloader import DeepSpeedDataLoader

        dataloader = DeepSpeedDataLoader(
            training_data, batch_size=int(cfg.train_batch_size),
            mesh=mesh, collate_fn=collate_fn, shuffle=True, seed=cfg.seed)
        # seqlen curriculum: legacy top-level group or the data_efficiency
        # nested form — both feed the same scheduler
        cl = dict(cfg.curriculum_learning or {})
        if not cl.get("enabled"):
            cl = dict(cfg.data_efficiency.data_sampling.get(
                "curriculum_learning", {})) if cfg.data_efficiency.enabled \
                else {}
        if cl.get("enabled"):
            from .data_pipeline import CurriculumScheduler
            from .data_pipeline.data_sampler import CurriculumDataLoader

            sched = CurriculumScheduler(cl)
            engine.curriculum_scheduler = sched
            dataloader = CurriculumDataLoader(
                dataloader, sched, lambda: engine.global_steps)
            log_dist(f"curriculum learning: seqlen "
                     f"{sched.min}→{sched.max} over "
                     f"{getattr(sched, 'total', '?')} steps")

    # --- resilience plane (resilience/ — ISSUE 4) -------------------------
    # wired LAST so resume-from-snapshot sees the fully-assembled engine
    # (and the dataloader's cursor hook is registered before any restore)
    if getattr(engine, "resilience", None) is not None:
        if dataloader is not None:
            dl = dataloader  # bind the (possibly curriculum-wrapped) loader
            inner = getattr(dl, "loader", dl)
            # sample-progress anchor: steps*tb alone under-counts any
            # run whose global batch already changed once (an earlier
            # reshape), so progress ACCUMULATES from the last restored
            # position instead of being re-derived from the current tb
            base = {"samples": 0, "steps": 0}

            def _capture_cursor(eng=engine, inner=inner, base=base):
                # position in SAMPLES, not steps: a snapshot resumed on
                # a different world (different global batch) converts
                # back without double-consuming any window
                tb = int(eng.train_batch_size or 0)
                consumed = base["samples"] \
                    + (int(eng.global_steps) - base["steps"]) * tb
                return {"epoch": int(getattr(inner, "_epoch", 0)),
                        "consumed_samples": consumed,
                        "train_batch_size": tb}

            def _restore_cursor(p, eng=engine, inner=inner, base=base):
                inner._epoch = int(p.get("epoch", 0))
                origin_tb = int(p.get("train_batch_size", 0) or 0)
                consumed = int(p.get("consumed_samples", -1))
                if consumed < 0:
                    return
                # every step from here on consumes THIS engine's tb
                base["samples"], base["steps"] = \
                    consumed, int(eng.global_steps)
                if (origin_tb
                        and origin_tb != int(eng.train_batch_size or 0)
                        and hasattr(inner, "resume_from_samples")):
                    # mesh reshape changed the global batch: re-point
                    # the cursor at the absolute sample position
                    inner.resume_from_samples(consumed)

            engine.snapshots.register_meta(
                "data_sampler", _capture_cursor, restore=_restore_cursor)
        if cfg.resilience.buddy_tier and os.environ.get("DS_RDZV_ENDPOINT"):
            # tier 2 from the WORKER process: the sealed ring + buddy
            # slot live in the store, so a plain client suffices even
            # when the elastic agent heartbeats in a different process
            from ..elasticity.rendezvous import (ElasticRendezvous,
                                                 RendezvousClient)

            engine.snapshots.attach_rendezvous(ElasticRendezvous(
                RendezvousClient(os.environ["DS_RDZV_ENDPOINT"]),
                node_id=os.environ.get("DS_ELASTIC_NODE_ID",
                                       f"node-{os.getpid()}")))
        # elastic restart path: the agent exported DS_ELASTIC_RESTART_COUNT;
        # a restarted worker resumes from the policy-chosen newest VALID
        # snapshot (checksum-gated, tier fallback)
        engine.resilience.resume_if_restarted()

    log_dist(f"deepspeed_tpu.initialize: stage={cfg.zero_optimization.stage} "
             f"dtype={cfg.dtype().__name__} mesh={dict(mesh.shape)} "
             f"batch={cfg.train_batch_size}(micro={cfg.train_micro_batch_size_per_gpu}"
             f"×gas={cfg.gradient_accumulation_steps})")
    return engine, engine.optimizer, dataloader, engine.lr_scheduler
