"""Mixed precision: dynamic loss scaling + dtype policy.

Capability parity with the reference ``deepspeed/runtime/fp16/loss_scaler.py``
(``DynamicLossScaler``: overflow check → skip step → halve scale; grow scale
after ``loss_scale_window`` clean steps; ``optimizer.overflow`` attribute
[L ACC-DS:306-319]) and the bf16/fp16 master-weight schemes of
``bf16_optimizer.py`` / ``fp16/fused_optimizer.py`` [K].

TPU-first: bf16 needs NO loss scaler (same exponent range as fp32) and is the
default; fp16+DynamicLossScaler is kept for config compatibility.  The scaler
is a functional state threaded through the jitted train step — the overflow
check (``jnp.isfinite`` reduction) compiles into the step program instead of
being a separate host round-trip like the reference's.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray  # f32 scalar
    growth_counter: jnp.ndarray  # i32 — clean steps since last overflow
    hysteresis: jnp.ndarray  # i32 — remaining tolerated overflows before cut


class DynamicLossScaler:
    """Config + pure update rules; all state lives in ``LossScaleState``."""

    def __init__(self, initial_scale_power: int = 16, loss_scale_window: int = 1000,
                 hysteresis: int = 2, min_loss_scale: float = 1.0,
                 static_scale: float = 0.0, consecutive_hysteresis: bool = False):
        self.init_scale = static_scale if static_scale > 0 else 2.0 ** initial_scale_power
        self.window = loss_scale_window
        self.hysteresis = hysteresis
        self.min_scale = min_loss_scale
        self.static = static_scale > 0
        self.consecutive_hysteresis = consecutive_hysteresis

    @classmethod
    def from_config(cls, fp16) -> "DynamicLossScaler":
        """ONE home for FP16Config → scaler construction (fused engine
        and Infinity streaming).  Caps ``initial_scale_power`` at 15: the
        loss cotangent enters the f16 subgraph carrying the scale, and
        f16 max is 65504 — a 2^16 seed would saturate immediately."""
        return cls(
            initial_scale_power=min(fp16.initial_scale_power, 15),
            loss_scale_window=fp16.loss_scale_window,
            hysteresis=fp16.hysteresis,
            min_loss_scale=fp16.min_loss_scale,
            static_scale=fp16.loss_scale,
            consecutive_hysteresis=fp16.consecutive_hysteresis)

    def init_state(self) -> LossScaleState:
        return LossScaleState(scale=jnp.float32(self.init_scale),
                              growth_counter=jnp.int32(0),
                              hysteresis=jnp.int32(self.hysteresis))

    def update(self, state: LossScaleState, overflow: jnp.ndarray) -> LossScaleState:
        """Reference semantics (``fp16/loss_scaler.py:update_scale`` [K]):
        overflow with hysteresis left → decrement only; at hysteresis 1 →
        halve.  Hysteresis restores on every clean step only under
        ``consecutive_hysteresis``; otherwise at the growth window."""
        if self.static:
            return state
        cut = overflow & (state.hysteresis <= 1)
        hyst = jnp.where(overflow & (state.hysteresis > 1),
                         state.hysteresis - 1, state.hysteresis)
        new_scale = jnp.where(
            cut, jnp.maximum(state.scale / 2.0, self.min_scale), state.scale)
        if self.consecutive_hysteresis:
            hyst = jnp.where(overflow, hyst, jnp.int32(self.hysteresis))
        counter = jnp.where(overflow, 0, state.growth_counter + 1)
        grow = (~overflow) & (counter >= self.window)
        if not self.consecutive_hysteresis:
            hyst = jnp.where(grow, jnp.int32(self.hysteresis), hyst)
        new_scale = jnp.where(grow, new_scale * 2.0, new_scale)
        counter = jnp.where(grow, 0, counter)
        return LossScaleState(scale=new_scale, growth_counter=counter,
                              hysteresis=hyst)


def has_overflow(grads: Any) -> jnp.ndarray:
    """True if any grad entry is non-finite (the reference's
    ``check_grad_overflow``) — compiles to a fused reduction + DP psum."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.bool_(False)
    flags = [~jnp.all(jnp.isfinite(leaf)) for leaf in leaves]
    return jnp.any(jnp.stack(flags))


def cast_tree(tree: Any, dtype) -> Any:
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree)


def global_grad_norm(grads: Any) -> jnp.ndarray:
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in jax.tree.leaves(grads)]
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def clip_grads_by_global_norm(grads: Any, max_norm: float,
                              precomputed_norm: jnp.ndarray = None
                              ) -> Tuple[Any, jnp.ndarray]:
    norm = precomputed_norm if precomputed_norm is not None else global_grad_norm(grads)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: g * factor.astype(g.dtype), grads), norm
