import sys

from .bench import main

sys.exit(main())
