"""MoE expert-parallel bench — the ep plane's headline numbers.

Three gated figures (telemetry/perf PERF_METRICS, ISSUE 19):

* ``moe_ep_tokens_per_sec`` — the Mixtral proxy trained end-to-end
  through ``deepspeed_tpu.initialize`` with the expert mesh axis > 1:
  expert-stacked params sharded via ``param_specs()``, ZeRO over the
  flattened ``("expert","data")`` axes, sparse index-form dispatch.
* ``moe_dispatch_speedup`` — the index-form dispatch/combine
  (``ops/pallas/moe_dispatch``) vs the dense GShard ``[T,E,C]`` einsum
  on the same routing and shapes.  The dense form is O(T·E·C) FLOPs and
  memory; sub-1.0 means the crossover auto-dispatch regressed.
* ``moe_drop_rate`` — capacity-dropped token fraction at the bench's
  fixed capacity factor, read from the ``moe/drop_rate`` gauge the
  engine publishes from gate meta (PR-18 plumbing, proven here).

``--dry-run`` shrinks the proxy to a seconds-scale CPU run — the
run_suite smoke and the ep acceptance test drive it; the fields are the
same ones ``bench.py``'s ``moe_ep`` variant lands in the gated BENCH
line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, Optional

import numpy as np


def _pick_ep(devices: int, num_experts: int) -> int:
    """Largest expert-axis degree the device count and expert count both
    divide into (capped at 4 — data parallelism needs room too)."""
    for ep in (4, 2):
        if devices % ep == 0 and devices > ep and num_experts % ep == 0:
            return ep
    return 1


def _train_tokens_per_sec(model_cfg: Any, ep: int, steps: int,
                          warmup: int, micro: int,
                          dispatch_impl: str) -> Dict[str, Any]:
    """One config-driven training run: build the engine with
    ``moe.expert_parallel_size = ep``, train, measure steady-state
    tokens/sec and pull the gate gauges + expert shard fraction."""
    import jax

    import deepspeed_tpu as dst
    from ..models.mixtral import MixtralModel
    from ..telemetry import get_telemetry
    from ..utils import groups

    groups.reset_mesh()
    ds_cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "moe": {"expert_parallel_size": ep, "dispatch_impl": dispatch_impl},
        "steps_per_print": 0,
        # hub on (in-memory only) + gate telemetry every step so
        # drop/overflow land in the moe/* gauges this bench (and the
        # rollup) reads
        "telemetry": {"enabled": True, "jsonl": False,
                      "numerics": {"every": 1}},
    }
    model = MixtralModel(model_cfg)
    engine, *_ = dst.initialize(model=model, config=ds_cfg)
    seq = model_cfg.max_seq_len
    batch = engine.train_batch_size
    rng = np.random.default_rng(11)

    def one_batch():
        ids = rng.integers(1, model_cfg.vocab_size, size=(batch, seq),
                           dtype=np.int32)
        return {"input_ids": ids}

    losses = []
    for _ in range(warmup):
        losses.append(float(engine.train_step(one_batch())["loss"]))
    t0 = time.perf_counter()
    for _ in range(steps):
        losses.append(float(engine.train_step(one_batch())["loss"]))
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / max(dt, 1e-9)

    # expert shard fraction: per-device bytes of an expert-stacked param
    # over its global bytes — the "params really sharded ~1/ep" proof
    wg = engine.state.params["layers"]["moe"]["w_gate"]
    try:
        shard = wg.sharding.shard_shape(wg.shape)
        frac = float(np.prod(shard) / np.prod(wg.shape))
    except Exception:
        frac = 1.0

    drop = None
    snap = get_telemetry().registry.snapshot()
    g = snap.get("gauges", {}).get("moe/drop_rate")
    if g is not None:
        drop = float(g["value"])
    return {"tokens_per_sec": tps, "losses": losses, "drop_rate": drop,
            "expert_bytes_frac": frac,
            "mesh": {k: int(v) for k, v in engine.mesh.shape.items()}}


def _dispatch_speedup(hidden: int, experts: int, intermediate: int,
                      tokens: int, reps: int = 5) -> float:
    """Dense [T,E,C] einsum dispatch vs index-form sparse dispatch on
    the same MoE block and routing — jitted, fenced, single program
    each.  Returns t_dense / t_sparse."""
    import jax
    import jax.numpy as jnp

    from .layer import swiglu_expert_fn
    from .sharded_moe import MOELayer, TopKGate

    rng = np.random.default_rng(3)
    wg = jnp.asarray(rng.standard_normal((hidden, experts)),
                     dtype=jnp.float32) * 0.02
    ew = {
        "w_gate": jnp.asarray(rng.standard_normal(
            (experts, hidden, intermediate)), jnp.float32) * 0.02,
        "w_up": jnp.asarray(rng.standard_normal(
            (experts, hidden, intermediate)), jnp.float32) * 0.02,
        "w_down": jnp.asarray(rng.standard_normal(
            (experts, intermediate, hidden)), jnp.float32) * 0.02,
    }
    x = jnp.asarray(rng.standard_normal((1, tokens, hidden)), jnp.float32)

    def timed(impl: str) -> float:
        gate = TopKGate(num_experts=experts, k=2, capacity_factor=2.0,
                        eval_capacity_factor=2.0, min_capacity=4)
        layer = MOELayer(gate, swiglu_expert_fn, dispatch_impl=impl)
        f = jax.jit(lambda w, e, t: layer(w, e, t, train=False)[0])
        f(wg, ew, x).block_until_ready()  # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(wg, ew, x)
        out.block_until_ready()
        return (time.perf_counter() - t0) / reps

    t_dense = timed("dense")
    t_sparse = timed("sparse")
    return t_dense / max(t_sparse, 1e-12)


def run_moe_ep_bench(dry_run: bool = False, ep: Optional[int] = None,
                     steps: int = 4, warmup: int = 2,
                     dispatch_impl: str = "sparse") -> Dict[str, Any]:
    """The moe_ep bench: ep>1 vs ep=1 training runs + the dispatch
    micro-bench.  Returns the JSON-able result dict whose
    ``moe_ep_tokens_per_sec`` / ``moe_dispatch_speedup`` /
    ``moe_drop_rate`` keys are the gated PERF_METRICS."""
    import jax

    from ..models.mixtral import MixtralConfig
    from ..telemetry import get_telemetry
    from ..utils import groups

    hub_was_enabled = get_telemetry().enabled

    if dry_run:
        mcfg = MixtralConfig.tiny(num_layers=2, max_seq_len=128)
        disp_shapes = dict(hidden=128, experts=4, intermediate=176,
                           tokens=2048)
        micro = 1
    else:
        # Mixtral aspect ratios scaled to a single-chip training proxy
        mcfg = MixtralConfig(vocab_size=32000, hidden_size=1024,
                             intermediate_size=3584, num_layers=4,
                             num_heads=16, num_kv_heads=8, max_seq_len=1024,
                             num_experts=8, top_k=2)
        disp_shapes = dict(hidden=1024, experts=8, intermediate=3584,
                           tokens=8192)
        micro = 1

    devices = jax.device_count()
    ep = int(ep) if ep else _pick_ep(devices, mcfg.num_experts)
    out: Dict[str, Any] = {"ep": ep, "devices": devices,
                           "dry_run": bool(dry_run),
                           "dispatch_impl": dispatch_impl}

    ep_run = _train_tokens_per_sec(mcfg, ep, steps, warmup, micro,
                                   dispatch_impl)
    out["moe_ep_tokens_per_sec"] = round(ep_run["tokens_per_sec"], 1)
    out["moe_expert_bytes_frac"] = round(ep_run["expert_bytes_frac"], 4)
    out["moe_ep_mesh"] = ep_run["mesh"]
    out["moe_ep_final_loss"] = round(ep_run["losses"][-1], 4)
    if ep > 1:
        ref = _train_tokens_per_sec(mcfg, 1, steps, warmup, micro,
                                    dispatch_impl)
        out["moe_ep1_tokens_per_sec"] = round(ref["tokens_per_sec"], 1)
        out["moe_ep_speedup_vs_ep1"] = round(
            ep_run["tokens_per_sec"] / max(ref["tokens_per_sec"], 1e-9), 3)
        out["moe_ep1_final_loss"] = round(ref["losses"][-1], 4)
    groups.reset_mesh()
    if not hub_was_enabled:
        get_telemetry().configure(enabled=False)

    drop = ep_run["drop_rate"]
    if drop is None:
        # telemetry hub disabled: derive the same figure from a direct
        # gate evaluation on bench-shaped random routing
        import jax.numpy as jnp

        from .sharded_moe import top_k_gating

        logits = jnp.asarray(
            np.random.default_rng(5).standard_normal(
                (disp_shapes["tokens"], disp_shapes["experts"])),
            jnp.float32)
        _, _, _, meta = top_k_gating(logits, k=2, capacity=max(
            2 * disp_shapes["tokens"] // disp_shapes["experts"], 4))
        drop = float(meta["drop_rate"])
    out["moe_drop_rate"] = round(float(drop), 4)

    out["moe_dispatch_speedup"] = round(_dispatch_speedup(
        **disp_shapes, reps=3 if dry_run else 5), 3)
    return out


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.moe",
        description="MoE expert-parallel bench (ISSUE 19)")
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bench", help="run the moe_ep bench; emits one "
                                     "JSON line with the gated metrics")
    b.add_argument("--dry-run", action="store_true",
                   help="tiny proxy, seconds-scale (CI smoke)")
    b.add_argument("--ep", type=int, default=0,
                   help="expert-parallel degree (0 = auto from devices)")
    b.add_argument("--steps", type=int, default=4)
    b.add_argument("--dispatch-impl", default="sparse",
                   choices=["auto", "dense", "sparse", "pallas"])
    args = p.parse_args(argv)
    if args.cmd == "bench":
        result = run_moe_ep_bench(dry_run=args.dry_run,
                                  ep=args.ep or None, steps=args.steps,
                                  dispatch_impl=args.dispatch_impl)
        print(json.dumps(result))
        return 0
    return 2


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
