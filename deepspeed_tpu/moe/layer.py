"""MoE wrapper with the reference ctor surface (``deepspeed/moe/layer.py:MoE``
[K]: hidden_size, expert, num_experts, ep_size, k, capacity_factor,
eval_capacity_factor, min_capacity, noisy_gate_policy, drop_tokens,
enable_expert_tensor_parallelism).

TPU adaptation: ``expert`` is a functional ``(params, [E,C,H]) → [E,C,H]``
callable (or None for the built-in SwiGLU expert); params live in the
caller's pytree with expert-stacked leading dim E, sharded over the
``expert`` mesh axis by ``param_specs``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..parallel.mesh import AXIS_EXPERT, AXIS_TENSOR
from ..utils import groups as groups_mod
from .sharded_moe import MOELayer, TopKGate

P = PartitionSpec


def swiglu_expert_fn(params: Any, x: jnp.ndarray,
                     constrain_act: Optional[Callable] = None) -> jnp.ndarray:
    """Default expert: SwiGLU FFN with expert-stacked params
    ``{w_gate [E,H,I], w_up [E,H,I], w_down [E,I,H]}``.  ``constrain_act``
    optionally pins the inner activation's sharding (expert-TP)."""
    dt = x.dtype
    gate = jnp.einsum("ech,ehi->eci", x, params["w_gate"].astype(dt))
    up = jnp.einsum("ech,ehi->eci", x, params["w_up"].astype(dt))
    act = jax.nn.silu(gate) * up
    if constrain_act is not None:
        act = constrain_act(act)
    return jnp.einsum("eci,eih->ech", act, params["w_down"].astype(dt))


class MoE:
    """Reference-shaped MoE block."""

    def __init__(self, hidden_size: int,
                 expert: Optional[Callable[[Any, jnp.ndarray], jnp.ndarray]] = None,
                 num_experts: int = 1, ep_size: int = 1, k: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, use_residual: bool = False,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True,
                 use_tutel: bool = False,
                 enable_expert_tensor_parallelism: bool = False,
                 mesh: Any = None, dispatch_impl: str = "auto"):
        if num_experts % max(ep_size, 1):
            raise ValueError(
                f"num_experts({num_experts}) % ep_size({ep_size}) != 0")
        if use_tutel:
            raise ValueError(
                "use_tutel is not supported on the TPU port: Tutel's fused "
                "dispatch kernels are CUDA-only — the equivalent fast path "
                "here is the Pallas sparse dispatch (dispatch_impl='pallas' "
                "or 'auto'); pass use_tutel=False")
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.use_residual = use_residual
        self.enable_expert_tensor_parallelism = enable_expert_tensor_parallelism
        self.gate = TopKGate(num_experts=num_experts, k=k,
                             capacity_factor=capacity_factor,
                             eval_capacity_factor=eval_capacity_factor,
                             min_capacity=min_capacity,
                             noisy_gate_policy=noisy_gate_policy,
                             drop_tokens=drop_tokens,
                             use_rts=use_rts)
        try:
            mesh = mesh if mesh is not None else groups_mod.get_mesh()
        except Exception:
            mesh = None
        self.moe_layer = MOELayer(self.gate, expert or swiglu_expert_fn,
                                  mesh=mesh, dispatch_impl=dispatch_impl)

    # ------------------------------------------------------------------

    def init_params(self, rng: jax.Array, intermediate_size: int) -> Any:
        """Params for the built-in SwiGLU expert + router (+ the residual
        dense MLP and 2-way mixing coefficient when ``use_residual``)."""
        E, H, I = self.num_experts, self.hidden_size, intermediate_size
        k1, k2, k3, k4, k5, k6, k7, k8 = jax.random.split(rng, 8)
        import numpy as np

        def normal(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    / np.sqrt(fan_in))

        params = {
            "wg": normal(k1, (H, E), H),
            "experts": {
                "w_gate": normal(k2, (E, H, I), H),
                "w_up": normal(k3, (E, H, I), H),
                "w_down": normal(k4, (E, I, H), I),
            },
        }
        if self.use_residual:
            params["residual_mlp"] = {
                "w_gate": normal(k5, (1, H, I), H),
                "w_up": normal(k6, (1, H, I), H),
                "w_down": normal(k7, (1, I, H), I),
            }
            params["coefficient"] = normal(k8, (H, 2), H)
        return params

    def param_specs(self) -> Any:
        """Expert-stacked dims shard over the ``expert`` axis (+ optional TP
        on the FFN inner dim — reference enable_expert_tensor_parallelism)."""
        t = AXIS_TENSOR if self.enable_expert_tensor_parallelism else None
        specs = {
            "wg": P(None, None),
            "experts": {
                "w_gate": P(AXIS_EXPERT, None, t),
                "w_up": P(AXIS_EXPERT, None, t),
                "w_down": P(AXIS_EXPERT, t, None),
            },
        }
        if self.use_residual:
            specs["residual_mlp"] = {
                "w_gate": P(None, None, t),
                "w_up": P(None, None, t),
                "w_down": P(None, t, None),
            }
            specs["coefficient"] = P(None, None)
        return specs

    def __call__(self, params: Any, x: jnp.ndarray, train: bool = True,
                 noise_rng: Optional[jax.Array] = None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, Any]:
        """x: [B, S, H] → (y, l_aux, meta).

        ``meta`` is the FULL gate metadata (``l_aux``, ``exp_counts``,
        ``drop_rate``, ``load``, ``entropy``, ``overflow_frac``) so callers
        can feed the telemetry plane without re-deriving.  Back-compat: the
        tuple slot historically carried bare ``exp_counts`` —
        :class:`~.sharded_moe.GateMeta.__array__` keeps
        ``np.asarray(meta)`` meaning exactly that.
        """
        y, l_aux, meta = self.moe_layer(params["wg"], params["experts"], x,
                                        train=train, noise_rng=noise_rng)
        if self.use_residual:
            # reference Residual-MoE (moe/layer.py [K]): a dense MLP runs in
            # parallel and a learned 2-way softmax coefficient mixes the two
            dense = swiglu_expert_fn(params["residual_mlp"],
                                     x.reshape(1, -1, x.shape[-1]))
            dense = dense.reshape(x.shape)
            coef = jax.nn.softmax(
                jnp.einsum("...h,hc->...c", x,
                           params["coefficient"].astype(x.dtype)), axis=-1)
            y = y * coef[..., 0:1] + dense * coef[..., 1:2]
        return y, l_aux, meta
