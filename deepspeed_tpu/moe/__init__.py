"""Mixture-of-Experts with expert parallelism.

Reference: ``deepspeed/moe/`` [K] — ``layer.py:MoE``, ``sharded_moe.py``
(TopKGate, MOELayer, all-to-all token dispatch), ``experts.py``.
"""

from .layer import MoE
from .sharded_moe import (GateIndices, GateMeta, MOELayer, TopKGate,
                          top_k_gating, top_k_gating_indices)

__all__ = ["MoE", "MOELayer", "TopKGate", "top_k_gating",
           "top_k_gating_indices", "GateIndices", "GateMeta"]
