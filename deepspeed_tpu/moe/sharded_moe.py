"""Sharded MoE: gating + expert-parallel dispatch, TPU-first.

Reference: ``deepspeed/moe/sharded_moe.py`` [K] — ``TopKGate`` (top-1/top-2,
capacity factor, load-balancing aux loss à la GShard/Switch), ``MOELayer``
(all-to-all token dispatch to expert-parallel ranks), token dropping +
random-token-selection.  Papers: GShard arXiv 2006.16668, Switch arXiv
2101.03961, DeepSpeed-MoE arXiv 2201.05596 [P].

TPU-first, two dispatch formulations sharing ONE gating core:

* dense — the GShard one-hot dispatch/combine tensors contracted with
  einsum, static capacity shapes.  The reference's explicit ``_AllToAll``
  autograd op disappears: GSPMD inserts the all-to-all from the sharding
  transition tokens→experts inside the one jitted train step.
* sparse — the same routing decision lowered to index form
  (:func:`top_k_gating_indices`) and executed as gathers via
  ``ops.pallas.moe_dispatch`` (jnp reference under GSPMD meshes, Pallas
  kernels on unsharded TPU).  ``MOELayer(dispatch_impl=...)`` picks the
  rung; ``auto`` keeps small T·E·C on the fused dense path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.mesh import AXIS_DATA, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR, DP_AXES
from ..utils.logging import logger

P = PartitionSpec


def _one_hot(idx: jnp.ndarray, num: int) -> jnp.ndarray:
    return jax.nn.one_hot(idx, num, dtype=jnp.float32)


class GateMeta(dict):
    """Gate metadata dict with a back-compat shim: historical callers got
    bare ``exp_counts`` in ``MoE.__call__``'s third tuple slot, so
    ``np.asarray(meta)`` still yields the per-expert assignment counts."""

    def __array__(self, dtype=None):
        a = np.asarray(self["exp_counts"])
        return a.astype(dtype) if dtype is not None else a


jax.tree_util.register_pytree_node(
    GateMeta,
    lambda d: (tuple(d[k] for k in sorted(d)), tuple(sorted(d))),
    lambda keys, vals: GateMeta(zip(keys, vals)))


@dataclasses.dataclass
class GateIndices:
    """Routing decision in index form (the sparse dispatch contract).

    Per choice k and token t: which expert (``expert_idx``), which slot
    within it (``slot``), whether the assignment survived capacity
    (``keep``), and the renormalized combine weight (``gate``, zero for
    dropped assignments).  ``capacity``/``num_experts`` are static.
    """

    expert_idx: jnp.ndarray  # [K, T] int32
    slot: jnp.ndarray        # [K, T] int32
    keep: jnp.ndarray        # [K, T] bool
    gate: jnp.ndarray        # [K, T] f32
    capacity: int
    num_experts: int


jax.tree_util.register_pytree_node(
    GateIndices,
    lambda g: ((g.expert_idx, g.slot, g.keep, g.gate),
               (g.capacity, g.num_experts)),
    lambda aux, leaves: GateIndices(*leaves, *aux))


def _gating_core(logits: jnp.ndarray, k: int, capacity: int,
                 noise_rng: Optional[jax.Array],
                 noisy_gate_policy: Optional[str],
                 drop_tokens: bool,
                 rts_rng: Optional[jax.Array]) -> Dict[str, Any]:
    """The one top-k routing computation both output forms are built from.

    Returns the raw pieces: softmax ``gates``, per-choice one-hot ``masks``
    (post capacity filter when ``drop_tokens``), ``positions`` (slot within
    the chosen expert), ``within`` (slot < capacity), expert ``idxs``,
    renormalized per-choice ``gate_k`` weights, ``l_aux`` and the
    pre-``drop_rate`` metadata.
    """
    if k not in (1, 2):
        raise ValueError(f"k must be 1 or 2, got {k}")
    T, E = logits.shape
    C = capacity

    route_logits = logits
    if noisy_gate_policy == "RSample" and noise_rng is not None:
        route_logits = logits + jax.random.normal(noise_rng, logits.shape,
                                                  logits.dtype)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]

    idx1 = jnp.argmax(route_logits, axis=-1)  # [T]
    mask1 = _one_hot(idx1, E)

    # load-balancing aux loss (Switch eq.4 / reference l_aux): E·Σ me·ce
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    masks = [mask1]
    idxs = [idx1]
    if k == 2:
        logits2 = jnp.where(mask1.astype(bool), -jnp.inf, route_logits)
        idx2 = jnp.argmax(logits2, axis=-1)
        masks.append(_one_hot(idx2, E))
        idxs.append(idx2)

    # capacity priority order over tokens: arrival order by default;
    # random-token-selection (reference use_rts) shuffles it so overflow
    # drops a uniform sample instead of always the tail — deterministic
    # under the passed rng
    perm = inv = None
    if rts_rng is not None:
        perm = jax.random.permutation(rts_rng, T)
        inv = jnp.zeros_like(perm).at[perm].set(
            jnp.arange(T, dtype=perm.dtype))

    # positions within each expert: running count over tokens (in priority
    # order), per choice (second choices queue behind ALL first choices —
    # reference behavior)
    positions = []
    offset = jnp.zeros((E,), jnp.float32)
    for m in masks:
        mp = m[perm] if perm is not None else m
        loc = jnp.cumsum(mp, axis=0) - mp + offset[None, :]
        offset = offset + jnp.sum(mp, axis=0)
        pos = jnp.sum(loc * mp, axis=-1)  # [T] slot in priority order
        positions.append(pos[inv] if inv is not None else pos)

    exp_counts = jnp.sum(masks[0], axis=0)  # pre-drop assignment counts

    # routing telemetry from the PRE-capacity state: per-expert load share,
    # gating entropy over the mean softmax (collapse detector — ln(E) is
    # uniform, → 0 as the router funnels everything to one expert), and the
    # fraction of assignments that overflowed their expert's capacity
    load = exp_counts / jnp.maximum(jnp.float32(T), 1.0)
    entropy = -jnp.sum(me * jnp.log(jnp.maximum(me, 1e-9)))
    assigned = sum(jnp.sum(m) for m in masks)
    overflowed = sum(jnp.sum(m * (pos >= C).astype(m.dtype)[:, None])
                     for m, pos in zip(masks, positions))
    overflow_frac = overflowed / jnp.maximum(assigned, 1.0)

    within = [(pos < C) for pos in positions]

    # capacity-filter masks BEFORE renormalizing (reference top2gating order:
    # a token whose 2nd choice is dropped keeps FULL weight on its 1st)
    if drop_tokens:
        masks = [m * w.astype(m.dtype)[:, None]
                 for m, w in zip(masks, within)]

    denom = sum(jnp.sum(gates * m, axis=-1) for m in masks)
    denom = jnp.maximum(denom, 1e-9)
    gate_k = [jnp.sum(gates * m, axis=-1) / denom for m in masks]

    meta = GateMeta({"l_aux": l_aux, "exp_counts": exp_counts,
                     "load": load, "entropy": entropy,
                     "overflow_frac": overflow_frac})
    return dict(gates=gates, masks=masks, positions=positions,
                within=within, idxs=idxs, gate_k=gate_k, l_aux=l_aux,
                meta=meta, T=T, E=E, C=C, k=k)


def top_k_gating(logits: jnp.ndarray, k: int, capacity: int,
                 noise_rng: Optional[jax.Array] = None,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 rts_rng: Optional[jax.Array] = None,
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
    """GShard-style top-k gating over ``[T, E]`` router logits.

    Returns ``(combine_weights [T,E,C], dispatch_mask [T,E,C] bool,
    l_aux, metadata)``.  k ∈ {1, 2} (reference supports exactly these).
    ``rts_rng`` switches capacity overflow to random-token-selection.
    """
    core = _gating_core(logits, k, capacity, noise_rng, noisy_gate_policy,
                        drop_tokens, rts_rng)
    T, E, C = core["T"], core["E"], core["C"]

    combine = jnp.zeros((T, E, C), jnp.float32)
    dispatch = jnp.zeros((T, E, C), bool)
    for m, pos, g in zip(core["masks"], core["positions"], core["gate_k"]):
        # out-of-range pos rows one-hot to all-zero, but m is already zero
        # there after the capacity filter
        pos_oh = _one_hot(pos.astype(jnp.int32), C + 1)[:, :C]
        contrib = m[:, :, None] * pos_oh[:, None, :]
        combine = combine + g[:, None, None] * contrib
        dispatch = dispatch | (contrib > 0)

    meta = core["meta"]
    meta["drop_rate"] = 1.0 - jnp.sum(combine > 0) / jnp.maximum(k * T, 1)
    return combine, dispatch, core["l_aux"], meta


def top_k_gating_indices(logits: jnp.ndarray, k: int, capacity: int,
                         noise_rng: Optional[jax.Array] = None,
                         noisy_gate_policy: Optional[str] = None,
                         drop_tokens: bool = True,
                         rts_rng: Optional[jax.Array] = None,
                         ) -> Tuple[GateIndices, jnp.ndarray, Dict[str, jnp.ndarray]]:
    """:func:`top_k_gating` lowered to index form — same routing decision
    (one shared core), returned as ``(GateIndices, l_aux, meta)`` for the
    sparse dispatch path in ``ops.pallas.moe_dispatch``."""
    core = _gating_core(logits, k, capacity, noise_rng, noisy_gate_policy,
                        drop_tokens, rts_rng)
    T, E, C, kk = core["T"], core["E"], core["C"], core["k"]

    expert_idx = jnp.stack([i.astype(jnp.int32) for i in core["idxs"]])
    slot = jnp.stack([p.astype(jnp.int32) for p in core["positions"]])
    # an assignment lands iff its (possibly filtered) mask row is live AND
    # its slot is within capacity — exactly the dense contrib support
    keep = jnp.stack([(jnp.sum(m, axis=-1) > 0) & w
                      for m, w in zip(core["masks"], core["within"])])
    gate = jnp.stack(core["gate_k"])

    meta = core["meta"]
    kept = sum(jnp.sum((g > 0) & kp)
               for g, kp in zip(core["gate_k"], keep))
    meta["drop_rate"] = 1.0 - kept / jnp.maximum(kk * T, 1)
    gi = GateIndices(expert_idx=expert_idx, slot=slot, keep=keep,
                     gate=gate, capacity=C, num_experts=E)
    return gi, core["l_aux"], meta


@dataclasses.dataclass
class TopKGate:
    """Router config + params-free apply (reference ``TopKGate`` ctor keys).

    The router projection weight lives in the caller's param pytree
    (``wg: [H, E]``) — functional style, no hidden state.  When a mesh is
    known, :meth:`capacity` auto-pads to the next multiple of the expert
    axis size so downstream expert-axis sharding never silently drops
    (``pad_to_ep=False`` restores the raw reference formula).
    """

    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = False
    pad_to_ep: bool = True
    mesh: Optional[Any] = None

    def _ep_size(self) -> int:
        if self.mesh is None:
            return 1
        try:
            return int(dict(self.mesh.shape).get(AXIS_EXPERT, 1))
        except Exception:
            return 1

    def capacity(self, num_tokens: int, train: bool = True) -> int:
        f = self.capacity_factor if train else self.eval_capacity_factor
        cap = int(np.ceil(self.k * num_tokens * f / self.num_experts))
        cap = max(cap, self.min_capacity)
        ep = self._ep_size()
        if self.pad_to_ep and ep > 1:
            cap = int(-(-cap // ep) * ep)  # ceil to next multiple of ep
        return cap

    def _rts_rng(self, noise_rng: Optional[jax.Array],
                 train: bool) -> Optional[jax.Array]:
        if not (self.use_rts and train) or noise_rng is None:
            return None
        # decorrelate from the RSample noise draw
        return jax.random.fold_in(noise_rng, 0x5eed)

    def __call__(self, wg: jnp.ndarray, x: jnp.ndarray, train: bool = True,
                 noise_rng: Optional[jax.Array] = None):
        """x: [T, H] tokens → gating tensors (see :func:`top_k_gating`)."""
        logits = x.astype(jnp.float32) @ wg.astype(jnp.float32)
        return top_k_gating(logits, self.k, self.capacity(x.shape[0], train),
                            noise_rng=noise_rng,
                            noisy_gate_policy=self.noisy_gate_policy
                            if train else None,
                            drop_tokens=self.drop_tokens,
                            rts_rng=self._rts_rng(noise_rng, train))

    def route(self, wg: jnp.ndarray, x: jnp.ndarray, train: bool = True,
              noise_rng: Optional[jax.Array] = None
              ) -> Tuple[GateIndices, jnp.ndarray, Dict[str, jnp.ndarray]]:
        """Index-form twin of :meth:`__call__` (sparse dispatch path)."""
        logits = x.astype(jnp.float32) @ wg.astype(jnp.float32)
        return top_k_gating_indices(
            logits, self.k, self.capacity(x.shape[0], train),
            noise_rng=noise_rng,
            noisy_gate_policy=self.noisy_gate_policy if train else None,
            drop_tokens=self.drop_tokens,
            rts_rng=self._rts_rng(noise_rng, train))


class MOELayer:
    """Expert-parallel MoE layer (reference ``MOELayer`` [K]).

    ``expert_fn(expert_params, x)`` maps ``[E, C, H] → [E, C, H]`` with
    expert-stacked params (leading dim E).  Experts shard over the ``expert``
    mesh axis; the tokens→experts transition (einsum on the dense rung,
    gather on the sparse rungs) IS the all-to-all under GSPMD.

    ``dispatch_impl``: ``auto`` | ``dense`` | ``sparse`` | ``pallas`` —
    see :func:`~..ops.pallas.moe_dispatch.choose_dispatch_impl`.
    """

    def __init__(self, gate: TopKGate,
                 expert_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                 mesh: Optional[Mesh] = None,
                 dispatch_impl: str = "auto"):
        self.gate = gate
        self.expert_fn = expert_fn
        self.mesh = mesh
        if gate.mesh is None:
            gate.mesh = mesh  # capacity auto-pad sees the expert axis
        self.dispatch_impl = dispatch_impl
        self._warned_dropped = False

    # ------------------------------------------------------------------

    def _constrain(self, x, *spec):
        """Sharding constraint, skipped per-entry when a dim isn't divisible
        by its axes (standalone small-batch use outside the engine)."""
        if self.mesh is None:
            return x
        shape = dict(self.mesh.shape)

        def size_of(entry):
            axes = entry if isinstance(entry, tuple) else (entry,)
            return int(np.prod([shape[a] for a in axes]))

        entries = [None if e is not None and x.shape[i] % size_of(e) else e
                   for i, e in enumerate(spec)]
        dropped = [(i, e) for i, e in enumerate(spec)
                   if e is not None and entries[i] is None]
        if dropped:
            # a capacity/hidden size that doesn't divide the expert axis
            # silently replicates expert compute — count every occurrence
            # (trace-time events) and log the first
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                "moe/ep_constraint_dropped", float(len(dropped)),
                help="sharding constraints dropped on MoE tensors "
                     "(dim not divisible by mesh axis; EP disabled there)")
            if not self._warned_dropped:
                self._warned_dropped = True
                logger.warning(
                    "MOELayer: dropping sharding constraint(s) %s on shape %s "
                    "(dim not divisible by mesh axis) — expert parallelism is "
                    "DISABLED for this tensor; pad capacity/hidden to a "
                    "multiple of the axis size to restore EP",
                    dropped, tuple(x.shape))
        from ..parallel.mesh import strip_manual_axes

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, strip_manual_axes(*entries)))

    # ------------------------------------------------------------------

    def _sharded(self) -> bool:
        if self.mesh is None:
            return False
        return int(np.prod(list(dict(self.mesh.shape).values()))) > 1

    def _resolve_impl(self, T: int, E: int, C: int) -> str:
        from ..ops.pallas.moe_dispatch import choose_dispatch_impl

        return choose_dispatch_impl(self.dispatch_impl, T, E, C,
                                    sharded=self._sharded())

    def _register_scratch(self, impl: str, T: int, E: int, C: int, H: int,
                          dtype) -> None:
        from ..ops.pallas.moe_dispatch import dispatch_scratch_bytes
        from ..telemetry.memory.ledger import get_memory_ledger

        ledger = get_memory_ledger()
        if not ledger.enabled:
            return
        item = jnp.dtype(dtype).itemsize
        if impl == "dense":
            # one-hot combine (f32) + dispatch (bool) masks + both buffers
            nbytes = T * E * C * 5 + 2 * E * C * H * item
        else:
            nbytes = dispatch_scratch_bytes(E, C, H, dtype, k=self.gate.k)
        ledger.register("collective_scratch", "moe/dispatch", int(nbytes),
                        tag=impl, transient=True)

    # ------------------------------------------------------------------

    def __call__(self, wg: jnp.ndarray, expert_params: Any, x: jnp.ndarray,
                 train: bool = True, noise_rng: Optional[jax.Array] = None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
        """x: [B, S, H] → (y [B, S, H], l_aux, metadata)."""
        from ..ops.pallas import moe_dispatch as md

        B, S, H = x.shape
        tokens = x.reshape(B * S, H)
        T, E = B * S, self.gate.num_experts
        C = self.gate.capacity(T, train)
        impl = self._resolve_impl(T, E, C)
        dtype = x.dtype
        self._register_scratch(impl, T, E, C, H, dtype)

        if impl == "dense":
            combine, dispatch, l_aux, meta = self.gate(wg, tokens, train,
                                                       noise_rng)
            # tokens → expert buffers: [E, C, H]; the einsum over T is the
            # all-to-all boundary (tokens sharded over DP, buffers over
            # expert)
            expert_in = jnp.einsum("tec,th->ech",
                                   dispatch.astype(dtype), tokens)
            expert_in = self._constrain(expert_in, AXIS_EXPERT, None, None)
            expert_out = self.expert_fn(expert_params, expert_in)
            expert_out = self._constrain(expert_out, AXIS_EXPERT, None, None)
            y = jnp.einsum("tec,ech->th", combine.astype(dtype), expert_out)
        else:
            gi, l_aux, meta = self.gate.route(wg, tokens, train, noise_rng)
            src_idx, flat_idx = md.routing_to_indices(
                gi.expert_idx, gi.slot, gi.keep, E, C)
            if impl == "pallas":
                expert_in = md.pallas_dispatch(tokens, src_idx)
            else:
                expert_in = md.dispatch_reference(tokens, src_idx)
            expert_in = self._constrain(expert_in, AXIS_EXPERT, None, None)
            expert_out = self.expert_fn(expert_params, expert_in)
            expert_out = self._constrain(expert_out, AXIS_EXPERT, None, None)
            gates_tk = gi.gate.T  # [T, K]
            if impl == "pallas":
                y = md.pallas_combine(expert_out, flat_idx, gates_tk)
            else:
                y = md.combine_reference(expert_out, flat_idx, gates_tk)
            y = y.astype(dtype)

        # static, host-side record of the resolved rung (meta stays a pure
        # array pytree so it can cross the jit boundary)
        self.last_impl = impl
        meta = GateMeta(meta)
        y = self._constrain(y.reshape(B, S, H), DP_AXES, AXIS_SEQ, None)
        return y, l_aux, meta
