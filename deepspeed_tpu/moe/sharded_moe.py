"""Sharded MoE: gating + expert-parallel dispatch, TPU-first.

Reference: ``deepspeed/moe/sharded_moe.py`` [K] — ``TopKGate`` (top-1/top-2,
capacity factor, load-balancing aux loss à la GShard/Switch), ``MOELayer``
(all-to-all token dispatch to expert-parallel ranks), token dropping +
random-token-selection.  Papers: GShard arXiv 2006.16668, Switch arXiv
2101.03961, DeepSpeed-MoE arXiv 2201.05596 [P].

TPU-first: the dispatch is the GShard DENSE formulation — one-hot
dispatch/combine tensors contracted with einsum, static capacity shapes (no
dynamic gather), experts sharded over the ``expert`` mesh axis.  The
reference's explicit ``_AllToAll`` autograd op disappears: GSPMD inserts the
all-to-all from the sharding transition tokens→experts, and the whole thing
lives inside the one jitted train step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.mesh import AXIS_DATA, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR, DP_AXES
from ..utils.logging import logger

P = PartitionSpec


def _one_hot(idx: jnp.ndarray, num: int) -> jnp.ndarray:
    return jax.nn.one_hot(idx, num, dtype=jnp.float32)


def top_k_gating(logits: jnp.ndarray, k: int, capacity: int,
                 noise_rng: Optional[jax.Array] = None,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
    """GShard-style top-k gating over ``[T, E]`` router logits.

    Returns ``(combine_weights [T,E,C], dispatch_mask [T,E,C] bool,
    l_aux, metadata)``.  k ∈ {1, 2} (reference supports exactly these).
    """
    if k not in (1, 2):
        raise ValueError(f"k must be 1 or 2, got {k}")
    T, E = logits.shape
    C = capacity

    route_logits = logits
    if noisy_gate_policy == "RSample" and noise_rng is not None:
        route_logits = logits + jax.random.normal(noise_rng, logits.shape,
                                                  logits.dtype)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]

    idx1 = jnp.argmax(route_logits, axis=-1)  # [T]
    mask1 = _one_hot(idx1, E)

    # load-balancing aux loss (Switch eq.4 / reference l_aux): E·Σ me·ce
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    masks = [mask1]
    idxs = [idx1]
    if k == 2:
        logits2 = jnp.where(mask1.astype(bool), -jnp.inf, route_logits)
        idx2 = jnp.argmax(logits2, axis=-1)
        masks.append(_one_hot(idx2, E))
        idxs.append(idx2)

    # positions within each expert: running count over tokens, per choice
    # (second choices queue behind ALL first choices — reference behavior)
    locations = []
    positions = []
    offset = jnp.zeros((E,), jnp.float32)
    for m in masks:
        loc = jnp.cumsum(m, axis=0) - m + offset[None, :]
        offset = offset + jnp.sum(m, axis=0)
        locations.append(loc)
        positions.append(jnp.sum(loc * m, axis=-1))  # [T] slot in expert

    exp_counts = jnp.sum(masks[0], axis=0)  # pre-drop assignment counts

    # routing telemetry from the PRE-capacity state: per-expert load share,
    # gating entropy over the mean softmax (collapse detector — ln(E) is
    # uniform, → 0 as the router funnels everything to one expert), and the
    # fraction of assignments that overflowed their expert's capacity
    load = exp_counts / jnp.maximum(jnp.float32(T), 1.0)
    entropy = -jnp.sum(me * jnp.log(jnp.maximum(me, 1e-9)))
    assigned = sum(jnp.sum(m) for m in masks)
    overflowed = sum(jnp.sum(m * (pos >= C).astype(m.dtype)[:, None])
                     for m, pos in zip(masks, positions))
    overflow_frac = overflowed / jnp.maximum(assigned, 1.0)

    # capacity-filter masks BEFORE renormalizing (reference top2gating order:
    # a token whose 2nd choice is dropped keeps FULL weight on its 1st)
    if drop_tokens:
        masks = [m * (pos < C).astype(m.dtype)[:, None]
                 for m, pos in zip(masks, positions)]

    combine = jnp.zeros((T, E, C), jnp.float32)
    dispatch = jnp.zeros((T, E, C), bool)
    denom = sum(jnp.sum(gates * m, axis=-1) for m in masks)
    denom = jnp.maximum(denom, 1e-9)
    for m, pos in zip(masks, positions):
        gate_k = jnp.sum(gates * m, axis=-1) / denom  # renormalized over kept
        # out-of-range pos rows one-hot to all-zero, but m is already zero
        # there after the capacity filter
        pos_oh = _one_hot(pos.astype(jnp.int32), C + 1)[:, :C]
        contrib = m[:, :, None] * pos_oh[:, None, :]
        combine = combine + gate_k[:, None, None] * contrib
        dispatch = dispatch | (contrib > 0)

    meta = {"l_aux": l_aux, "exp_counts": exp_counts,
            "drop_rate": 1.0 - jnp.sum(combine > 0) / jnp.maximum(k * T, 1),
            "load": load, "entropy": entropy,
            "overflow_frac": overflow_frac}
    return combine, dispatch, l_aux, meta


@dataclasses.dataclass
class TopKGate:
    """Router config + params-free apply (reference ``TopKGate`` ctor keys).

    The router projection weight lives in the caller's param pytree
    (``wg: [H, E]``) — functional style, no hidden state.
    """

    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True

    def capacity(self, num_tokens: int, train: bool = True) -> int:
        f = self.capacity_factor if train else self.eval_capacity_factor
        cap = int(np.ceil(self.k * num_tokens * f / self.num_experts))
        return max(cap, self.min_capacity)

    def __call__(self, wg: jnp.ndarray, x: jnp.ndarray, train: bool = True,
                 noise_rng: Optional[jax.Array] = None):
        """x: [T, H] tokens → gating tensors (see :func:`top_k_gating`)."""
        logits = x.astype(jnp.float32) @ wg.astype(jnp.float32)
        return top_k_gating(logits, self.k, self.capacity(x.shape[0], train),
                            noise_rng=noise_rng,
                            noisy_gate_policy=self.noisy_gate_policy
                            if train else None,
                            drop_tokens=self.drop_tokens)


class MOELayer:
    """Expert-parallel MoE layer (reference ``MOELayer`` [K]).

    ``expert_fn(expert_params, x)`` maps ``[E, C, H] → [E, C, H]`` with
    expert-stacked params (leading dim E).  Experts shard over the ``expert``
    mesh axis; the tokens→experts einsum transition IS the all-to-all under
    GSPMD.
    """

    def __init__(self, gate: TopKGate,
                 expert_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                 mesh: Optional[Mesh] = None):
        self.gate = gate
        self.expert_fn = expert_fn
        self.mesh = mesh
        self._warned_dropped = False

    def _constrain(self, x, *spec):
        """Sharding constraint, skipped per-entry when a dim isn't divisible
        by its axes (standalone small-batch use outside the engine)."""
        if self.mesh is None:
            return x
        shape = dict(self.mesh.shape)

        def size_of(entry):
            axes = entry if isinstance(entry, tuple) else (entry,)
            return int(np.prod([shape[a] for a in axes]))

        entries = [None if e is not None and x.shape[i] % size_of(e) else e
                   for i, e in enumerate(spec)]
        dropped = [(i, e) for i, e in enumerate(spec)
                   if e is not None and entries[i] is None]
        if dropped and not self._warned_dropped:
            # a capacity/hidden size that doesn't divide the expert axis
            # silently replicates expert compute — surface it once
            self._warned_dropped = True
            logger.warning(
                "MOELayer: dropping sharding constraint(s) %s on shape %s "
                "(dim not divisible by mesh axis) — expert parallelism is "
                "DISABLED for this tensor; pad capacity/hidden to a multiple "
                "of the axis size to restore EP", dropped, tuple(x.shape))
        from ..parallel.mesh import strip_manual_axes

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, strip_manual_axes(*entries)))

    def __call__(self, wg: jnp.ndarray, expert_params: Any, x: jnp.ndarray,
                 train: bool = True, noise_rng: Optional[jax.Array] = None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
        """x: [B, S, H] → (y [B, S, H], l_aux, metadata)."""
        B, S, H = x.shape
        tokens = x.reshape(B * S, H)
        combine, dispatch, l_aux, meta = self.gate(wg, tokens, train,
                                                   noise_rng)
        dtype = x.dtype
        # tokens → expert buffers: [E, C, H]; the einsum over T is the
        # all-to-all boundary (tokens sharded over DP, buffers over expert)
        expert_in = jnp.einsum("tec,th->ech",
                               dispatch.astype(dtype), tokens)
        expert_in = self._constrain(expert_in, AXIS_EXPERT, None, None)
        expert_out = self.expert_fn(expert_params, expert_in)
        expert_out = self._constrain(expert_out, AXIS_EXPERT, None, None)
        y = jnp.einsum("tec,ech->th", combine.astype(dtype), expert_out)
        y = self._constrain(y.reshape(B, S, H), DP_AXES, AXIS_SEQ, None)
        return y, l_aux, meta
