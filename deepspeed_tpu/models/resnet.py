"""CIFAR ResNet — driver config-ladder rung 1 (ZeRO-0, one chip).

Capability anchor: the reference's canonical getting-started example is
CIFAR-10 training through the engine (DeepSpeedExamples ``cifar`` tutorial,
referenced from the reference README [K]); the driver ladder names
"CIFAR ResNet-56 (ZeRO-0, 1 chip)" as config 1 [D BASELINE.md].

TPU-first notes:

* convs via ``jax.lax.conv_general_dilated`` in NHWC — the layout XLA:TPU
  prefers (channels-last feeds the MXU as a [spatial, C_in]x[C_in, C_out]
  contraction);
* the three stages are scans over stacked per-block params (same design
  grammar as the transformer models: one compiled block body per stage);
* normalization is batch-statistics BatchNorm *without* running averages —
  the functional-training formulation (statistics recomputed at eval):
  documented deviation, keeps the engine's params-only TrainState.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.mesh import DP_AXES

P = PartitionSpec


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 56                 # 6n+2; n blocks per stage
    num_classes: int = 10
    width: int = 16                 # stage-1 channels (then 2x, 4x)
    image_size: int = 32
    dtype: Any = jnp.bfloat16

    @property
    def blocks_per_stage(self) -> int:
        if (self.depth - 2) % 6:
            raise ValueError("depth must be 6n+2 (20, 32, 44, 56, 110, …)")
        return (self.depth - 2) // 6

    @classmethod
    def resnet56(cls, **kw) -> "ResNetConfig":
        return cls(depth=56, **kw)

    @classmethod
    def tiny(cls, **kw) -> "ResNetConfig":
        d = dict(depth=8, width=8, image_size=8)
        d.update(kw)
        return cls(**d)

    def num_params(self) -> int:
        n = self.blocks_per_stage
        w = self.width
        total = 3 * 3 * 3 * w + 2 * w                      # stem
        for s, c in enumerate((w, 2 * w, 4 * w)):
            cin = w if s == 0 else c // 2
            total += (9 * cin * c + 9 * c * c + 4 * c      # first block
                      + (cin != c) * cin * c)
            total += (n - 1) * (18 * c * c + 4 * c)        # rest
        return total + 4 * w * self.num_classes + self.num_classes


def _conv(x: jnp.ndarray, w: jnp.ndarray, stride: int = 1) -> jnp.ndarray:
    """NHWC conv, SAME padding; w is [kh, kw, cin, cout]."""
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
        eps: float = 1e-5) -> jnp.ndarray:
    """Batch-statistics norm over (N, H, W) — see module docstring."""
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(x32, axis=(0, 1, 2), keepdims=True)
    return (((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(dt)
            * scale + bias)


class ResNetModel:
    """Functional CIFAR ResNet; params pytree + pure forward/loss."""

    aux_loss_coef: float = 0.0

    def __init__(self, config: ResNetConfig, mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh

    # ------------------------------------------------------------------

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        c = self.config
        n, w = c.blocks_per_stage, c.width
        keys = iter(jax.random.split(rng, 8))

        def he(key, shape):
            # conv fan-in is kh*kw*cin — the last-4-to-last-1 dims whether or
            # not a leading stack dim is present (which may be 0 blocks)
            fan = shape[-4:-1] if len(shape) >= 4 else shape[:-1]
            fan_in = max(int(np.prod(fan)), 1)
            return (jax.random.normal(key, shape, jnp.float32)
                    * np.sqrt(2.0 / fan_in)).astype(jnp.float32)

        def stage(key, cin, cout, blocks):
            ks = jax.random.split(key, 3)
            p = {
                # first block may change channels/stride; stacked rest
                "first": {
                    "conv1": he(ks[0], (3, 3, cin, cout)),
                    "conv2": he(ks[1], (3, 3, cout, cout)),
                    "bn1_s": jnp.ones((cout,), jnp.float32),
                    "bn1_b": jnp.zeros((cout,), jnp.float32),
                    "bn2_s": jnp.ones((cout,), jnp.float32),
                    "bn2_b": jnp.zeros((cout,), jnp.float32),
                },
                "rest": {
                    "conv1": he(ks[2], (blocks - 1, 3, 3, cout, cout)),
                    "conv2": he(jax.random.fold_in(ks[2], 1),
                                (blocks - 1, 3, 3, cout, cout)),
                    "bn1_s": jnp.ones((blocks - 1, cout), jnp.float32),
                    "bn1_b": jnp.zeros((blocks - 1, cout), jnp.float32),
                    "bn2_s": jnp.ones((blocks - 1, cout), jnp.float32),
                    "bn2_b": jnp.zeros((blocks - 1, cout), jnp.float32),
                },
            }
            if cin != cout:
                p["first"]["proj"] = he(jax.random.fold_in(ks[0], 7),
                                        (1, 1, cin, cout))
            return p

        return {
            "stem": {"conv": he(next(keys), (3, 3, 3, w)),
                     "bn_s": jnp.ones((w,), jnp.float32),
                     "bn_b": jnp.zeros((w,), jnp.float32)},
            "stage1": stage(next(keys), w, w, n),
            "stage2": stage(next(keys), w, 2 * w, n),
            "stage3": stage(next(keys), 2 * w, 4 * w, n),
            "head": {"w": he(next(keys), (4 * w, c.num_classes)),
                     "b": jnp.zeros((c.num_classes,), jnp.float32)},
        }

    def param_specs(self, params: Optional[Any] = None) -> Dict[str, Any]:
        """Vision model: no TP split (convs are small); ZeRO composes DP
        sharding on top via the engine's policy."""
        return jax.tree.map(lambda _: P(), self.init_shapes())

    def init_shapes(self):
        return jax.eval_shape(lambda: self.init_params(jax.random.PRNGKey(0)))

    # ------------------------------------------------------------------

    def _block(self, bp: Dict[str, Any], x: jnp.ndarray,
               stride: int = 1) -> jnp.ndarray:
        dt = self.config.dtype
        h = _conv(x, bp["conv1"].astype(dt), stride)
        h = jax.nn.relu(_bn(h, bp["bn1_s"].astype(dt), bp["bn1_b"].astype(dt)))
        h = _conv(h, bp["conv2"].astype(dt))
        h = _bn(h, bp["bn2_s"].astype(dt), bp["bn2_b"].astype(dt))
        if "proj" in bp:
            x = _conv(x, bp["proj"].astype(dt), stride)
        elif stride != 1:
            x = x[:, ::stride, ::stride]
        return jax.nn.relu(x + h)

    def forward(self, params: Any, images: jnp.ndarray) -> jnp.ndarray:
        """[B, H, W, 3] images → [B, num_classes] logits (fp32)."""
        c = self.config
        dt = c.dtype
        x = images.astype(dt)
        x = self._constrain(x)
        st = params["stem"]
        x = jax.nn.relu(_bn(_conv(x, st["conv"].astype(dt)),
                            st["bn_s"].astype(dt), st["bn_b"].astype(dt)))

        for name, stride in (("stage1", 1), ("stage2", 2), ("stage3", 2)):
            sp = params[name]
            x = self._block(sp["first"], x, stride)

            def block(carry, bp):
                return self._block(bp, carry), None

            x, _ = jax.lax.scan(block, x, sp["rest"])

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        logits = (x @ params["head"]["w"].astype(dt)
                  + params["head"]["b"].astype(dt))
        return logits.astype(jnp.float32)

    __call__ = forward

    def _constrain(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.mesh is None:
            return x
        from ..parallel.mesh import strip_manual_axes

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, strip_manual_axes(
                DP_AXES, None, None, None)))

    def loss(self, params: Any, batch: Any) -> jnp.ndarray:
        """Softmax cross entropy; ``batch = {"images", "labels"}`` (or
        ``{"input_ids", "labels"}`` aliasing images for engine compat)."""
        images = batch.get("images", batch.get("input_ids"))
        labels = batch["labels"]
        logits = self.forward(params, images)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, labels[:, None], axis=-1)[:, 0])
