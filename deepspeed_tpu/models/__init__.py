"""Model zoo — TPU-native reference models for the driver config ladder.

The reference ships no models of its own for training (users bring torch
modules); its inference-v2 tree carries llama/mistral/mixtral implementations
(``deepspeed/inference/v2/model_implementations/`` [K]).  Here the model zoo
is first-class because the JAX engine consumes pure loss functions: each
model exposes ``init_params``, ``forward``, ``loss`` and partition-spec rules
that compose with the ZeRO sharding policy.
"""

from .bert import BertConfig, BertModel
from .llama import LlamaConfig, LlamaModel
from .mixtral import MixtralConfig, MixtralModel
from .opt import OPTConfig, OPTModel
from .resnet import ResNetConfig, ResNetModel

__all__ = ["BertConfig", "BertModel", "LlamaConfig", "LlamaModel",
           "MixtralConfig", "MixtralModel", "OPTConfig", "OPTModel",
           "ResNetConfig", "ResNetModel"]
