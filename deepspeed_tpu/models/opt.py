"""OPT-family decoder — completes the reference inference-v2 model list.

Capability anchor: ``deepspeed/inference/v2/model_implementations/opt/``
[K] ships OPT alongside llama/mistral/mixtral; this zoo mirrors that
coverage (llama + mistral preset + mixtral already exist).

Architecture deltas vs Llama (all expressed in the same functional
grammar): learned absolute position embeddings (HF OPT offsets them by 2
— kept for checkpoint compatibility), LayerNorm (with bias) instead of
RMSNorm, biased attention/MLP projections, ReLU MLP, pre-LN blocks with
a final layer norm, tied lm head.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.mesh import AXIS_SEQ, AXIS_TENSOR, DP_AXES
from .bert import _layer_norm
from .llama import _attention

P = PartitionSpec

#: HF OPT reserves positions 0/1 (pad/bos legacy) — positions start here
POSITION_OFFSET = 2


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 2048
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def hd(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "OPTConfig":
        d = dict(vocab_size=512, hidden_size=128, ffn_dim=256,
                 num_layers=4, num_heads=8, max_seq_len=128)
        d.update(kw)
        return cls(**d)

    @classmethod
    def opt_1_3b(cls, **kw) -> "OPTConfig":
        d = dict(hidden_size=2048, ffn_dim=8192, num_layers=24,
                 num_heads=32)
        d.update(kw)
        return cls(**d)

    def num_params(self) -> int:
        H, F, V, L = (self.hidden_size, self.ffn_dim, self.vocab_size,
                      self.num_layers)
        per_layer = 4 * H * H + 4 * H + 2 * H * F + F + H + 4 * H
        return (V + self.max_seq_len + POSITION_OFFSET) * H + \
            L * per_layer + 2 * H


class OPTModel:
    """Functional OPT: tied-embedding causal LM."""

    aux_loss_coef: float = 0.0

    def __init__(self, config: OPTConfig, mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh

    # ------------------------------------------------------------------

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        c = self.config
        H, F, V, L = c.hidden_size, c.ffn_dim, c.vocab_size, c.num_layers
        nh, hd = c.num_heads, c.hd
        k = iter(jax.random.split(rng, 12))

        def normal(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    * (1.0 / np.sqrt(fan_in))).astype(jnp.float32)

        return {
            "embed": normal(next(k), (V, H), H),
            "pos_embed": normal(
                next(k), (c.max_seq_len + POSITION_OFFSET, H), H),
            "layers": {
                "attn": {
                    "wq": normal(next(k), (L, H, nh, hd), H),
                    "wk": normal(next(k), (L, H, nh, hd), H),
                    "wv": normal(next(k), (L, H, nh, hd), H),
                    "wo": normal(next(k), (L, nh, hd, H), H),
                    "bq": jnp.zeros((L, nh, hd), jnp.float32),
                    "bk": jnp.zeros((L, nh, hd), jnp.float32),
                    "bv": jnp.zeros((L, nh, hd), jnp.float32),
                    "bo": jnp.zeros((L, H), jnp.float32),
                },
                "mlp": {
                    "w_in": normal(next(k), (L, H, F), H),
                    "b_in": jnp.zeros((L, F), jnp.float32),
                    "w_out": normal(next(k), (L, F, H), F),
                    "b_out": jnp.zeros((L, H), jnp.float32),
                },
                "attn_ln_w": jnp.ones((L, H), jnp.float32),
                "attn_ln_b": jnp.zeros((L, H), jnp.float32),
                "mlp_ln_w": jnp.ones((L, H), jnp.float32),
                "mlp_ln_b": jnp.zeros((L, H), jnp.float32),
            },
            "final_ln_w": jnp.ones((H,), jnp.float32),
            "final_ln_b": jnp.zeros((H,), jnp.float32),
        }

    def param_specs(self, params: Optional[Any] = None) -> Dict[str, Any]:
        t = AXIS_TENSOR
        return {
            "embed": P(None, None),
            "pos_embed": P(None, None),
            "layers": {
                "attn": {
                    "wq": P(None, None, t, None), "wk": P(None, None, t, None),
                    "wv": P(None, None, t, None), "wo": P(None, t, None, None),
                    "bq": P(None, t, None), "bk": P(None, t, None),
                    "bv": P(None, t, None), "bo": P(None, None),
                },
                "mlp": {
                    "w_in": P(None, None, t), "b_in": P(None, t),
                    "w_out": P(None, t, None), "b_out": P(None, None),
                },
                "attn_ln_w": P(None, None), "attn_ln_b": P(None, None),
                "mlp_ln_w": P(None, None), "mlp_ln_b": P(None, None),
            },
            "final_ln_w": P(None), "final_ln_b": P(None),
        }

    # ------------------------------------------------------------------

    def _constrain(self, x: jnp.ndarray, *spec) -> jnp.ndarray:
        if self.mesh is None:
            return x
        from ..parallel.mesh import strip_manual_axes

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, strip_manual_axes(*spec)))

    def _attn_block(self, lp: Any, x: jnp.ndarray, mask) -> jnp.ndarray:
        c = self.config
        dt = c.dtype
        h = _layer_norm(x, lp["attn_ln_w"].astype(dt),
                        lp["attn_ln_b"].astype(dt), c.layer_norm_eps)
        q = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wq"].astype(dt)) \
            + lp["attn"]["bq"].astype(dt)
        kk = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wk"].astype(dt)) \
            + lp["attn"]["bk"].astype(dt)
        vv = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wv"].astype(dt)) \
            + lp["attn"]["bv"].astype(dt)
        q = self._constrain(q, DP_AXES, AXIS_SEQ, AXIS_TENSOR, None)
        kk = self._constrain(kk, DP_AXES, AXIS_SEQ, AXIS_TENSOR, None)
        vv = self._constrain(vv, DP_AXES, AXIS_SEQ, AXIS_TENSOR, None)
        attn = _attention(q, kk, vv, mask)
        out = jnp.einsum("bshd,hdH->bsH", attn, lp["attn"]["wo"].astype(dt)) \
            + lp["attn"]["bo"].astype(dt)
        return x + out

    def _mlp_block(self, lp: Any, x: jnp.ndarray) -> jnp.ndarray:
        c = self.config
        dt = c.dtype
        h = _layer_norm(x, lp["mlp_ln_w"].astype(dt),
                        lp["mlp_ln_b"].astype(dt), c.layer_norm_eps)
        from ..compression.quantization import maybe_quantize_activation

        h = jnp.einsum("bsH,HF->bsF", h, lp["mlp"]["w_in"].astype(dt)) \
            + lp["mlp"]["b_in"].astype(dt)
        h = maybe_quantize_activation(self, jax.nn.relu(h))
        h = self._constrain(h, DP_AXES, AXIS_SEQ, AXIS_TENSOR)
        h = jnp.einsum("bsF,FH->bsH", h, lp["mlp"]["w_out"].astype(dt)) \
            + lp["mlp"]["b_out"].astype(dt)
        return x + h

    def _check_len(self, S: int) -> None:
        # learned positions have a hard table bound; an OOB jnp.take fills
        # NaN silently, so fail loudly at trace time instead
        if S > self.config.max_seq_len:
            raise ValueError(
                f"sequence length {S} exceeds max_seq_len "
                f"{self.config.max_seq_len} (learned position table)")

    def _trunk(self, params: Any, input_ids: jnp.ndarray,
               positions: jnp.ndarray, mask) -> jnp.ndarray:
        c = self.config
        dt = c.dtype
        x = (jnp.take(params["embed"].astype(dt), input_ids, axis=0)
             + jnp.take(params["pos_embed"].astype(dt),
                        positions + POSITION_OFFSET, axis=0))
        x = self._constrain(x, DP_AXES, AXIS_SEQ, None)

        def layer(carry, lp):
            x = self._attn_block(lp, carry, mask)
            return self._mlp_block(lp, x), None

        body = layer
        if c.remat:
            body = jax.checkpoint(
                layer,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        x, _ = jax.lax.scan(lambda carry, lp: body(carry, lp), x,
                            params["layers"])
        return _layer_norm(x, params["final_ln_w"].astype(dt),
                           params["final_ln_b"].astype(dt), c.layer_norm_eps)

    def forward(self, params: Any, input_ids: jnp.ndarray) -> jnp.ndarray:
        """[B, S] ids → [B, S, V] logits (fp32; tied lm head)."""
        B, S = input_ids.shape
        self._check_len(S)
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
        x = self._trunk(params, input_ids, positions, mask)
        logits = jnp.einsum("bsH,VH->bsV", x,
                            params["embed"].astype(self.config.dtype))
        return logits.astype(jnp.float32)

    __call__ = forward

    def loss(self, params: Any, batch: Any) -> jnp.ndarray:
        from .llama import LlamaModel, masked_cross_entropy

        input_ids, labels = LlamaModel.batch_labels(batch)
        return masked_cross_entropy(self.forward(params, input_ids), labels)

    # ------------------------------------------------------------------
    # v1 inference (init_cache/prefill/decode_step contract)
    # ------------------------------------------------------------------

    def init_cache(self, batch_size: int, max_len: int) -> Dict[str, Any]:
        c = self.config
        shape = (c.num_layers, batch_size, max_len, c.num_heads, c.hd)
        return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype),
                "lengths": jnp.zeros((batch_size,), jnp.int32)}

    def prefill(self, params: Any, input_ids: jnp.ndarray,
                cache: Dict[str, Any]) -> Tuple[jnp.ndarray, Dict[str, Any]]:
        c = self.config
        dt = c.dtype
        B, S = input_ids.shape
        self._check_len(S)
        max_len = cache["k"].shape[2]
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
        x = (jnp.take(params["embed"].astype(dt), input_ids, axis=0)
             + jnp.take(params["pos_embed"].astype(dt),
                        positions + POSITION_OFFSET, axis=0))

        def layer(carry, lp):
            x, = carry
            h = _layer_norm(x, lp["attn_ln_w"].astype(dt),
                            lp["attn_ln_b"].astype(dt), c.layer_norm_eps)
            q = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wq"].astype(dt)) \
                + lp["attn"]["bq"].astype(dt)
            kk = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wk"].astype(dt)) \
                + lp["attn"]["bk"].astype(dt)
            vv = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wv"].astype(dt)) \
                + lp["attn"]["bv"].astype(dt)
            attn = _attention(q, kk, vv, mask)
            out = jnp.einsum("bshd,hdH->bsH", attn,
                             lp["attn"]["wo"].astype(dt)) \
                + lp["attn"]["bo"].astype(dt)
            x = self._mlp_block(lp, x + out)
            pad = max_len - S
            k_entry = jnp.pad(kk, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v_entry = jnp.pad(vv, ((0, 0), (0, pad), (0, 0), (0, 0)))
            return (x,), (k_entry, v_entry)

        (x,), (ks, vs) = jax.lax.scan(layer, (x,), params["layers"])
        x = _layer_norm(x, params["final_ln_w"].astype(dt),
                        params["final_ln_b"].astype(dt), c.layer_norm_eps)
        logits = jnp.einsum("bH,VH->bV", x[:, -1], params["embed"].astype(dt))
        return logits.astype(jnp.float32), {
            "k": ks, "v": vs, "lengths": jnp.full((B,), S, jnp.int32)}

    def decode_step(self, params: Any, cache: Dict[str, Any],
                    tokens: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
        from ..ops.pallas.decode_attention import decode_attention

        c = self.config
        dt = c.dtype
        B = tokens.shape[0]
        lengths = cache["lengths"]
        # clamp: generation past the table emits the last position's
        # embedding rather than NaN (the engine sizes the cache, so this
        # only triggers when a caller over-generates deliberately)
        pos_idx = jnp.minimum(lengths + POSITION_OFFSET,
                              params["pos_embed"].shape[0] - 1)
        x = (jnp.take(params["embed"].astype(dt), tokens, axis=0)
             + jnp.take(params["pos_embed"].astype(dt), pos_idx, axis=0))

        def layer(carry, xs):
            x, = carry
            lp, k_cache, v_cache = xs
            h = _layer_norm(x, lp["attn_ln_w"].astype(dt),
                            lp["attn_ln_b"].astype(dt), c.layer_norm_eps)
            q = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wq"].astype(dt)) \
                + lp["attn"]["bq"].astype(dt)
            kk = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wk"].astype(dt)) \
                + lp["attn"]["bk"].astype(dt)
            vv = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wv"].astype(dt)) \
                + lp["attn"]["bv"].astype(dt)
            k_cache = k_cache.at[jnp.arange(B), lengths].set(kk)
            v_cache = v_cache.at[jnp.arange(B), lengths].set(vv)
            attn = decode_attention(q, k_cache, v_cache, lengths + 1)
            out = jnp.einsum("bhd,hdH->bH", attn,
                             lp["attn"]["wo"].astype(dt)) \
                + lp["attn"]["bo"].astype(dt)
            x = x + out
            h = _layer_norm(x, lp["mlp_ln_w"].astype(dt),
                            lp["mlp_ln_b"].astype(dt), c.layer_norm_eps)
            h = jax.nn.relu(h @ lp["mlp"]["w_in"].astype(dt)
                            + lp["mlp"]["b_in"].astype(dt))
            x = x + h @ lp["mlp"]["w_out"].astype(dt) \
                + lp["mlp"]["b_out"].astype(dt)
            return (x,), (k_cache, v_cache)

        (x,), (ks, vs) = jax.lax.scan(
            layer, (x,), (params["layers"], cache["k"], cache["v"]))
        x = _layer_norm(x, params["final_ln_w"].astype(dt),
                        params["final_ln_b"].astype(dt), c.layer_norm_eps)
        logits = jnp.einsum("bH,VH->bV", x, params["embed"].astype(dt))
        return logits.astype(jnp.float32), {
            "k": ks, "v": vs, "lengths": lengths + 1}
