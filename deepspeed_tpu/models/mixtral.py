"""Mixtral-family sparse-MoE decoder (driver config 4: Mixtral-8x7B + EP).

Reference anchor: DeepSpeed trains Mixtral through MoE+ZeRO (``deepspeed/moe``
[K]; z3 leaf-module interplay for ``MixtralSparseMoeBlock`` [L ACC-DC:1148]);
its inference-v2 tree has a mixtral implementation [K].

TPU-first: Llama backbone (scan-over-layers, Ulysses attention) with the FFN
swapped for the GShard-dense MoE block — expert-stacked per-layer params
``[L, E, ...]`` sharded over the ``expert`` mesh axis, router aux loss
accumulated through the scan carry.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ..parallel.mesh import AXIS_EXPERT, AXIS_TENSOR
from .llama import LlamaConfig, LlamaModel

P = PartitionSpec


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    aux_loss_coef: float = 0.02
    #: token dispatch rung for the MOELayer: auto | dense | sparse | pallas
    #: (ops/pallas/moe_dispatch.choose_dispatch_impl) — a tuning dimension
    moe_dispatch_impl: str = "auto"

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        d = dict(vocab_size=512, hidden_size=128, intermediate_size=176,
                 num_layers=4, num_heads=8, num_kv_heads=4, max_seq_len=256,
                 num_experts=4, top_k=2)
        d.update(kw)
        return cls(**d)

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        d = dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                 num_layers=32, num_heads=32, num_kv_heads=8,
                 max_seq_len=32768, rope_theta=1e6, num_experts=8, top_k=2)
        d.update(kw)
        return cls(**d)


class MixtralModel(LlamaModel):
    """Llama backbone + top-k routed SwiGLU experts."""

    def __init__(self, config: MixtralConfig, mesh: Any = None):
        super().__init__(config, mesh=mesh)
        self.aux_loss_coef = config.aux_loss_coef
        from ..moe.layer import swiglu_expert_fn
        from ..moe.sharded_moe import MOELayer, TopKGate

        gate = TopKGate(num_experts=config.num_experts, k=config.top_k,
                        capacity_factor=config.capacity_factor,
                        eval_capacity_factor=config.capacity_factor,
                        min_capacity=4)
        expert_fn = partial(
            swiglu_expert_fn,
            constrain_act=lambda a: self._constrain(
                a, AXIS_EXPERT, None, AXIS_TENSOR))
        self._moe_layer = MOELayer(gate, expert_fn, mesh=mesh,
                                   dispatch_impl=config.moe_dispatch_impl)

    # ------------------------------------------------------------------

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        c = self.config
        params = super().init_params(rng)
        L, E, H, I = c.num_layers, c.num_experts, c.hidden_size, \
            c.intermediate_size
        k1, k2, k3, k4 = jax.random.split(jax.random.fold_in(rng, 17), 4)

        def normal(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    / np.sqrt(fan_in)).astype(jnp.float32)

        # replace the dense MLP with router + expert-stacked FFN
        del params["layers"]["mlp"]
        params["layers"]["moe"] = {
            "wg": normal(k1, (L, H, E), H),
            "w_gate": normal(k2, (L, E, H, I), H),
            "w_up": normal(k3, (L, E, H, I), H),
            "w_down": normal(k4, (L, E, I, H), I),
        }
        return params

    def param_specs(self, params: Optional[Any] = None) -> Dict[str, Any]:
        specs = super().param_specs(params)
        e, t = AXIS_EXPERT, AXIS_TENSOR
        from ..parallel.mesh import AXIS_PIPE

        pipe = (AXIS_PIPE if self.mesh is not None
                and int(self.mesh.shape.get(AXIS_PIPE, 1)) > 1 else None)
        del specs["layers"]["mlp"]
        specs["layers"]["moe"] = {
            "wg": P(pipe, None, None),
            "w_gate": P(pipe, e, None, t),
            "w_up": P(pipe, e, None, t),
            "w_down": P(pipe, e, t, None),
        }
        return specs

    # ------------------------------------------------------------------

    def _ffn(self, h: jnp.ndarray, lp: Any) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Routed-FFN via the shared MOELayer (one dispatch implementation
        for the whole framework) with an expert-TP-constrained SwiGLU expert."""
        from ..telemetry import numerics

        moe = lp["moe"]
        y, l_aux, meta = self._moe_layer(
            moe["wg"], {k: moe[k] for k in ("w_gate", "w_up", "w_down")}, h)
        numerics.moe_stats(meta)
        return numerics.probe("mlp_out", y), l_aux
