"""Llama-family decoder — the flagship train/bench model, TPU-first.

Capability anchor: the reference trains HF torch Llama through its engine and
ships llama model implementations for inference
(``deepspeed/inference/v2/model_implementations/llama_v2/`` [K]); the driver
ladder names Llama-3-8B (ZeRO-3) and Llama-3-70B (Infinity + Ulysses SP) as
headline configs [D BASELINE.json].

TPU-first design, none of which mirrors the reference's torch modules:

* **Stacked-layer params + ``lax.scan``** — one compiled layer body regardless
  of depth: compile time O(1) in num_layers, and the layout pipeline/layer-
  streaming (ZeRO-Infinity) needs is the native one.
* **GSPMD Ulysses** — sequence parallelism is expressed as sharding
  constraints: activations ride sequence-sharded ``[B, S/sp, H]`` everywhere
  except attention, where Q/K/V are constrained to head-sharded
  ``[B, S, h/(sp·tp), D]``; XLA inserts the all-to-alls the reference issues
  by hand in ``ulysses_sp.py`` (SURVEY §5.7).
* **Tensor parallelism** — Megatron-style column/row sharding is a
  PartitionSpec on the weights (``tensor`` axis) + the same activation
  constraints; no module surgery (reference: ``module_inject/auto_tp.py``).
* **Remat** — ``jax.checkpoint`` on the layer body with a dots-saveable
  policy ≈ reference ``activation_checkpointing`` with partitioned
  activations for free (saved residuals inherit their shardings).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.mesh import AXIS_PIPE, AXIS_SEQ, AXIS_TENSOR, DP_AXES
from ..telemetry import numerics

P = PartitionSpec


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    #: Mistral-style sliding-window attention: each token attends at most
    #: this many previous positions (None → full causal).  Training and
    #: prefill mask by window; decode masks the cache tail (a rolling
    #: window KV cache is a serving optimization for a later round).
    sliding_window: Optional[int] = None
    dtype: Any = jnp.bfloat16
    remat: bool = True
    #: >1 → chunk final projection+loss over the sequence so the [B,S,V]
    #: logits are never materialized (ALST sequence-tiled loss)
    loss_tiles: int = 1
    #: pipeline microbatch count (0 → pipe axis size); used when the mesh has
    #: a pipe axis > 1
    pp_microbatches: int = 0
    #: virtual stages per pipe rank (>1 → interleaved schedule: bubble
    #: shrinks by this factor; num_layers must divide by pp*pp_interleave)
    pp_interleave: int = 1
    #: "flash" → Pallas online-softmax kernel (TPU; falls back to XLA off-TPU),
    #: "xla" → einsum+softmax left to the XLA fuser
    attn_impl: str = "xla"
    #: flash kernel block sizes; 0 = the seq-length-aware table
    #: (ops/pallas/lattice.auto_flash_blocks) — surfaced so the tuning
    #: plane's kernels.flash_block_* dimensions reach the kernel
    flash_block_q: int = 0
    flash_block_k: int = 0

    @property
    def hd(self) -> int:
        return self.head_dim if self.head_dim is not None else (
            self.hidden_size // self.num_heads)

    # ------------------------------------------------------------------
    # presets (sizes follow the public Llama/Llama-3 configs)
    # ------------------------------------------------------------------

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test/CI model — small enough for an 8-device CPU mesh."""
        d = dict(vocab_size=512, hidden_size=128, intermediate_size=352,
                 num_layers=4, num_heads=8, num_kv_heads=4, max_seq_len=256)
        d.update(kw)
        return cls(**d)

    @classmethod
    def mistral_7b(cls, **kw) -> "LlamaConfig":
        """Mistral-7B: Llama architecture + GQA + sliding-window attention
        (the reference ships a mistral implementation in
        ``inference/v2/model_implementations`` [K])."""
        d = dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                 num_layers=32, num_heads=32, num_kv_heads=8,
                 max_seq_len=8192, rope_theta=10000.0, sliding_window=4096)
        d.update(kw)
        return cls(**d)

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        d = dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                 num_layers=32, num_heads=32, num_kv_heads=8,
                 max_seq_len=8192, rope_theta=500000.0)
        d.update(kw)
        return cls(**d)

    @classmethod
    def llama3_70b(cls, **kw) -> "LlamaConfig":
        d = dict(vocab_size=128256, hidden_size=8192, intermediate_size=28672,
                 num_layers=80, num_heads=64, num_kv_heads=8,
                 max_seq_len=8192, rope_theta=500000.0)
        d.update(kw)
        return cls(**d)

    def num_params(self) -> int:
        H, I, V, L = (self.hidden_size, self.intermediate_size,
                      self.vocab_size, self.num_layers)
        hd, nh, nkv = self.hd, self.num_heads, self.num_kv_heads
        per_layer = (H * nh * hd + 2 * H * nkv * hd + nh * hd * H  # attn
                     + 3 * H * I  # swiglu (gate, up, down)
                     + 2 * H)  # norms
        head = H if self.tie_embeddings else H + H * V
        return V * H + L * per_layer + head


@jax.custom_vjp
def _tp_copy(x: jnp.ndarray) -> jnp.ndarray:
    """Megatron's *f* operator for the manual-TP layer: identity forward,
    psum over the (manual) ``tensor`` axis in backward — the input of a
    column-parallel linear is used by every rank, so its cotangent is the
    cross-rank sum."""
    return x


def _tp_copy_fwd(x):
    return x, None


def _tp_copy_bwd(_, g):
    from ..comm.comm import psum
    from ..parallel.mesh import AXIS_TENSOR

    return (psum(g, AXIS_TENSOR),)


_tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


@jax.custom_vjp
def _tp_reduce(x: jnp.ndarray) -> jnp.ndarray:
    """Megatron's *g* operator: psum over the manual ``tensor`` axis in
    forward, IDENTITY backward (the psum output is replicated, so its
    cotangent is already the full value on every rank).  Explicit because
    ``lax.psum``'s autodiff transpose under ``check_vma=False`` shard_map
    is another psum — which would scale row-parallel cotangents by tp."""
    from ..comm.comm import psum
    from ..parallel.mesh import AXIS_TENSOR

    return psum(x, AXIS_TENSOR)


def _tp_reduce_fwd(x):
    return _tp_reduce(x), None


def _tp_reduce_bwd(_, g):
    return (g,)


_tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


@jax.custom_vjp
def _tp_max(x: jnp.ndarray) -> jnp.ndarray:
    """Cross-rank max over the manual ``tensor`` axis with a ZERO
    backward — used only for the log-sum-exp shift, whose derivative
    w.r.t. the shift is identically 0 (``lax.pmax`` has no autodiff rule
    at all, so the no-op cotangent must be spelled out)."""
    from ..comm.comm import pmax
    from ..parallel.mesh import AXIS_TENSOR

    return pmax(x, AXIS_TENSOR)


def _tp_max_fwd(x):
    return _tp_max(x), None


def _tp_max_bwd(_, g):
    return (jnp.zeros_like(g),)


_tp_max.defvjp(_tp_max_fwd, _tp_max_bwd)


def _rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(dt) * w


def _rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary embedding on [..., S, h, D] with positions [..., S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., S, D/2]
    cos = jnp.cos(angles)[..., :, None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def masked_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray
                         ) -> jnp.ndarray:
    """Token-mean CE with -100 ignore positions (HF convention) — the one
    home of the loss tail shared by every LM in the zoo."""
    valid = labels != -100
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)


def _attention(q, k, v, mask):
    """Reference attention: fp32 softmax; [B, S, h, D] layout.

    Swapped for the Pallas flash kernel on TPU via ops.attention once the
    kernel path lands (SURVEY §7 phase 11) — the caller controls that.
    """
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class LlamaModel:
    """Functional model: params are a plain pytree, forward is pure.

    ``mesh=None`` (single device) skips all sharding constraints; with a mesh,
    the constraints express ZeRO/TP/SP placement and GSPMD inserts the
    collectives.
    """

    #: weight on the router load-balancing aux loss (dense model: no-op)
    aux_loss_coef: float = 0.0

    def __init__(self, config: LlamaConfig, mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh

    # ------------------------------------------------------------------
    # init
    # ------------------------------------------------------------------

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        c = self.config
        H, I, V, L = c.hidden_size, c.intermediate_size, c.vocab_size, c.num_layers
        hd, nh, nkv = c.hd, c.num_heads, c.num_kv_heads
        k = iter(jax.random.split(rng, 9))

        def normal(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    * (1.0 / np.sqrt(fan_in))).astype(jnp.float32)

        params = {
            "embed": normal(next(k), (V, H), H),
            "layers": {
                "attn": {
                    "wq": normal(next(k), (L, H, nh, hd), H),
                    "wk": normal(next(k), (L, H, nkv, hd), H),
                    "wv": normal(next(k), (L, H, nkv, hd), H),
                    "wo": normal(next(k), (L, nh, hd, H), nh * hd),
                },
                "mlp": {
                    "w_gate": normal(next(k), (L, H, I), H),
                    "w_up": normal(next(k), (L, H, I), H),
                    "w_down": normal(next(k), (L, I, H), I),
                },
                "attn_norm": jnp.ones((L, H), jnp.float32),
                "mlp_norm": jnp.ones((L, H), jnp.float32),
            },
            "final_norm": jnp.ones((H,), jnp.float32),
        }
        if not c.tie_embeddings:
            params["lm_head"] = normal(next(k), (H, V), H)
        return params

    # ------------------------------------------------------------------
    # partition specs (composed with ZeRO by the engine's sharding policy)
    # ------------------------------------------------------------------

    def param_specs(self, params: Optional[Any] = None) -> Dict[str, Any]:
        """Megatron-style TP specs on the ``tensor`` axis; the layer-stack
        dim shards over ``pipe`` when pipeline parallelism is active; DP/ZeRO
        axes are layered on top by ``ZeroShardingPolicy.compose`` (reference
        analogue: AutoTP column/row policy, ``module_inject/auto_tp.py`` [K])."""
        t = AXIS_TENSOR
        pipe = (AXIS_PIPE if self.mesh is not None
                and int(self.mesh.shape.get(AXIS_PIPE, 1)) > 1 else None)
        specs = {
            "embed": P(None, None),  # vocab gather stays local; H replicated
            "layers": {
                "attn": {
                    "wq": P(pipe, None, t, None),   # column (head) split
                    "wk": P(pipe, None, t, None),
                    "wv": P(pipe, None, t, None),
                    "wo": P(pipe, t, None, None),   # row split
                },
                "mlp": {
                    "w_gate": P(pipe, None, t),     # column split
                    "w_up": P(pipe, None, t),
                    "w_down": P(pipe, t, None),     # row split
                },
                "attn_norm": P(pipe, None),
                "mlp_norm": P(pipe, None),
            },
            "final_norm": P(None),
        }
        if not self.config.tie_embeddings:
            specs["lm_head"] = P(None, t)  # vocab-sharded output projection
        return specs

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------

    def _constrain(self, x: jnp.ndarray, *spec) -> jnp.ndarray:
        if self.mesh is None:
            return x
        from ..parallel.mesh import strip_manual_axes

        stripped = strip_manual_axes(*spec)
        from ..utils.jax_compat import abstract_mesh_or_none

        am = abstract_mesh_or_none()
        if am is not None and not am.empty:
            # inside a (partial-manual) shard_map / set_mesh scope: a bare
            # PartitionSpec binds to the CONTEXT mesh — a concrete-mesh
            # NamedSharding would fail the context-consistency check
            return jax.lax.with_sharding_constraint(x, stripped)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, stripped))

    def decoder_layer(self, lp: Any, x: jnp.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """ONE decoder layer ``[B, S, H] → ([B, S, H], aux_loss)`` — the unit
        of the scan in :meth:`_forward_trunk` AND the unit of ZeRO-Infinity
        layer streaming (``runtime/swap_tensor``), where each layer's params
        arrive from host/NVMe just ahead of use."""
        c = self.config
        out = self._attn_block(lp, x)
        # back to the sequence-sharded home layout
        x = numerics.probe(
            "resid_attn", self._constrain(x + out, DP_AXES, AXIS_SEQ, None))

        h = _rms_norm(x, lp["mlp_norm"].astype(c.dtype), c.rms_norm_eps)
        ffn_out, l_aux = self._ffn(h, lp)
        x = numerics.probe(
            "resid_ffn",
            self._constrain(x + ffn_out, DP_AXES, AXIS_SEQ, None))
        return x, l_aux

    def _attn_block(self, lp: Any, x: jnp.ndarray) -> jnp.ndarray:
        """Attention half of one decoder layer (its norm + QKV + attention
        + output proj, WITHOUT the residual) — separately callable so the
        per-module flops profiler can attribute cost at module_depth 2."""
        from ..runtime.sequence_parallel.ulysses_sp import ulysses_attention

        c = self.config
        n_rep = c.num_heads // c.num_kv_heads
        # the ring branch below is taken only with a mesh; every other path
        # (incl. ring-configured but mesh-less) needs GQA-expanded KV
        ring_active = c.attn_impl == "ring" and self.mesh is not None

        def apply_rope_qk(q, kk):
            """Global-position RoPE on q/k — ONE home for position handling
            (used by the local attn body AND the ring branch)."""
            S = q.shape[1]
            positions = jnp.arange(S)[None, :]
            return (_rope(q, positions, c.rope_theta),
                    _rope(kk, positions, c.rope_theta))

        def attn_fn(q, kk, vv):
            """Position-exact attention on [b, S, h_local, d] blocks — runs
            under shard_map with the FULL sequence after the Ulysses
            all-to-all (heads local), or directly when unsharded."""
            q, kk = apply_rope_qk(q, kk)
            S = q.shape[1]
            W = c.sliding_window
            if c.attn_impl == "flash":
                from ..ops.pallas.flash_attention import flash_attention

                # window rides into the kernel: k-blocks wholly outside the
                # window are skipped, so windowed work is O(S·W), not O(S²)
                return flash_attention(q, kk, vv, True,
                                       block_q=c.flash_block_q,
                                       block_k=c.flash_block_k, window=W)
            from ..ops.masks import local_attention_mask

            pos = jnp.arange(S)
            mask = local_attention_mask(pos, pos, causal=True, window=W)
            return _attention(q, kk, vv, mask[None, None])

        h = _rms_norm(x, lp["attn_norm"].astype(c.dtype), c.rms_norm_eps)
        q = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wq"].astype(c.dtype))
        kk = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wk"].astype(c.dtype))
        vv = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wv"].astype(c.dtype))
        if n_rep > 1 and not ring_active:
            # GQA: repeat KV heads so every Ulysses rank holds a slice;
            # the ring path rotates kv-width blocks and expands per-visit
            kk = jnp.repeat(kk, n_rep, axis=2)
            vv = jnp.repeat(vv, n_rep, axis=2)
        # probe sites live OUTSIDE the attention branch below: the
        # ulysses path runs attn_fn under shard_map and the ring path
        # rotates inside collectives — a probe in there would register a
        # tracer that cannot escape the manual region
        q = numerics.probe(
            "attn_q", self._constrain(q, DP_AXES, AXIS_SEQ, AXIS_TENSOR,
                                      None))
        kk = self._constrain(kk, DP_AXES, AXIS_SEQ, AXIS_TENSOR, None)
        vv = self._constrain(vv, DP_AXES, AXIS_SEQ, AXIS_TENSOR, None)
        if ring_active:
            # ring SP: sequence stays sharded THROUGH attention (no
            # head-count bound, unlike Ulysses) — RoPE on global positions
            # first, then KV blocks rotate over the seq axis
            from ..runtime.sequence_parallel.ring import ring_attention

            q, kk = apply_rope_qk(q, kk)
            attn = ring_attention(q, kk, vv, causal=True, mesh=self.mesh,
                                  window=c.sliding_window)
        elif self.mesh is not None:
            attn = ulysses_attention(attn_fn, q, kk, vv, mesh=self.mesh)
        else:
            attn = attn_fn(q, kk, vv)
        attn = numerics.probe("attn_ctx", attn)
        return numerics.probe(
            "attn_out", jnp.einsum("bshd,hdH->bsH", attn,
                                   lp["attn"]["wo"].astype(c.dtype)))

    def decoder_layer_manual_tp(self, lp: Any, x: jnp.ndarray
                                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """ONE decoder layer on LOCAL tensor shards under a MANUAL
        ``tensor`` axis — the 1F1B × TP path.

        Why it exists: the 1F1B schedule is a pipe-manual ``shard_map``,
        and tensor-axis GSPMD constraints INSIDE a partial-manual region
        trip an XLA partitioner CHECK (spmd_partitioner_util.cc; see the
        engine's routing note).  Manualizing the tensor axis too removes
        every in-region constraint: this method is the Megatron
        column/row pattern (reference ``megatron/mpu`` semantics via
        AutoTP specs, SURVEY §2.1 #25) with explicit collectives —
        ``_tp_copy`` (identity fwd / psum bwd: Megatron's *f*) before the
        column-parallel projections, ``psum`` (Megatron's *g*) after the
        row-parallel ones.

        ``lp`` leaves are the per-rank shards ``param_specs`` dictates:
        wq/wk/wv ``[H, h/tp, d]``, wo ``[h/tp, d, H]``, w_gate/w_up
        ``[H, I/tp]``, w_down ``[I/tp, H]``, norms replicated.  ``x`` is
        the full ``[B, S, H]`` activation (replicated over tensor)."""
        c = self.config
        n_rep = c.num_heads // c.num_kv_heads

        h = _rms_norm(x, lp["attn_norm"].astype(c.dtype), c.rms_norm_eps)
        h = _tp_copy(h)
        q = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wq"].astype(c.dtype))
        kk = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wk"].astype(c.dtype))
        vv = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wv"].astype(c.dtype))
        if n_rep > 1:
            kk = jnp.repeat(kk, n_rep, axis=2)
            vv = jnp.repeat(vv, n_rep, axis=2)
        S = q.shape[1]
        positions = jnp.arange(S)[None, :]
        q = _rope(q, positions, c.rope_theta)
        kk = _rope(kk, positions, c.rope_theta)
        W = c.sliding_window
        if c.attn_impl == "flash":
            from ..ops.pallas.flash_attention import flash_attention

            attn = flash_attention(q, kk, vv, True,
                                   block_q=c.flash_block_q,
                                   block_k=c.flash_block_k, window=W)
        else:
            from ..ops.masks import local_attention_mask

            pos = jnp.arange(S)
            mask = local_attention_mask(pos, pos, causal=True, window=W)
            attn = _attention(q, kk, vv, mask[None, None])
        out = jnp.einsum("bshd,hdH->bsH", attn,
                         lp["attn"]["wo"].astype(c.dtype))
        x = x + _tp_reduce(out)

        h2 = _rms_norm(x, lp["mlp_norm"].astype(c.dtype), c.rms_norm_eps)
        h2 = _tp_copy(h2)
        gate = jnp.einsum("bsH,HI->bsI", h2,
                          lp["mlp"]["w_gate"].astype(c.dtype))
        up = jnp.einsum("bsH,HI->bsI", h2, lp["mlp"]["w_up"].astype(c.dtype))
        down = jnp.einsum("bsI,IH->bsH", jax.nn.silu(gate) * up,
                          lp["mlp"]["w_down"].astype(c.dtype))
        x = x + _tp_reduce(down)
        return x, jnp.float32(0.0)

    def profile_submodules(self) -> Dict[str, Any]:
        """Depth-2 module pieces for the flops profiler: name →
        ``fn(lp, x)`` over one decoder layer's params + activations."""
        c = self.config

        def mlp(lp, x):
            h = _rms_norm(x, lp["mlp_norm"].astype(c.dtype), c.rms_norm_eps)
            return self._ffn(h, lp)[0]

        return {"attn": self._attn_block, "mlp": mlp}

    def embed_fwd(self, params: Any, input_ids: jnp.ndarray) -> jnp.ndarray:
        """[B, S] ids → embedded activations in the home layout."""
        c = self.config
        x = jnp.take(params["embed"].astype(c.dtype), input_ids, axis=0)
        # activations ride batch-sharded + sequence-sharded (Ulysses home
        # layout; a 1-sized seq axis makes this a no-op)
        return self._constrain(x, DP_AXES, AXIS_SEQ, None)

    def _forward_trunk(self, params: Any, input_ids: jnp.ndarray
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """[B, S] token ids → (final-norm hidden [B, S, H], aux loss)."""
        c = self.config
        x = numerics.probe("embed", self.embed_fwd(params, input_ids))

        def layer(carry, lp):
            x, aux = carry
            # numerics bracket: the body's probe stats exit the scan as
            # its ys (stacked [L, ...] per-layer) — None when the plane
            # is off, which leaves today's jaxpr untouched
            mark = numerics.scan_mark()
            x, l_aux = self.decoder_layer(lp, x)
            return (x, aux + l_aux), numerics.scan_drain(mark)

        body = layer
        if c.remat:
            body = jax.checkpoint(
                layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

        pp = (int(self.mesh.shape.get(AXIS_PIPE, 1))
              if self.mesh is not None else 1)
        if pp > 1:
            from ..parallel.pipeline import pipeline_apply

            B, S = input_ids.shape
            M = c.pp_microbatches or pp
            if B % M:
                raise ValueError(
                    f"batch {B} not divisible by pipeline microbatches {M}")
            if c.num_layers % pp:
                raise ValueError(
                    f"num_layers {c.num_layers} not divisible by pp={pp}")
            micro = (x.reshape(M, B // M, S, -1),
                     jnp.zeros((M,), jnp.float32))

            def pipe_layer(lp, act):
                (nx, naux), _ = body(act, lp)
                return (nx, naux)

            out_x, out_aux = pipeline_apply(pipe_layer, params["layers"],
                                            micro, self.mesh,
                                            virtual_stages=c.pp_interleave)
            x = out_x.reshape(B, S, -1)
            aux = out_aux.mean()
        else:
            (x, aux), ys = jax.lax.scan(lambda carry, lp: body(carry, lp),
                                        (x, jnp.float32(0.0)),
                                        params["layers"])
            numerics.scan_collect(ys)

        x = numerics.probe(
            "final_norm",
            _rms_norm(x, params["final_norm"].astype(c.dtype),
                      c.rms_norm_eps))
        return x, aux

    def _ffn(self, h: jnp.ndarray, lp: Any) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Dense SwiGLU FFN; Mixtral overrides with the MoE block.  Returns
        (output, aux_loss)."""
        c = self.config
        gate = jnp.einsum("bsH,HI->bsI", h, lp["mlp"]["w_gate"].astype(c.dtype))
        up = jnp.einsum("bsH,HI->bsI", h, lp["mlp"]["w_up"].astype(c.dtype))
        from ..compression.quantization import maybe_quantize_activation

        act = maybe_quantize_activation(self, jax.nn.silu(gate) * up)
        act = self._constrain(act, DP_AXES, AXIS_SEQ, AXIS_TENSOR)
        down = jnp.einsum("bsI,IH->bsH", act,
                          lp["mlp"]["w_down"].astype(c.dtype))
        return numerics.probe("mlp_out", down), jnp.float32(0.0)

    def _head(self, params: Any) -> jnp.ndarray:
        return (params["embed"].T if self.config.tie_embeddings
                else params["lm_head"])

    # ------------------------------------------------------------------
    # KV-cache inference path (consumed by deepspeed_tpu.inference)
    # ------------------------------------------------------------------

    def init_cache(self, batch_size: int, max_len: int) -> Dict[str, Any]:
        """Decode cache: stores ``num_kv_heads`` heads only — GQA groups are
        expanded inside the decode kernel, keeping the cache-HBM footprint at
        the GQA size (4× smaller for llama3-8b, 8× for 70b)."""
        c = self.config
        shape = (c.num_layers, batch_size, max_len, c.num_kv_heads, c.hd)
        return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype),
                "lengths": jnp.zeros((batch_size,), jnp.int32)}

    def prefill(self, params: Any, input_ids: jnp.ndarray,
                cache: Dict[str, Any]) -> Tuple[jnp.ndarray, Dict[str, Any]]:
        """Process the prompt [B, S]; returns (last-token logits [B, V],
        filled cache)."""
        c = self.config
        B, S = input_ids.shape
        max_len = cache["k"].shape[2]
        n_rep = c.num_heads // c.num_kv_heads
        from ..ops.masks import local_attention_mask

        x = jnp.take(params["embed"].astype(c.dtype), input_ids, axis=0)
        positions = jnp.arange(S)[None, :]
        pos = jnp.arange(S)
        causal = local_attention_mask(pos, pos, causal=True,
                                      window=c.sliding_window)[None, None]

        def layer(carry, lp):
            x, = carry
            h = _rms_norm(x, lp["attn_norm"].astype(c.dtype), c.rms_norm_eps)
            q = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wq"].astype(c.dtype))
            kk = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wk"].astype(c.dtype))
            vv = jnp.einsum("bsH,Hhd->bshd", h, lp["attn"]["wv"].astype(c.dtype))
            q = _rope(q, positions, c.rope_theta)
            kk = _rope(kk, positions, c.rope_theta)
            # cache keeps the GQA (kv-head) layout; expand only for compute
            kk_full = jnp.repeat(kk, n_rep, axis=2) if n_rep > 1 else kk
            vv_full = jnp.repeat(vv, n_rep, axis=2) if n_rep > 1 else vv
            attn = _attention(q, kk_full, vv_full, causal)
            out = jnp.einsum("bshd,hdH->bsH", attn,
                             lp["attn"]["wo"].astype(c.dtype))
            x = x + out
            h = _rms_norm(x, lp["mlp_norm"].astype(c.dtype), c.rms_norm_eps)
            ffn_out, _ = self._ffn(h, lp)
            x = x + ffn_out
            pad = max_len - S
            k_entry = jnp.pad(kk, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v_entry = jnp.pad(vv, ((0, 0), (0, pad), (0, 0), (0, 0)))
            return (x,), (k_entry, v_entry)

        (x,), (ks, vs) = jax.lax.scan(layer, (x,), params["layers"])
        x = _rms_norm(x, params["final_norm"].astype(c.dtype), c.rms_norm_eps)
        logits = jnp.einsum("bH,HV->bV", x[:, -1],
                            self._head(params).astype(c.dtype))
        cache = {"k": ks, "v": vs,
                 "lengths": jnp.full((B,), S, jnp.int32)}
        return logits.astype(jnp.float32), cache

    def decode_step(self, params: Any, cache: Dict[str, Any],
                    tokens: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
        """One generation step: tokens [B] → (logits [B, V], updated cache)."""
        from ..ops.pallas.decode_attention import decode_attention

        c = self.config
        B = tokens.shape[0]
        n_rep = c.num_heads // c.num_kv_heads
        lengths = cache["lengths"]
        x = jnp.take(params["embed"].astype(c.dtype), tokens, axis=0)  # [B,H]
        pos = lengths[:, None]  # [B,1] next position per sequence

        def layer(carry, xs):
            x, = carry
            lp, k_cache, v_cache = xs
            h = _rms_norm(x, lp["attn_norm"].astype(c.dtype), c.rms_norm_eps)
            q = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wq"].astype(c.dtype))
            kk = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wk"].astype(c.dtype))
            vv = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wv"].astype(c.dtype))
            q = _rope(q[:, None], pos, c.rope_theta)[:, 0]
            kk = _rope(kk[:, None], pos, c.rope_theta)[:, 0]
            # cache stays in kv-head layout; the kernel expands GQA groups
            k_cache = k_cache.at[jnp.arange(B), lengths].set(kk)
            v_cache = v_cache.at[jnp.arange(B), lengths].set(vv)
            attn = decode_attention(q, k_cache, v_cache, lengths + 1,
                                    window=c.sliding_window)
            out = jnp.einsum("bhd,hdH->bH", attn,
                             lp["attn"]["wo"].astype(c.dtype))
            x = x + out
            h = _rms_norm(x, lp["mlp_norm"].astype(c.dtype), c.rms_norm_eps)
            ffn_out, _ = self._ffn(h[:, None, :], lp)
            x = x + ffn_out[:, 0, :]
            return (x,), (k_cache, v_cache)

        (x,), (ks, vs) = jax.lax.scan(
            layer, (x,), (params["layers"], cache["k"], cache["v"]))
        x = _rms_norm(x, params["final_norm"].astype(c.dtype), c.rms_norm_eps)
        logits = jnp.einsum("bH,HV->bV", x,
                            self._head(params).astype(c.dtype))
        new_cache = {"k": ks, "v": vs, "lengths": lengths + 1}
        return logits.astype(jnp.float32), new_cache

    def forward(self, params: Any, input_ids: jnp.ndarray) -> jnp.ndarray:
        """[B, S] token ids → [B, S, V] logits (fp32)."""
        x, _ = self._forward_trunk(params, input_ids)
        logits = jnp.einsum("bsH,HV->bsV", x,
                            self._head(params).astype(self.config.dtype))
        return logits.astype(jnp.float32)

    __call__ = forward

    # ------------------------------------------------------------------
    # loss
    # ------------------------------------------------------------------

    @staticmethod
    def batch_labels(batch: Any) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(input_ids, labels) from either batch form (labels default to
        shifted inputs; -100 = ignore, HF convention)."""
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        else:
            input_ids, labels = batch, None
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], -100)], axis=1)
        return input_ids, labels

    def _ce_from_hidden(self, params: Any, hidden: jnp.ndarray,
                        labels: jnp.ndarray) -> jnp.ndarray:
        """Cross entropy from final-norm'd hidden states."""
        c = self.config
        head = self._head(params).astype(c.dtype)
        if c.loss_tiles > 1:
            from ..runtime.sequence_parallel.ulysses_sp import \
                sequence_tiled_loss

            return sequence_tiled_loss(
                lambda h: jnp.einsum("bsH,HV->bsV", h, head),
                hidden, labels, c.loss_tiles)
        logits = jnp.einsum("bsH,HV->bsV", hidden, head)
        return masked_cross_entropy(logits, labels)

    def head_loss(self, params: Any, x: jnp.ndarray, batch: Any
                  ) -> jnp.ndarray:
        """Loss tail for layer streaming: post-last-layer activations →
        final norm → CE.  ``params`` needs only the resident leaves
        (final_norm + embed/lm_head)."""
        c = self.config
        _, labels = self.batch_labels(batch)
        hidden = _rms_norm(x, params["final_norm"].astype(c.dtype),
                           c.rms_norm_eps)
        return self._ce_from_hidden(params, hidden, labels)

    #: resident leaves head_loss_manual_tp reads — the engine narrows the
    #: manual-region head argument to exactly these (a module reading more
    #: must extend this, or the key goes missing inside the shard_map)
    manual_tp_head_param_keys = ("final_norm", "lm_head")

    def head_loss_manual_tp(self, params: Any, x: jnp.ndarray, batch: Any
                            ) -> jnp.ndarray:
        """Vocab-parallel loss tail for the manual-TP 1F1B region:
        ``params["lm_head"]`` is this rank's COLUMN shard ``[H, V/tp]``
        (Megatron parallel cross entropy) — local logits, cross-rank
        max-shifted log-sum-exp and gold-logit gather via explicit
        collectives, so no rank ever materializes (or differentiates)
        the full-vocab projection.  Numerics match
        :func:`masked_cross_entropy` on the gathered logits."""
        from ..parallel.mesh import AXIS_TENSOR

        c = self.config
        _, labels = self.batch_labels(batch)
        hidden = _rms_norm(x, params["final_norm"].astype(c.dtype),
                           c.rms_norm_eps)
        W = params["lm_head"].astype(c.dtype)          # [H, V/tp] local
        vshard = W.shape[-1]
        rank = jax.lax.axis_index(AXIS_TENSOR)

        def chunk_nll(hid, lab):
            """(Σ nll over valid, valid count) for one sequence chunk."""
            logits = jnp.einsum("bsH,HV->bsV", _tp_copy(hid),
                                W).astype(jnp.float32)
            valid = lab != -100
            # max-shift across shards; zero-grad (d lse/dm is 0)
            m = _tp_max(jnp.max(logits, axis=-1))       # [B, s]
            se = jnp.sum(jnp.exp(logits - m[..., None]), axis=-1)
            lse = jnp.log(_tp_reduce(se)) + m
            # gold logit lives on exactly one shard
            off = jnp.where(valid, lab, 0) - rank * vshard
            in_shard = (off >= 0) & (off < vshard)
            gold_loc = jnp.take_along_axis(
                logits, jnp.clip(off, 0, vshard - 1)[..., None],
                -1)[..., 0]
            gold = _tp_reduce(jnp.where(in_shard, gold_loc, 0.0))
            nll = lse - gold
            return (jnp.sum(jnp.where(valid, nll, 0.0)),
                    jnp.sum(valid).astype(jnp.int32))

        T = c.loss_tiles
        if T > 1 and hidden.shape[1] % T == 0:
            # ALST sequence tiling, vocab-parallel flavor: each tile's
            # [B, S/T, V/tp] logits live only inside its (rematerialized)
            # scan step — the same memory bound head_loss gets from
            # sequence_tiled_loss
            B, S, H = hidden.shape
            hs = jnp.moveaxis(hidden.reshape(B, T, S // T, H), 1, 0)
            ls = jnp.moveaxis(labels.reshape(B, T, S // T), 1, 0)

            def body(carry, xs):
                tot, cnt = carry
                t, n = jax.checkpoint(chunk_nll)(xs[0], xs[1])
                return (tot + t, cnt + n), None

            (tot, cnt), _ = jax.lax.scan(
                body, (jnp.float32(0.0), jnp.int32(0)), (hs, ls))
        else:
            tot, cnt = chunk_nll(hidden, labels)
        return tot / jnp.maximum(cnt, 1)

    def loss(self, params: Any, batch: Any) -> jnp.ndarray:
        """Next-token cross entropy.  ``batch`` is ``{"input_ids": [B, S]}``
        (labels = shifted inputs) or ``{"input_ids", "labels"}`` with -100
        ignore positions (HF convention)."""
        input_ids, labels = self.batch_labels(batch)
        hidden, aux = self._forward_trunk(params, input_ids)
        ce = self._ce_from_hidden(params, hidden, labels)
        return ce + self.aux_loss_coef * aux
