"""BERT-family encoder — driver config-ladder rung 2 (ZeRO-1/2).

Capability anchor: the reference's canonical ZeRO-1/2 showcase is
BERT-large pretraining (``tests/model/BingBertSquad`` convergence suite +
the FusedLamb large-batch BERT path [K], SURVEY §4/§2.2); the driver
ladder names "BERT-large (ZeRO-1/2 over ICI)" as config 2 [D BASELINE.md].

TPU-first, same design grammar as ``llama.py``:

* stacked per-layer params + ``lax.scan`` — one compiled encoder block;
* bidirectional (no causal mask) attention left to XLA's fusion — at
  BERT sizes (S=512) flash tiling buys nothing over the fused softmax;
* masked-LM loss with -100 ignore positions (HF convention), so HF-style
  data pipelines feed it unchanged;
* TP/ZeRO placement via ``param_specs`` exactly like the decoder models.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.mesh import AXIS_SEQ, AXIS_TENSOR, DP_AXES
from ..telemetry import numerics

P = PartitionSpec


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024          # BERT-large defaults
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    max_seq_len: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    remat: bool = True
    #: "flash" → the Pallas online-softmax kernel, non-causal, with the
    #: padding mask riding in as segment ids (kernels.flash_attention
    #: config knob / model.attn_impl tuning dimension); "xla" → the
    #: einsum+softmax left to XLA's fuser (at S=512 flash tiling is
    #: roughly break-even — the knob exists so the tuning plane can
    #: measure, not assume)
    attn_impl: str = "xla"

    @property
    def hd(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        d = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                 num_layers=4, num_heads=8, max_seq_len=128)
        d.update(kw)
        return cls(**d)

    @classmethod
    def bert_large(cls, **kw) -> "BertConfig":
        return cls(**kw)

    def num_params(self) -> int:
        H, I, V, L = (self.hidden_size, self.intermediate_size,
                      self.vocab_size, self.num_layers)
        per_layer = 4 * H * H + 4 * H + 2 * H * I + I + H + 4 * H
        embeds = (V + self.max_seq_len + self.type_vocab_size) * H + 2 * H
        return embeds + L * per_layer + H * H + 3 * H + V  # MLM head


def _layer_norm(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray,
                eps: float) -> jnp.ndarray:
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(dt) * w + b


class BertModel:
    """Functional MLM encoder: pure forward, params as a plain pytree."""

    aux_loss_coef: float = 0.0

    def __init__(self, config: BertConfig, mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh
        #: random-LTD state, assigned by the engine from the
        #: ``data_efficiency.data_routing.random_ltd`` config: middle
        #: layers process ``ltd_keep`` randomly-selected tokens (None →
        #: off).  BERT's learned ABSOLUTE position embeddings are added at
        #: embedding time, so gathering tokens is exact — no RoPE
        #: re-indexing problem (why the reference's random-LTD showcase is
        #: BERT/GPT2-era models, arXiv 2211.11586)
        self.ltd_keep: Optional[int] = None
        self.ltd_layer_ids: tuple = ()

    # ------------------------------------------------------------------

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        c = self.config
        H, I, V, L = (c.hidden_size, c.intermediate_size, c.vocab_size,
                      c.num_layers)
        nh, hd = c.num_heads, c.hd
        k = iter(jax.random.split(rng, 16))

        def normal(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    * (1.0 / np.sqrt(fan_in))).astype(jnp.float32)

        return {
            "embed": {
                "word": normal(next(k), (V, H), H),
                "position": normal(next(k), (c.max_seq_len, H), H),
                "token_type": normal(next(k), (c.type_vocab_size, H), H),
                "ln_w": jnp.ones((H,), jnp.float32),
                "ln_b": jnp.zeros((H,), jnp.float32),
            },
            "layers": {
                "attn": {
                    "wq": normal(next(k), (L, H, nh, hd), H),
                    "wk": normal(next(k), (L, H, nh, hd), H),
                    "wv": normal(next(k), (L, H, nh, hd), H),
                    "wo": normal(next(k), (L, nh, hd, H), H),
                    "bq": jnp.zeros((L, nh, hd), jnp.float32),
                    "bk": jnp.zeros((L, nh, hd), jnp.float32),
                    "bv": jnp.zeros((L, nh, hd), jnp.float32),
                    "bo": jnp.zeros((L, H), jnp.float32),
                },
                "mlp": {
                    "w_in": normal(next(k), (L, H, I), H),
                    "b_in": jnp.zeros((L, I), jnp.float32),
                    "w_out": normal(next(k), (L, I, H), I),
                    "b_out": jnp.zeros((L, H), jnp.float32),
                },
                "attn_ln_w": jnp.ones((L, H), jnp.float32),
                "attn_ln_b": jnp.zeros((L, H), jnp.float32),
                "mlp_ln_w": jnp.ones((L, H), jnp.float32),
                "mlp_ln_b": jnp.zeros((L, H), jnp.float32),
            },
            "mlm": {  # prediction-head transform; decoder ties to word embed
                "w": normal(next(k), (H, H), H),
                "b": jnp.zeros((H,), jnp.float32),
                "ln_w": jnp.ones((H,), jnp.float32),
                "ln_b": jnp.zeros((H,), jnp.float32),
                "bias": jnp.zeros((V,), jnp.float32),
            },
        }

    def param_specs(self, params: Optional[Any] = None) -> Dict[str, Any]:
        t = AXIS_TENSOR
        return {
            "embed": {"word": P(None, None), "position": P(None, None),
                      "token_type": P(None, None),
                      "ln_w": P(None), "ln_b": P(None)},
            "layers": {
                "attn": {
                    "wq": P(None, None, t, None), "wk": P(None, None, t, None),
                    "wv": P(None, None, t, None), "wo": P(None, t, None, None),
                    "bq": P(None, t, None), "bk": P(None, t, None),
                    "bv": P(None, t, None), "bo": P(None, None),
                },
                "mlp": {
                    "w_in": P(None, None, t), "b_in": P(None, t),
                    "w_out": P(None, t, None), "b_out": P(None, None),
                },
                "attn_ln_w": P(None, None), "attn_ln_b": P(None, None),
                "mlp_ln_w": P(None, None), "mlp_ln_b": P(None, None),
            },
            "mlm": {"w": P(None, None), "b": P(None), "ln_w": P(None),
                    "ln_b": P(None), "bias": P(None)},
        }

    # ------------------------------------------------------------------

    def _constrain(self, x: jnp.ndarray, *spec) -> jnp.ndarray:
        if self.mesh is None:
            return x
        from ..parallel.mesh import strip_manual_axes

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, strip_manual_axes(*spec)))

    def encoder_layer(self, lp: Any, x: jnp.ndarray,
                      pad_mask: jnp.ndarray) -> jnp.ndarray:
        """One post-LN encoder block ``[B, S, H] → [B, S, H]``;
        ``pad_mask [B, S]`` True at real tokens."""
        c = self.config
        dt = c.dtype
        q = jnp.einsum("bsH,Hhd->bshd", x, lp["attn"]["wq"].astype(dt)) \
            + lp["attn"]["bq"].astype(dt)
        kk = jnp.einsum("bsH,Hhd->bshd", x, lp["attn"]["wk"].astype(dt)) \
            + lp["attn"]["bk"].astype(dt)
        vv = jnp.einsum("bsH,Hhd->bshd", x, lp["attn"]["wv"].astype(dt)) \
            + lp["attn"]["bv"].astype(dt)
        q = self._constrain(q, DP_AXES, AXIS_SEQ, AXIS_TENSOR, None)
        kk = self._constrain(kk, DP_AXES, AXIS_SEQ, AXIS_TENSOR, None)
        vv = self._constrain(vv, DP_AXES, AXIS_SEQ, AXIS_TENSOR, None)
        if c.attn_impl == "flash":
            # padding rides as segment ids: real tokens are segment 1,
            # pads segment 0, so cross-segment pairs mask out in-kernel.
            # (A pad QUERY then attends only pads where the dense path
            # lets it see real keys — those rows are -100-masked in the
            # loss, and the parity test compares real rows only.)
            from ..ops.pallas.flash_attention import flash_attention

            attn = flash_attention(q, kk, vv, causal=False,
                                   segment_ids=pad_mask.astype(jnp.int32))
        else:
            scale = 1.0 / np.sqrt(c.hd)
            s = jnp.einsum("bqhd,bkhd->bhqk", q,
                           kk).astype(jnp.float32) * scale
            s = jnp.where(pad_mask[:, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(dt)
            attn = jnp.einsum("bhqk,bkhd->bqhd", p, vv)
        out = numerics.probe(
            "attn_out",
            jnp.einsum("bshd,hdH->bsH", attn, lp["attn"]["wo"].astype(dt))
            + lp["attn"]["bo"].astype(dt))
        x = numerics.probe(
            "resid_attn",
            _layer_norm(x + out, lp["attn_ln_w"].astype(dt),
                        lp["attn_ln_b"].astype(dt), c.layer_norm_eps))

        h = jnp.einsum("bsH,HI->bsI", x, lp["mlp"]["w_in"].astype(dt)) \
            + lp["mlp"]["b_in"].astype(dt)
        from ..compression.quantization import maybe_quantize_activation

        h = maybe_quantize_activation(self, jax.nn.gelu(h, approximate=False))
        h = self._constrain(h, DP_AXES, AXIS_SEQ, AXIS_TENSOR)
        h = numerics.probe(
            "mlp_out",
            jnp.einsum("bsI,IH->bsH", h, lp["mlp"]["w_out"].astype(dt))
            + lp["mlp"]["b_out"].astype(dt))
        x = numerics.probe(
            "resid_ffn",
            _layer_norm(x + h, lp["mlp_ln_w"].astype(dt),
                        lp["mlp_ln_b"].astype(dt), c.layer_norm_eps))
        return self._constrain(x, DP_AXES, AXIS_SEQ, None)

    def forward(self, params: Any, input_ids: jnp.ndarray,
                attention_mask: Optional[jnp.ndarray] = None,
                token_type_ids: Optional[jnp.ndarray] = None,
                ltd_step: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """[B, S] ids → [B, S, V] MLM logits (fp32)."""
        c = self.config
        dt = c.dtype
        B, S = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), bool)
        else:
            attention_mask = attention_mask.astype(bool)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((B, S), jnp.int32)
        e = params["embed"]
        x = (jnp.take(e["word"].astype(dt), input_ids, axis=0)
             + e["position"].astype(dt)[None, :S]
             + jnp.take(e["token_type"].astype(dt), token_type_ids, axis=0))
        x = _layer_norm(x, e["ln_w"].astype(dt), e["ln_b"].astype(dt),
                        c.layer_norm_eps)
        x = numerics.probe("embed",
                           self._constrain(x, DP_AXES, AXIS_SEQ, None))

        keep = self.ltd_keep
        ltd_on = (keep is not None and 0 < keep < S
                  and len(self.ltd_layer_ids) > 0)
        if ltd_on:
            from ..runtime.data_pipeline.random_ltd import random_ltd_apply

            # selection rng: content + step keyed (the engine threads the
            # step in as the ``_step`` batch leaf) — a revisited sample
            # drops a FRESH token subset each epoch, matching the
            # reference's per-step selection
            base_rng = jax.random.fold_in(
                jax.random.PRNGKey(17),
                jnp.sum(input_ids).astype(jnp.uint32))
            if ltd_step is not None:
                base_rng = jax.random.fold_in(
                    base_rng, ltd_step.reshape(-1)[0].astype(jnp.uint32))
            is_ltd = jnp.asarray([i in self.ltd_layer_ids
                                  for i in range(c.num_layers)])

            def ltd_layer(lp, x, rng):
                return random_ltd_apply(
                    lambda sub, sub_mask: self.encoder_layer(lp, sub,
                                                             sub_mask),
                    x, keep, rng, mask=attention_mask)

            def layer(carry, xs):
                x, i = carry
                lp, flag = xs
                nx = jax.lax.cond(
                    flag,
                    lambda: ltd_layer(lp, x, jax.random.fold_in(base_rng, i)),
                    lambda: self.encoder_layer(lp, x, attention_mask))
                return (nx, i + 1), None

            body = layer
            if c.remat:
                body = jax.checkpoint(
                    layer,
                    policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            # numerics probes stay OFF through the LTD trunk: the
            # per-layer lax.cond routing would trap their stat tracers
            # inside branch scopes
            with numerics.suppressed():
                (x, _), _ = jax.lax.scan(body, (x, jnp.int32(0)),
                                         (params["layers"], is_ltd))
        else:
            def layer(carry, lp):
                mark = numerics.scan_mark()
                x = self.encoder_layer(lp, carry, attention_mask)
                return x, numerics.scan_drain(mark)

            body = layer
            if c.remat:
                body = jax.checkpoint(
                    layer,
                    policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            x, ys = jax.lax.scan(lambda carry, lp: body(carry, lp), x,
                                 params["layers"])
            numerics.scan_collect(ys)

        m = params["mlm"]
        h = jax.nn.gelu(jnp.einsum("bsH,HG->bsG", x, m["w"].astype(dt))
                        + m["b"].astype(dt), approximate=False)
        h = _layer_norm(h, m["ln_w"].astype(dt), m["ln_b"].astype(dt),
                        c.layer_norm_eps)
        logits = (jnp.einsum("bsH,VH->bsV", h, e["word"].astype(dt))
                  + m["bias"])
        return numerics.probe("mlm_logits", logits.astype(jnp.float32))

    __call__ = forward

    def loss(self, params: Any, batch: Any) -> jnp.ndarray:
        """Masked-LM cross entropy; ``batch = {"input_ids", "labels"[, "
        attention_mask", "token_type_ids"]}`` with -100 = not masked."""
        input_ids = batch["input_ids"]
        labels = batch["labels"]
        logits = self.forward(params, input_ids,
                              batch.get("attention_mask"),
                              batch.get("token_type_ids"),
                              ltd_step=batch.get("_step"))
        from .llama import masked_cross_entropy

        return masked_cross_entropy(logits, labels)
