"""HF checkpoint import — map Hugging Face weights into the model zoo
(Llama, Mistral, Mixtral, OPT, BERT).

Capability anchor: reference users bring HF torch models directly
(``deepspeed.initialize(model=hf_model)``); this build's engine consumes
functional param pytrees instead, so checkpoint-level import is the parity
surface (SURVEY §7 hard-part 4: "HF-model story without torch").

The mapping is layout-only — HF stores ``[out, in]`` projection matrices
per layer; this zoo stores stacked ``[L, in, heads, head_dim]`` tensors so
``lax.scan`` consumes one leaf per weight.  RoPE conventions agree (both
use the GPT-NeoX half-split rotation), so no permutation is needed beyond
the reshape/transpose.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig


def _to_np(t: Any) -> np.ndarray:
    """torch tensor / np array → fp32 numpy without importing torch here."""
    if hasattr(t, "detach"):
        t = t.detach().float().cpu().numpy()
    return np.asarray(t, np.float32)


def _getter(hf_config: Any):
    """Uniform key access over an HF config object or a config.json dict."""
    return (hf_config.get if isinstance(hf_config, dict)
            else lambda k, d=None: getattr(hf_config, k, d))


def _load(model_name_or_path: str, config_fn, params_fn, model_cls=None,
          **config_overrides):
    """Shared load pipeline: AutoConfig → zoo config → from_pretrained →
    state-dict mapping.  ``transformers`` (torch CPU) handles safetensors
    and sharded bins uniformly."""
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(model_name_or_path)
    config = config_fn(hf_cfg, **config_overrides)
    model = (model_cls or AutoModelForCausalLM).from_pretrained(
        model_name_or_path)
    try:
        params = params_fn(model.state_dict(), config)
    finally:
        del model
    return config, params


def config_from_hf(hf_config: Any, **overrides) -> LlamaConfig:
    """Build a :class:`LlamaConfig` from an HF ``LlamaConfig`` object or a
    plain dict (``config.json`` contents)."""
    get = _getter(hf_config)
    d = dict(
        vocab_size=int(get("vocab_size")),
        hidden_size=int(get("hidden_size")),
        intermediate_size=int(get("intermediate_size")),
        num_layers=int(get("num_hidden_layers")),
        num_heads=int(get("num_attention_heads")),
        num_kv_heads=int(get("num_key_value_heads",
                             get("num_attention_heads"))),
        max_seq_len=int(get("max_position_embeddings", 4096)),
        rope_theta=float(get("rope_theta", 10000.0)),
        rms_norm_eps=float(get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
    )
    hd = get("head_dim")
    if hd is not None and int(hd) != d["hidden_size"] // d["num_heads"]:
        d["head_dim"] = int(hd)
    sw = get("sliding_window")
    if sw is not None:
        d["sliding_window"] = int(sw)
    d.update(overrides)
    return LlamaConfig(**d)


def params_from_hf_state_dict(state_dict: Dict[str, Any],
                              config: LlamaConfig) -> Dict[str, Any]:
    """HF ``LlamaForCausalLM`` state dict → this zoo's stacked param pytree."""
    c = config
    H, L = c.hidden_size, c.num_layers
    nh, nkv, hd = c.num_heads, c.num_kv_heads, c.hd

    def w(name):
        key = f"model.layers.{{i}}.{name}.weight"
        return [_to_np(state_dict[key.format(i=i)]) for i in range(L)]

    # HF proj weights are [out, in]; ours are [in, ...out-structured]
    wq = np.stack([m.T.reshape(H, nh, hd) for m in w("self_attn.q_proj")])
    wk = np.stack([m.T.reshape(H, nkv, hd) for m in w("self_attn.k_proj")])
    wv = np.stack([m.T.reshape(H, nkv, hd) for m in w("self_attn.v_proj")])
    wo = np.stack([m.T.reshape(nh, hd, H) for m in w("self_attn.o_proj")])
    w_gate = np.stack([m.T for m in w("mlp.gate_proj")])
    w_up = np.stack([m.T for m in w("mlp.up_proj")])
    w_down = np.stack([m.T for m in w("mlp.down_proj")])
    attn_norm = np.stack(w("input_layernorm"))
    mlp_norm = np.stack(w("post_attention_layernorm"))

    params = {
        "embed": _to_np(state_dict["model.embed_tokens.weight"]),
        "layers": {
            "attn": {"wq": jnp.asarray(wq), "wk": jnp.asarray(wk),
                     "wv": jnp.asarray(wv), "wo": jnp.asarray(wo)},
            "mlp": {"w_gate": jnp.asarray(w_gate),
                    "w_up": jnp.asarray(w_up),
                    "w_down": jnp.asarray(w_down)},
            "attn_norm": jnp.asarray(attn_norm),
            "mlp_norm": jnp.asarray(mlp_norm),
        },
        "final_norm": jnp.asarray(_to_np(state_dict["model.norm.weight"])),
    }
    params["embed"] = jnp.asarray(params["embed"])
    if not c.tie_embeddings:
        key = ("lm_head.weight" if "lm_head.weight" in state_dict
               else "model.embed_tokens.weight")
        params["lm_head"] = jnp.asarray(_to_np(state_dict[key]).T)
    return params


def load_hf_llama(model_name_or_path: str, **config_overrides
                  ) -> Tuple[LlamaConfig, Dict[str, Any]]:
    """Load an HF Llama checkpoint directory into (config, params).

    Uses ``transformers`` (torch CPU) for robust format handling —
    safetensors and sharded bins both resolve through ``from_pretrained``.
    """
    return _load(model_name_or_path, config_from_hf,
                 params_from_hf_state_dict, **config_overrides)


# ---------------------------------------------------------------------------
# Mistral — same layout as Llama (HF MistralForCausalLM shares the module
# names), plus the sliding-window config key
# ---------------------------------------------------------------------------

def load_hf_mistral(model_name_or_path: str, **config_overrides
                    ) -> Tuple[LlamaConfig, Dict[str, Any]]:
    """HF Mistral checkpoint → (LlamaConfig-with-window, params).  The zoo
    serves Mistral through :class:`LlamaModel` (sliding_window set)."""
    return _load(model_name_or_path, config_from_hf,
                 params_from_hf_state_dict, **config_overrides)


# ---------------------------------------------------------------------------
# Mixtral — Llama attention + block-sparse MoE experts
# ---------------------------------------------------------------------------

def config_from_hf_mixtral(hf_config: Any, **overrides):
    from .mixtral import MixtralConfig

    get = _getter(hf_config)
    d = dict(
        vocab_size=int(get("vocab_size")),
        hidden_size=int(get("hidden_size")),
        intermediate_size=int(get("intermediate_size")),
        num_layers=int(get("num_hidden_layers")),
        num_heads=int(get("num_attention_heads")),
        num_kv_heads=int(get("num_key_value_heads",
                             get("num_attention_heads"))),
        max_seq_len=int(get("max_position_embeddings", 4096)),
        rope_theta=float(get("rope_theta", 10000.0)),
        rms_norm_eps=float(get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
        num_experts=int(get("num_local_experts", 8)),
        top_k=int(get("num_experts_per_tok", 2)),
    )
    d.update(overrides)
    return MixtralConfig(**d)


def params_from_hf_mixtral_state_dict(state_dict: Dict[str, Any],
                                      config: Any) -> Dict[str, Any]:
    """HF ``MixtralForCausalLM`` state dict → stacked params: the dense
    Llama attention mapping plus ``moe`` (router + expert-stacked FFN;
    HF per-expert w1/w3/w2 = gate/up/down, each ``[I, H]``/``[H, I]``)."""
    c = config
    H, L, E = c.hidden_size, c.num_layers, c.num_experts
    nh, nkv, hd = c.num_heads, c.num_kv_heads, c.hd

    def w(name):
        key = f"model.layers.{{i}}.{name}.weight"
        return [_to_np(state_dict[key.format(i=i)]) for i in range(L)]

    wq = np.stack([m.T.reshape(H, nh, hd) for m in w("self_attn.q_proj")])
    wk = np.stack([m.T.reshape(H, nkv, hd) for m in w("self_attn.k_proj")])
    wv = np.stack([m.T.reshape(H, nkv, hd) for m in w("self_attn.v_proj")])
    wo = np.stack([m.T.reshape(nh, hd, H) for m in w("self_attn.o_proj")])
    wg = np.stack([m.T for m in w("block_sparse_moe.gate")])  # [L, H, E]

    def experts(proj):
        out = []
        for i in range(L):
            per = [_to_np(state_dict[
                f"model.layers.{i}.block_sparse_moe.experts.{e}."
                f"{proj}.weight"]).T for e in range(E)]
            out.append(np.stack(per))
        return np.stack(out)

    params = {
        "embed": jnp.asarray(_to_np(state_dict["model.embed_tokens.weight"])),
        "layers": {
            "attn": {"wq": jnp.asarray(wq), "wk": jnp.asarray(wk),
                     "wv": jnp.asarray(wv), "wo": jnp.asarray(wo)},
            "moe": {
                "wg": jnp.asarray(wg),
                "w_gate": jnp.asarray(experts("w1")),  # [L, E, H, I]
                "w_up": jnp.asarray(experts("w3")),    # [L, E, H, I]
                "w_down": jnp.asarray(experts("w2")),  # [L, E, I, H]
            },
            "attn_norm": jnp.asarray(np.stack(w("input_layernorm"))),
            "mlp_norm": jnp.asarray(np.stack(w("post_attention_layernorm"))),
        },
        "final_norm": jnp.asarray(_to_np(state_dict["model.norm.weight"])),
    }
    if not c.tie_embeddings:
        key = ("lm_head.weight" if "lm_head.weight" in state_dict
               else "model.embed_tokens.weight")
        params["lm_head"] = jnp.asarray(_to_np(state_dict[key]).T)
    return params


def load_hf_mixtral(model_name_or_path: str, **config_overrides):
    return _load(model_name_or_path, config_from_hf_mixtral,
                 params_from_hf_mixtral_state_dict, **config_overrides)


# ---------------------------------------------------------------------------
# OPT — pre-LN decoder with learned positions (HF offset-2 table maps 1:1)
# ---------------------------------------------------------------------------

def config_from_hf_opt(hf_config: Any, **overrides):
    from .opt import OPTConfig

    get = _getter(hf_config)
    if get("do_layer_norm_before", True) is False:
        raise NotImplementedError(
            "this OPT implementation is pre-LN; post-LN variants "
            "(do_layer_norm_before=false, e.g. opt-350m) are not supported")
    proj = get("word_embed_proj_dim")
    if proj is not None and int(proj) != int(get("hidden_size")):
        raise NotImplementedError(
            f"word_embed_proj_dim {proj} != hidden_size "
            f"{get('hidden_size')} (project_in/out variants like opt-350m "
            "are not supported)")
    d = dict(
        vocab_size=int(get("vocab_size")),
        hidden_size=int(get("hidden_size")),
        ffn_dim=int(get("ffn_dim")),
        num_layers=int(get("num_hidden_layers")),
        num_heads=int(get("num_attention_heads")),
        max_seq_len=int(get("max_position_embeddings", 2048)),
    )
    d.update(overrides)
    return OPTConfig(**d)


def params_from_hf_opt_state_dict(state_dict: Dict[str, Any],
                                  config: Any) -> Dict[str, Any]:
    """HF ``OPTForCausalLM`` state dict → stacked params.  HF's learned
    position table already carries the legacy offset-2 rows, matching this
    zoo's ``POSITION_OFFSET`` layout row-for-row."""
    c = config
    H, L = c.hidden_size, c.num_layers
    nh, hd = c.num_heads, c.hd
    pre = "model.decoder."

    def w(name):
        return [_to_np(state_dict[f"{pre}layers.{i}.{name}.weight"])
                for i in range(L)]

    def b(name):
        return [_to_np(state_dict[f"{pre}layers.{i}.{name}.bias"])
                for i in range(L)]

    return {
        "embed": jnp.asarray(_to_np(state_dict[pre + "embed_tokens.weight"])),
        "pos_embed": jnp.asarray(
            _to_np(state_dict[pre + "embed_positions.weight"])),
        "layers": {
            "attn": {
                "wq": jnp.asarray(np.stack(
                    [m.T.reshape(H, nh, hd) for m in w("self_attn.q_proj")])),
                "wk": jnp.asarray(np.stack(
                    [m.T.reshape(H, nh, hd) for m in w("self_attn.k_proj")])),
                "wv": jnp.asarray(np.stack(
                    [m.T.reshape(H, nh, hd) for m in w("self_attn.v_proj")])),
                "wo": jnp.asarray(np.stack(
                    [m.T.reshape(nh, hd, H)
                     for m in w("self_attn.out_proj")])),
                "bq": jnp.asarray(np.stack(
                    [v.reshape(nh, hd) for v in b("self_attn.q_proj")])),
                "bk": jnp.asarray(np.stack(
                    [v.reshape(nh, hd) for v in b("self_attn.k_proj")])),
                "bv": jnp.asarray(np.stack(
                    [v.reshape(nh, hd) for v in b("self_attn.v_proj")])),
                "bo": jnp.asarray(np.stack(b("self_attn.out_proj"))),
            },
            "mlp": {
                "w_in": jnp.asarray(np.stack([m.T for m in w("fc1")])),
                "b_in": jnp.asarray(np.stack(b("fc1"))),
                "w_out": jnp.asarray(np.stack([m.T for m in w("fc2")])),
                "b_out": jnp.asarray(np.stack(b("fc2"))),
            },
            "attn_ln_w": jnp.asarray(np.stack(w("self_attn_layer_norm"))),
            "attn_ln_b": jnp.asarray(np.stack(b("self_attn_layer_norm"))),
            "mlp_ln_w": jnp.asarray(np.stack(w("final_layer_norm"))),
            "mlp_ln_b": jnp.asarray(np.stack(b("final_layer_norm"))),
        },
        "final_ln_w": jnp.asarray(
            _to_np(state_dict[pre + "final_layer_norm.weight"])),
        "final_ln_b": jnp.asarray(
            _to_np(state_dict[pre + "final_layer_norm.bias"])),
    }


def load_hf_opt(model_name_or_path: str, **config_overrides):
    return _load(model_name_or_path, config_from_hf_opt,
                 params_from_hf_opt_state_dict, **config_overrides)


# ---------------------------------------------------------------------------
# BERT — post-LN encoder + tied MLM head
# ---------------------------------------------------------------------------

def config_from_hf_bert(hf_config: Any, **overrides):
    from .bert import BertConfig

    get = _getter(hf_config)
    d = dict(
        vocab_size=int(get("vocab_size")),
        hidden_size=int(get("hidden_size")),
        intermediate_size=int(get("intermediate_size")),
        num_layers=int(get("num_hidden_layers")),
        num_heads=int(get("num_attention_heads")),
        max_seq_len=int(get("max_position_embeddings", 512)),
        type_vocab_size=int(get("type_vocab_size", 2)),
        layer_norm_eps=float(get("layer_norm_eps", 1e-12)),
    )
    d.update(overrides)
    return BertConfig(**d)


def params_from_hf_bert_state_dict(state_dict: Dict[str, Any],
                                   config: Any) -> Dict[str, Any]:
    """HF ``BertForMaskedLM`` state dict → stacked params (post-LN:
    ``attention.output.LayerNorm``/``output.LayerNorm`` land on the
    post-residual norms; the MLM decoder is tied to the word embedding,
    with its standalone bias imported)."""
    c = config
    H, L = c.hidden_size, c.num_layers
    nh, hd = c.num_heads, c.hd
    enc = "bert.encoder.layer.{i}."

    def w(name):
        return [_to_np(state_dict[(enc + name + ".weight").format(i=i)])
                for i in range(L)]

    def b(name):
        return [_to_np(state_dict[(enc + name + ".bias").format(i=i)])
                for i in range(L)]

    emb = "bert.embeddings."
    return {
        "embed": {
            "word": jnp.asarray(
                _to_np(state_dict[emb + "word_embeddings.weight"])),
            "position": jnp.asarray(
                _to_np(state_dict[emb + "position_embeddings.weight"])),
            "token_type": jnp.asarray(
                _to_np(state_dict[emb + "token_type_embeddings.weight"])),
            "ln_w": jnp.asarray(_to_np(state_dict[emb + "LayerNorm.weight"])),
            "ln_b": jnp.asarray(_to_np(state_dict[emb + "LayerNorm.bias"])),
        },
        "layers": {
            "attn": {
                "wq": jnp.asarray(np.stack(
                    [m.T.reshape(H, nh, hd)
                     for m in w("attention.self.query")])),
                "wk": jnp.asarray(np.stack(
                    [m.T.reshape(H, nh, hd)
                     for m in w("attention.self.key")])),
                "wv": jnp.asarray(np.stack(
                    [m.T.reshape(H, nh, hd)
                     for m in w("attention.self.value")])),
                "wo": jnp.asarray(np.stack(
                    [m.T.reshape(nh, hd, H)
                     for m in w("attention.output.dense")])),
                "bq": jnp.asarray(np.stack(
                    [v.reshape(nh, hd) for v in b("attention.self.query")])),
                "bk": jnp.asarray(np.stack(
                    [v.reshape(nh, hd) for v in b("attention.self.key")])),
                "bv": jnp.asarray(np.stack(
                    [v.reshape(nh, hd) for v in b("attention.self.value")])),
                "bo": jnp.asarray(np.stack(b("attention.output.dense"))),
            },
            "mlp": {
                "w_in": jnp.asarray(np.stack(
                    [m.T for m in w("intermediate.dense")])),
                "b_in": jnp.asarray(np.stack(b("intermediate.dense"))),
                "w_out": jnp.asarray(np.stack(
                    [m.T for m in w("output.dense")])),
                "b_out": jnp.asarray(np.stack(b("output.dense"))),
            },
            "attn_ln_w": jnp.asarray(np.stack(
                w("attention.output.LayerNorm"))),
            "attn_ln_b": jnp.asarray(np.stack(
                b("attention.output.LayerNorm"))),
            "mlp_ln_w": jnp.asarray(np.stack(w("output.LayerNorm"))),
            "mlp_ln_b": jnp.asarray(np.stack(b("output.LayerNorm"))),
        },
        "mlm": {
            "w": jnp.asarray(_to_np(
                state_dict["cls.predictions.transform.dense.weight"]).T),
            "b": jnp.asarray(_to_np(
                state_dict["cls.predictions.transform.dense.bias"])),
            "ln_w": jnp.asarray(_to_np(
                state_dict["cls.predictions.transform.LayerNorm.weight"])),
            "ln_b": jnp.asarray(_to_np(
                state_dict["cls.predictions.transform.LayerNorm.bias"])),
            "bias": jnp.asarray(_to_np(state_dict["cls.predictions.bias"])),
        },
    }


def load_hf_bert(model_name_or_path: str, **config_overrides):
    from transformers import BertForMaskedLM

    return _load(model_name_or_path, config_from_hf_bert,
                 params_from_hf_bert_state_dict, model_cls=BertForMaskedLM,
                 **config_overrides)
