"""HF checkpoint import — map Hugging Face Llama weights into the model zoo.

Capability anchor: reference users bring HF torch models directly
(``deepspeed.initialize(model=hf_model)``); this build's engine consumes
functional param pytrees instead, so checkpoint-level import is the parity
surface (SURVEY §7 hard-part 4: "HF-model story without torch").

The mapping is layout-only — HF stores ``[out, in]`` projection matrices
per layer; this zoo stores stacked ``[L, in, heads, head_dim]`` tensors so
``lax.scan`` consumes one leaf per weight.  RoPE conventions agree (both
use the GPT-NeoX half-split rotation), so no permutation is needed beyond
the reshape/transpose.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig


def _to_np(t: Any) -> np.ndarray:
    """torch tensor / np array → fp32 numpy without importing torch here."""
    if hasattr(t, "detach"):
        t = t.detach().float().cpu().numpy()
    return np.asarray(t, np.float32)


def config_from_hf(hf_config: Any, **overrides) -> LlamaConfig:
    """Build a :class:`LlamaConfig` from an HF ``LlamaConfig`` object or a
    plain dict (``config.json`` contents)."""
    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    d = dict(
        vocab_size=int(get("vocab_size")),
        hidden_size=int(get("hidden_size")),
        intermediate_size=int(get("intermediate_size")),
        num_layers=int(get("num_hidden_layers")),
        num_heads=int(get("num_attention_heads")),
        num_kv_heads=int(get("num_key_value_heads",
                             get("num_attention_heads"))),
        max_seq_len=int(get("max_position_embeddings", 4096)),
        rope_theta=float(get("rope_theta", 10000.0)),
        rms_norm_eps=float(get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
    )
    hd = get("head_dim")
    if hd is not None and int(hd) != d["hidden_size"] // d["num_heads"]:
        d["head_dim"] = int(hd)
    d.update(overrides)
    return LlamaConfig(**d)


def params_from_hf_state_dict(state_dict: Dict[str, Any],
                              config: LlamaConfig) -> Dict[str, Any]:
    """HF ``LlamaForCausalLM`` state dict → this zoo's stacked param pytree."""
    c = config
    H, L = c.hidden_size, c.num_layers
    nh, nkv, hd = c.num_heads, c.num_kv_heads, c.hd

    def w(name):
        key = f"model.layers.{{i}}.{name}.weight"
        return [_to_np(state_dict[key.format(i=i)]) for i in range(L)]

    # HF proj weights are [out, in]; ours are [in, ...out-structured]
    wq = np.stack([m.T.reshape(H, nh, hd) for m in w("self_attn.q_proj")])
    wk = np.stack([m.T.reshape(H, nkv, hd) for m in w("self_attn.k_proj")])
    wv = np.stack([m.T.reshape(H, nkv, hd) for m in w("self_attn.v_proj")])
    wo = np.stack([m.T.reshape(nh, hd, H) for m in w("self_attn.o_proj")])
    w_gate = np.stack([m.T for m in w("mlp.gate_proj")])
    w_up = np.stack([m.T for m in w("mlp.up_proj")])
    w_down = np.stack([m.T for m in w("mlp.down_proj")])
    attn_norm = np.stack(w("input_layernorm"))
    mlp_norm = np.stack(w("post_attention_layernorm"))

    params = {
        "embed": _to_np(state_dict["model.embed_tokens.weight"]),
        "layers": {
            "attn": {"wq": jnp.asarray(wq), "wk": jnp.asarray(wk),
                     "wv": jnp.asarray(wv), "wo": jnp.asarray(wo)},
            "mlp": {"w_gate": jnp.asarray(w_gate),
                    "w_up": jnp.asarray(w_up),
                    "w_down": jnp.asarray(w_down)},
            "attn_norm": jnp.asarray(attn_norm),
            "mlp_norm": jnp.asarray(mlp_norm),
        },
        "final_norm": jnp.asarray(_to_np(state_dict["model.norm.weight"])),
    }
    params["embed"] = jnp.asarray(params["embed"])
    if not c.tie_embeddings:
        key = ("lm_head.weight" if "lm_head.weight" in state_dict
               else "model.embed_tokens.weight")
        params["lm_head"] = jnp.asarray(_to_np(state_dict[key]).T)
    return params


def load_hf_llama(model_name_or_path: str, **config_overrides
                  ) -> Tuple[LlamaConfig, Dict[str, Any]]:
    """Load an HF Llama checkpoint directory into (config, params).

    Uses ``transformers`` (torch CPU) for robust format handling —
    safetensors and sharded bins both resolve through ``from_pretrained``.
    """
    from transformers import AutoConfig, LlamaForCausalLM

    hf_cfg = AutoConfig.from_pretrained(model_name_or_path)
    config = config_from_hf(hf_cfg, **config_overrides)
    model = LlamaForCausalLM.from_pretrained(model_name_or_path)
    try:
        params = params_from_hf_state_dict(model.state_dict(), config)
    finally:
        del model
    return config, params
