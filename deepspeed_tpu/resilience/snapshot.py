"""Tiered async snapshots of the FULL training state.

The recovery half of a production training stack (ISSUE 4 tentpole,
pillar 1).  Checkpoints answer "resume tomorrow"; snapshots answer
"lose at most ``snapshot_interval`` steps to a NaN, a kill -9, or a
host loss".  Three tiers, each a strictly cheaper/closer copy:

* **tier 0 — host memory**: a double-buffered ``jax.device_get`` of the
  whole :class:`~..runtime.engine.TrainState` (params, optimizer state,
  loss-scale, step, comm residuals) plus engine bookkeeping
  (global/micro steps, LR-scheduler state, registered data-sampler
  cursors, host RNG states).  Rollback from tier 0 is a ``device_put``
  — milliseconds, no storage round-trip.
* **tier 1 — local disk**: the tier-0 copy flushed through
  ``runtime/checkpoint_engine.py`` (async by default: the WHOLE job —
  serialize, hash, commit, replicate, prune — runs on one background
  worker thread over the already-taken immutable host copy, so the step
  path never blocks on storage).  Every flush commits a
  ``snapshot.json`` marker ONLY after the checksummed sidecar manifest
  is durable — restores are checksum-gated, torn flushes are invisible.
* **tier 2 — off-host replica, peer-to-peer**: the flushed snapshot dir
  is served by this node's :class:`~.replica_server.ReplicaServer` and
  PUSHED to the NEXT node in the sealed ring (the "buddy", the expected
  adopter), which holds a physical copy on its own disk and serves it
  too.  The rendezvous store carries only **index/placement metadata**
  (``resil/pub/<node>``: tag, bytes, sha256, holder endpoints) — never
  snapshot bytes — and that metadata is write-journaled, so a killed
  store neither destroys the tier nor forgets where the replicas live:
  adoption and scale-up bootstrap fetch from a holder peer through the
  same transport checksum gate.

The manager is engine-owned (``engine.snapshots``) and driven from
``train_step`` (:meth:`maybe_snapshot`); the recovery policy
(``policy.py``) consumes :meth:`latest`, :meth:`restore`, and the
module-level :func:`choose_resume_snapshot` tier-fallback.
"""

from __future__ import annotations

import json
import os
import pickle
import random
import shutil
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime.checkpoint_engine import (CheckpointCorruptionError,
                                         TorchCheckpointEngine,
                                         verify_sidecar_manifest)
from ..utils.logging import log_dist, logger

#: per-snapshot commit marker (meta + "the flush completed durably")
SNAPSHOT_MANIFEST = "snapshot.json"


class SnapshotUnsupportedError(RuntimeError):
    """Tiered snapshots cannot cover this engine's state.

    Raised by :func:`check_snapshot_support` when part of the training
    state lives outside the on-device TrainState (ZeRO-Offload / ZeRO-
    Infinity keep optimizer masters host-side in their own engines).
    The engine catches this and DEGRADES — logs once, disables
    snapshots/recovery, keeps training — instead of refusing to start
    (ROADMAP item 5: real snapshot support for those engines is the
    follow-up; until then a running job beats an error)."""


def check_snapshot_support(engine: Any) -> None:
    """Raise :class:`SnapshotUnsupportedError` naming the engine and the
    workaround when tiered snapshots cannot capture its full state."""
    if getattr(engine, "infinity", None) is not None:
        raise SnapshotUnsupportedError(
            "resilience snapshots cover the on-device TrainState, but "
            "ZeRO-Infinity streams trunk params and keeps optimizer "
            "masters in per-layer host/NVMe planes outside it — a "
            "snapshot would silently miss them.  Workaround: rely on "
            "ordinary checkpoints (save_checkpoint covers Infinity "
            "state), or disable offload_param/Infinity to get tiered "
            "snapshots.  (ROADMAP item 5 tracks native support.)")
    if getattr(engine, "offload_enabled", False):
        raise SnapshotUnsupportedError(
            "resilience snapshots cover the on-device TrainState, but "
            "ZeRO-Offload keeps fp32 masters and moments host-side in "
            "the C++ optimizer — a snapshot would capture stale device "
            "params and no optimizer state.  Workaround: rely on "
            "ordinary checkpoints (save_checkpoint covers offload "
            "state), or disable offload_optimizer to get tiered "
            "snapshots.  (ROADMAP item 5 tracks native support.)")
#: tier-2 store keys: INDEX/placement metadata only (the bytes live on
#: peers — see replica_server.py).  The chunk prefix remains only for
#: reading replicas published by pre-P2P builds.
RESIL_META_KEY = "resil/pub/{node}"
RESIL_CHUNK_PREFIX = "resil/chunk/{node}"
#: each node's replica-server endpoint (journaled, so a restarted store
#: re-learns the placement map from survivors)
RESIL_SRV_KEY = "resil/srv/{node}"


# ---------------------------------------------------------------------------
# mesh-elastic recovery: origin-topology stamping + reshard compatibility
# ---------------------------------------------------------------------------

def format_topology(topo: Optional[Dict[str, Any]]) -> str:
    """One-line human form of a :func:`~..parallel.mesh.mesh_topology`
    dict, used by :class:`MeshMismatchError` and the operator CLI."""
    if not isinstance(topo, dict):
        return "<unknown mesh>"
    axes = topo.get("axes") or {}
    ax = ",".join(f"{a}={s}" for a, s in axes.items()) or "shape unknown"
    return (f"world={topo.get('world_size', '?')} mesh({ax}) "
            f"device={topo.get('device_kind', '?')} "
            f"processes={topo.get('num_processes', '?')} "
            f"coverage={topo.get('host_coverage', '?')}")


class MeshMismatchError(RuntimeError):
    """A snapshot taken on mesh A cannot serve the engine's current mesh
    B.  Carries both topologies and a per-tier reshardability verdict so
    the 3am operator (and the ``verify --target-mesh`` pre-check) can
    read exactly WHY instead of a device_put shape error deep in
    restore."""

    def __init__(self, origin: Optional[Dict[str, Any]],
                 target: Optional[Dict[str, Any]], reason: str,
                 tiers: Optional[Dict[str, str]] = None):
        self.origin = origin
        self.target = target
        self.reason = reason
        self.tiers = tiers or {}
        tier_s = ("; tiers: " + ", ".join(
            f"{t}: {v}" for t, v in self.tiers.items())) if self.tiers \
            else ""
        super().__init__(
            f"snapshot mesh mismatch — origin {format_topology(origin)} "
            f"cannot serve target {format_topology(target)}: "
            f"{reason}{tier_s}")


def check_reshardable(meta: Dict[str, Any],
                      target: Dict[str, Any]) -> Tuple[bool, str]:
    """Can a snapshot whose manifest ``meta`` names its origin mesh be
    re-laid onto ``target``?  Returns ``(ok, reason)``.

    The state tree a snapshot holds is the GLOBAL logical tree (ZeRO
    shards via shardings, never by reshaping leaves), so resharding is a
    ``device_put`` onto the target's shardings — UNLESS

    * the origin capture only covered this host's shards
      (multi-controller ``host_coverage == "partial"``), or
    * part of the state is shaped BY the world size (the 1-bit
      error-feedback residuals are ``[dp_world, ...]`` per leaf).
    """
    origin = meta.get("mesh") if isinstance(meta.get("mesh"), dict) \
        else None
    if origin is None:
        return True, ("origin topology unknown (pre-reshard snapshot) — "
                      "proceeding as a same-mesh restore")
    same = (origin.get("axes") == target.get("axes")
            and origin.get("world_size") == target.get("world_size"))
    if same:
        return True, "identical topology"
    if origin.get("host_coverage") == "partial":
        return False, (
            f"origin snapshot covers only process "
            f"{origin.get('process_index')}'s shards "
            f"({origin.get('num_processes')} origin processes) — a "
            f"different shape needs every origin host's shards")
    baked = meta.get("world_baked_state") or []
    if baked:
        return False, (
            "state leaves are shaped by the origin world size and cannot "
            "be re-laid: " + "; ".join(baked))
    return True, ("global state tree reshards via device_put onto the "
                  "target mesh's shardings")


def reshard_tier_report(meta: Dict[str, Any],
                        target: Dict[str, Any]) -> Dict[str, str]:
    """Per-tier verdict for :class:`MeshMismatchError` / the CLI: which
    tiers could serve ``target``.  Tier 0/2 hold the same host tree as
    tier 1, so reshardability is uniform — EXCEPT partial coverage,
    where tier 1's per-host trees are exactly the shards that are
    missing."""
    ok, reason = check_reshardable(meta, target)
    verdict = "reshardable" if ok else f"NOT reshardable ({reason})"
    return {"tier0 (host memory)": verdict,
            "tier1 (local disk)": verdict,
            "tier2 (buddy replica)": verdict}


class Snapshot:
    """One tier-0 capture: the host-side state tree + JSON-able meta."""

    __slots__ = ("step", "global_steps", "state", "meta", "ts")

    def __init__(self, step: int, global_steps: int, state: Any,
                 meta: Dict[str, Any]):
        self.step = int(step)              # applied optimizer step
        self.global_steps = int(global_steps)
        self.state = state                 # host numpy TrainState tree
        self.meta = meta
        self.ts = time.time()


def _tag(step: int, emergency: bool = False) -> str:
    return f"snap-{step:08d}" + ("-emergency" if emergency else "")


class SnapshotManager:
    """Engine-driven tiered snapshots.  Hot-path cost: one deque-free
    double buffer write every ``snapshot_interval`` steps; everything
    else (serialization, hashing, replication) is off the step path."""

    def __init__(self, engine: Any, cfg: Any,
                 recorder: Any = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.engine = engine
        self.cfg = cfg
        self.recorder = recorder
        self._clock = clock
        self.snapshot_interval = max(1, int(cfg.snapshot_interval))
        self.snapshot_dir = cfg.snapshot_dir
        self.keep = max(1, int(cfg.keep_snapshots))
        # tier 0: double buffer — the newest capture never overwrites
        # the previous one in place, so a crash MID-capture still leaves
        # one intact copy
        self._buffers: List[Optional[Snapshot]] = [None, None]
        self._active = 0
        #: name -> (capture_fn() -> jsonable, restore_fn(payload)) for
        #: state the engine doesn't own (data-sampler cursors, user
        #: counters); registered by entry.initialize / user code
        self._meta_hooks: Dict[str, Tuple[Callable[[], Any],
                                          Optional[Callable[[Any], None]]]] \
            = {}
        #: async = the WHOLE tier-1 job (serialize, hash, commit,
        #: replicate, prune) runs on one background worker thread; the
        #: step path only pays the already-taken host copy.  Each flush
        #: uses its own throwaway sync engine, so the emergency path
        #: never races a shared engine's pending state.
        self._async = str(cfg.flush_engine) == "async"
        self._flush_pool = None
        self._pending_flush = None
        #: tier-2 plumbing, attached when an elastic rendezvous exists
        self._rdzv = None
        self.snapshots_taken = 0
        self.flushes = 0

    # -- registration ------------------------------------------------------

    def register_meta(self, name: str, capture: Callable[[], Any],
                      restore: Optional[Callable[[Any], None]] = None
                      ) -> None:
        """Attach a named (capture, restore) hook: ``capture()`` is
        folded into every snapshot's meta under ``extras[name]``;
        ``restore(payload)`` (optional) runs on rollback/resume."""
        self._meta_hooks[name] = (capture, restore)

    def attach_rendezvous(self, rdzv: Any) -> None:
        """Enable tier 2 against this elastic rendezvous: its sealed
        ring names the buddy, its client carries the INDEX metadata.
        With the buddy tier on, this also starts (or joins) the
        process-local replica server and publishes its endpoint — a
        journaled write, so a restarted store re-learns the placement
        map from the survivors."""
        self._rdzv = rdzv
        if not self.cfg.buddy_tier or rdzv is None:
            return
        try:
            from .replica_server import get_local_server

            server = get_local_server(
                create=True,
                base_dir=os.path.join(self.snapshot_dir, "replica_store"),
                chunk_bytes=self.cfg.buddy_chunk_bytes,
                max_bytes=self.cfg.buddy_max_bytes)
            rdzv.c.set(RESIL_SRV_KEY.format(node=rdzv.node_id),
                       server.endpoint, journal=True)
        except Exception as e:
            # tier 2 degrades to owner-only serving; tiers 0/1 are whole
            logger.warning(f"resilience: replica server start/publish "
                           f"failed: {e!r}")

    # -- capture (tier 0) --------------------------------------------------

    def _collect_meta(self) -> Dict[str, Any]:
        eng = self.engine
        extras: Dict[str, Any] = {}
        for name, (capture, _restore) in self._meta_hooks.items():
            try:
                extras[name] = capture()
            except Exception as e:  # a dead hook must not lose the snapshot
                extras[name] = {"error": repr(e)}
        return {
            "global_steps": int(eng.global_steps),
            "micro_steps": int(eng.micro_steps),
            "lr_scheduler": eng.lr_scheduler.state_dict(),
            "skipped_steps": int(eng.state.skipped_steps),
            "rng": {
                # host RNG driving data order/augmentation; pickled+hex so
                # the tuple structure survives the JSON manifest
                "python_random": pickle.dumps(random.getstate()).hex(),
                "numpy_global": pickle.dumps(np.random.get_state()).hex(),
            },
            "extras": extras,
            **self._origin_meta(),
        }

    def _origin_meta(self) -> Dict[str, Any]:
        """Origin-topology stamp (mesh-elastic recovery): every snapshot
        records the mesh it was taken on, the jax version, the resolved
        global batch, the state leaf layout, and any world-size-baked
        state — everything :func:`check_reshardable` and the offline
        ``verify --target-mesh`` pre-check need."""
        import jax

        from ..parallel.mesh import mesh_topology

        eng = self.engine
        out: Dict[str, Any] = {"jax_version": str(jax.__version__)}
        try:
            out["mesh"] = (eng.mesh_topology()
                           if hasattr(eng, "mesh_topology")
                           else mesh_topology(eng.mesh))
        except Exception as e:  # a stamp failure must not lose the snapshot
            logger.warning(f"resilience: mesh topology stamp failed: {e!r}")
            return out
        tb = getattr(eng, "train_batch_size", None)
        if tb:
            out["train_batch_size"] = int(tb)
        baked = []
        comm_leaves = jax.tree.leaves(
            getattr(eng.state, "comm_state", ()) or ())
        if comm_leaves:
            baked.append(
                "comm_state: 1-bit error-feedback residuals shaped "
                f"[dp_world={np.shape(comm_leaves[0])[0]}, ...] — baked "
                "to the origin DP world")
        out["world_baked_state"] = baked
        # per-leaf (path, shape) inventory: lets the CLI answer "can I
        # resume this on 3 hosts, and which leaves would still shard?"
        # without loading a single byte of state
        try:
            paths = jax.tree_util.tree_flatten_with_path(eng.state)[0]
            out["state_shapes"] = [
                [jax.tree_util.keystr(kp), list(np.shape(leaf))]
                for kp, leaf in paths]
        except Exception as e:
            logger.warning(f"resilience: state shape stamp failed: {e!r}")
        return out

    def take(self, emergency: bool = False) -> Snapshot:
        """Capture tier 0 NOW (device→host copy of the full state) and,
        when the disk tier is on, hand it to the async flusher."""
        import jax

        eng = self.engine
        t0 = self._clock()
        host_state = jax.tree.map(lambda x: np.asarray(jax.device_get(x)),
                                  eng.state)
        snap = Snapshot(step=int(host_state.step),
                        global_steps=eng.global_steps,
                        state=host_state, meta=self._collect_meta())
        # double buffer: write the NON-active slot, then flip
        self._active ^= 1
        self._buffers[self._active] = snap
        self.snapshots_taken += 1
        dt_ms = (self._clock() - t0) * 1e3
        from ..telemetry.memory import get_memory_ledger

        mem = get_memory_ledger()
        if mem.enabled:
            # tier-0 buffers are a full host copy of the TrainState per
            # slot — the biggest host allocation most runs make; keyed
            # per buffer slot so the double buffer accounts as two
            # entries, each replaced in place on reuse
            mem.register_tree(
                "snapshot", f"resilience/tier0_buffer{self._active}",
                host_state, space="host",
                tag=f"tier-0 snapshot (step {snap.global_steps})")
        from ..telemetry import get_telemetry
        from ..telemetry.perf import get_goodput_ledger

        # the device→host capture blocks the step loop: checkpoint time
        # in the goodput account (the async flush that follows does not)
        get_goodput_ledger().add("checkpoint", dt_ms / 1e3)
        tel = get_telemetry()
        tel.inc_counter("resilience/snapshots_total",
                        help="tier-0 training-state snapshots taken")
        tel.set_gauge("resilience/snapshot_last_ms", dt_ms,
                      help="device->host capture latency of the last "
                           "snapshot")
        tel.set_gauge("resilience/snapshot_last_step", snap.global_steps,
                      help="global step of the newest snapshot")
        if self.recorder is not None:
            self.recorder.annotate("snapshot", {
                "step": snap.global_steps, "capture_ms": round(dt_ms, 3),
                "emergency": emergency})
        if self.cfg.disk_tier:
            self.flush(snap, emergency=emergency)
        return snap

    def maybe_snapshot(self) -> Optional[Snapshot]:
        """The engine's per-step hook: snapshot on the configured
        cadence (cheap no-op between intervals)."""
        if self.engine.global_steps % self.snapshot_interval:
            return None
        return self.take()

    def latest(self) -> Optional[Snapshot]:
        """Newest tier-0 snapshot (the double buffer's active slot)."""
        return self._buffers[self._active] or self._buffers[self._active ^ 1]

    def buffered(self) -> List[Snapshot]:
        """Both tier-0 buffers, newest first."""
        out = [self._buffers[self._active], self._buffers[self._active ^ 1]]
        return [s for s in out if s is not None]

    def discard_newest(self) -> Optional[Snapshot]:
        """Drop the newest tier-0 buffer (the policy calls this when a
        restored snapshot immediately fails again — the capture itself
        is suspect, e.g. params that were already NaN when a later
        step's finite loss let the snapshot through).  Returns the
        discarded snapshot."""
        dropped = self._buffers[self._active]
        self._buffers[self._active] = None
        if self._buffers[self._active ^ 1] is not None:
            self._active ^= 1
        return dropped

    # -- flush (tier 1) ----------------------------------------------------

    def flush(self, snap: Optional[Snapshot] = None,
              emergency: bool = False) -> Optional[str]:
        """Flush ``snap`` (default: newest tier-0) under
        ``snapshot_dir/snap-<step>/``.  Async mode hands the ENTIRE job
        (serialize → checksummed sidecar → commit marker → tier-2
        replicate → prune) to the background worker; the step path only
        joins a still-running PREVIOUS flush (queue depth 1, like the
        reference decoupled engine — bounds host memory to two copies).
        A dir without the ``snapshot.json`` marker is an aborted flush
        and never restores."""
        snap = snap or self.latest()
        if snap is None:
            return None
        path = os.path.join(self.snapshot_dir,
                            _tag(snap.global_steps, emergency=emergency))
        if not self._async or emergency:
            return self._flush_sync(snap, emergency)
        t0 = self._clock()
        self.wait()  # queue depth 1
        if self._flush_pool is None:
            import concurrent.futures

            self._flush_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ds-snapshot-flush")
        self._pending_flush = self._flush_pool.submit(
            self._flush_sync, snap, emergency)
        from ..telemetry import get_telemetry

        get_telemetry().set_gauge(
            "resilience/snapshot_flush_dispatch_ms",
            (self._clock() - t0) * 1e3,
            help="step-path cost of dispatching the tier-1 flush "
                 "(async: excludes the background write)")
        return path

    def _flush_sync(self, snap: Snapshot, emergency: bool) -> str:
        """The full tier-1 job, on whatever thread calls it.  Uses a
        throwaway sync engine per call: concurrent emergency + regular
        flushes target different dirs and share no writer state."""
        tag = _tag(snap.global_steps, emergency=emergency)
        path = os.path.join(self.snapshot_dir, tag)
        os.makedirs(path, exist_ok=True)
        t0 = self._clock()
        state_path = os.path.join(path, "state")
        TorchCheckpointEngine().save(snap.state, state_path)
        # sha256 sidecar on EVERY host: the engine only stamps it on
        # process 0 (user checkpoints share one tree), but snapshots are
        # per-host local trees — each host gates its own restores.
        # (process 0's save already stamped it; don't hash twice)
        from ..runtime.checkpoint_engine import (_is_write_coordinator,
                                                 write_sidecar_manifest)

        if not _is_write_coordinator():
            write_sidecar_manifest(state_path)
        manifest = {"tag": tag, "step": snap.step,
                    "global_steps": snap.global_steps,
                    "emergency": bool(emergency),
                    "ts": snap.ts, "meta": snap.meta}
        tmp = os.path.join(path, SNAPSHOT_MANIFEST + ".tmp")
        with open(tmp, "w") as fh:
            json.dump(manifest, fh, indent=1, default=str)
        os.replace(tmp, os.path.join(path, SNAPSHOT_MANIFEST))  # commit
        self.flushes += 1
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        tel.inc_counter("resilience/snapshot_flushes_total",
                        help="tier-1 snapshot flushes committed durably")
        tel.set_gauge("resilience/snapshot_flush_ms",
                      (self._clock() - t0) * 1e3,
                      help="wall time of the last tier-1 flush "
                           "(background thread in async mode)")
        self._replicate(path)
        self._prune()
        return path

    def wait(self) -> None:
        """Join any in-flight async flush (tests / teardown / before a
        deliberate corruption or a restore decision)."""
        pending, self._pending_flush = self._pending_flush, None
        if pending is not None:
            try:
                pending.result()
            except Exception as e:
                # a failed background flush must surface (loudly) but
                # not kill the training step that joined it — the next
                # interval retries with a fresh snapshot
                logger.error(f"resilience: background snapshot flush "
                             f"failed: {e!r}")

    def emergency_flush(self) -> Optional[str]:
        """Watchdog-trip path: the device may be hung, but the newest
        tier-0 HOST copy is already taken — make it durable NOW, on the
        calling (watchdog) thread with its own sync writer (the
        background flusher may be the thing that is stuck)."""
        snap = self.latest()
        if snap is None:
            return None
        path = self._flush_sync(snap, emergency=True)
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "resilience/emergency_saves_total",
            help="emergency snapshot flushes on watchdog trip")
        if self.recorder is not None:
            self.recorder.annotate("resilience_emergency_save",
                                   {"path": path})
        return path

    def _prune(self) -> None:
        """Keep the newest ``keep`` committed snapshot dirs (plus any
        still-uncommitted flush target) — best-effort."""
        try:
            snaps = list_snapshots(self.snapshot_dir)
            for entry in snaps[self.keep:]:
                shutil.rmtree(entry["path"], ignore_errors=True)
        except OSError:
            pass

    # -- replicate (tier 2) ------------------------------------------------

    def _replicate(self, path: str) -> None:
        if not (self.cfg.buddy_tier and self._rdzv is not None):
            return
        try:
            buddy = self._rdzv.buddy()
            if buddy is None:
                return  # no surviving peer could ever adopt the replica
            meta = replicate_snapshot(self._rdzv.c, self._rdzv.node_id,
                                      path, rdzv=self._rdzv,
                                      chunk_bytes=self.cfg.buddy_chunk_bytes,
                                      max_bytes=self.cfg.buddy_max_bytes)
            if meta.get("dropped"):
                # a size-capped tar that dropped state files is a TORN
                # replica — it can never pass the checksum gate, so it
                # must not count as a successful replication
                logger.warning(
                    f"resilience: tier-2 replica of {path} exceeds "
                    f"buddy_max_bytes ({self.cfg.buddy_max_bytes}); "
                    f"dropped {meta['dropped']} — replica NOT restorable, "
                    f"raise the cap or disable buddy_tier")
                return
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                "resilience/buddy_replications_total",
                help="tier-2 snapshot replications through the store")
        except Exception as e:
            # replication is the LAST tier; its failure must never fail
            # the flush that tier 1 already committed
            logger.warning(f"resilience: buddy replication failed: {e!r}")

    # -- restore -----------------------------------------------------------

    def _reshard_guard(self, meta: Dict[str, Any],
                       source: str) -> Optional[Dict[str, Any]]:
        """Mesh-elastic restore gate: compare the snapshot's origin
        topology against the engine's CURRENT mesh.  Same mesh → None
        (the ordinary restore).  Different but reshardable → a reshape
        info dict (origin/target/direction) the caller accounts after
        the re-lay succeeds.  Not reshardable → a descriptive
        :class:`MeshMismatchError` naming both topologies and the
        per-tier verdict, instead of an opaque device_put error deep in
        the load."""
        from ..parallel.mesh import mesh_topology

        eng = self.engine
        target = (eng.mesh_topology() if hasattr(eng, "mesh_topology")
                  else mesh_topology(eng.mesh))
        origin = meta.get("mesh") if isinstance(meta.get("mesh"), dict) \
            else None
        if origin is None:
            return None  # pre-reshard snapshot: same-mesh semantics
        if (origin.get("axes") == target.get("axes")
                and origin.get("world_size") == target.get("world_size")):
            return None
        ok, reason = check_reshardable(meta, target)
        if not ok:
            raise MeshMismatchError(origin, target, reason,
                                    tiers=reshard_tier_report(meta, target))
        o_w, t_w = int(origin["world_size"]), int(target["world_size"])
        direction = "shrink" if t_w < o_w else "grow"
        logger.warning(
            f"resilience: resharding {source} snapshot taken on "
            f"[{format_topology(origin)}] onto the current mesh "
            f"[{format_topology(target)}] ({direction})")
        return {"origin": origin, "target": target,
                "direction": direction, "source": source,
                "origin_train_batch_size": meta.get("train_batch_size")}

    def _account_reshape(self, info: Dict[str, Any],
                         reshard_ms: float) -> None:
        """A cross-mesh restore COMPLETED: counters (total + the
        direction breakdown — the registry has no labels, so
        ``{direction}`` is a counter pair), latency gauge, and a
        ``reshape`` annotation carrying both topologies into the next
        debug bundle."""
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        # reshard_restores = the ENGINE actually re-laid state across
        # meshes; reshapes_total (agent) = the gang resealed at a new
        # world size.  Separate names so in-process deployments (agent +
        # worker share one registry) never double-count one event.
        tel.inc_counter("resilience/reshard_restores_total",
                        help="snapshot restores that re-laid state onto "
                             "a DIFFERENT mesh shape")
        tel.inc_counter(
            f"resilience/reshard_restores_{info['direction']}_total",
            help="cross-mesh snapshot restores, by direction (the "
                 "{direction} breakdown of "
                 "resilience/reshard_restores_total)")
        tel.set_gauge("resilience/reshard_last_ms", reshard_ms,
                      help="state re-lay latency of the last cross-mesh "
                           "restore")
        if self.recorder is not None:
            self.recorder.annotate("reshape", {
                "direction": info["direction"], "source": info["source"],
                "origin": info["origin"], "target": info["target"],
                "reshard_ms": round(reshard_ms, 3),
                "resumed_step": int(self.engine.global_steps)})

    def restore(self, snap: Snapshot) -> None:
        """Roll the ENGINE back to ``snap``: device_put the host tree
        onto the engine's current shardings, rewind the bookkeeping, and
        run every registered restore hook.  The host tree is the GLOBAL
        logical state, so a snapshot taken on a different mesh re-lays
        onto the current shardings in the same device_put — gated by
        :meth:`_reshard_guard`."""
        import jax

        eng = self.engine
        reshape = self._reshard_guard(snap.meta, "tier-0")
        t0 = self._clock()
        shardings = eng._state_shardings(eng.state)
        eng.state = jax.device_put(snap.state, shardings)
        self._restore_meta(snap.meta)
        if reshape is not None:
            self._account_reshape(reshape, (self._clock() - t0) * 1e3)
        log_dist(f"resilience: restored training state to step "
                 f"{snap.global_steps}")

    def _restore_meta(self, meta: Dict[str, Any]) -> None:
        eng = self.engine
        eng.global_steps = int(meta["global_steps"])
        eng.micro_steps = int(meta["micro_steps"])
        eng.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        eng.last_metrics = {}
        rng = meta.get("rng") or {}
        try:
            if rng.get("python_random"):
                random.setstate(pickle.loads(
                    bytes.fromhex(rng["python_random"])))
            if rng.get("numpy_global"):
                np.random.set_state(pickle.loads(
                    bytes.fromhex(rng["numpy_global"])))
        except Exception as e:
            logger.warning(f"resilience: host RNG restore failed: {e!r}")
        extras = meta.get("extras") or {}
        for name, (_capture, restore_fn) in self._meta_hooks.items():
            if restore_fn is not None and name in extras:
                try:
                    restore_fn(extras[name])
                except Exception as e:
                    logger.warning(f"resilience: meta hook {name!r} "
                                   f"restore failed: {e!r}")

    def load_from_disk(self, path: str) -> Snapshot:
        """Checksum-gated tier-1 restore: verify the commit marker and
        the sidecar, load the state tree INTO the engine's sharded
        layout (orbax reshard-on-load re-lays a snapshot taken on a
        different mesh, gated by :meth:`_reshard_guard`), apply it, and
        return the reconstructed snapshot."""
        import jax

        manifest = read_snapshot_manifest(path)  # raises when torn
        reshape = self._reshard_guard(manifest.get("meta") or {}, "tier-1")
        state_path = os.path.join(path, "state")
        verify_sidecar_manifest(state_path, strict=True)
        eng = self.engine
        t0 = self._clock()

        def abstract(x):
            return jax.ShapeDtypeStruct(np.shape(x), x.dtype,
                                        sharding=getattr(x, "sharding",
                                                         None))

        target = jax.tree.map(abstract, eng.state)
        # the sync loader verifies + restores resharded onto this
        # engine's mesh (orbax reshard-on-load)
        eng.state = TorchCheckpointEngine().load(state_path, target)
        self._restore_meta(manifest["meta"])
        if reshape is not None:
            self._account_reshape(reshape, (self._clock() - t0) * 1e3)
        snap = Snapshot(step=int(manifest["step"]),
                        global_steps=int(manifest["global_steps"]),
                        state=jax.tree.map(
                            lambda x: np.asarray(jax.device_get(x)),
                            eng.state),
                        meta=manifest["meta"])
        # seed tier 0 so the next rollback needn't touch disk
        self._active ^= 1
        self._buffers[self._active] = snap
        return snap


# ---------------------------------------------------------------------------
# on-disk inventory + validation (policy + operator CLI)
# ---------------------------------------------------------------------------

def read_snapshot_manifest(path: str) -> Dict[str, Any]:
    mp = os.path.join(path, SNAPSHOT_MANIFEST)
    if not os.path.exists(mp):
        raise CheckpointCorruptionError(
            f"snapshot {path!r} has no {SNAPSHOT_MANIFEST} commit marker "
            f"— the flush never completed")
    try:
        with open(mp) as fh:
            return json.load(fh)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptionError(
            f"snapshot {path!r}: unreadable {SNAPSHOT_MANIFEST} "
            f"({e!r})") from e


def verify_snapshot(path: str) -> Tuple[bool, str]:
    """Full integrity check of one snapshot dir.  Returns
    ``(valid, detail)`` — detail is the human-readable failure."""
    try:
        manifest = read_snapshot_manifest(path)
        verify_sidecar_manifest(os.path.join(path, "state"), strict=True)
        return True, f"ok (step {manifest.get('global_steps')})"
    except CheckpointCorruptionError as e:
        return False, str(e)


def list_snapshots(snapshot_dir: str) -> List[Dict[str, Any]]:
    """Committed snapshots under ``snapshot_dir``, NEWEST first (by
    step, emergency flushes ranked beneath a regular flush of the same
    step).  Uncommitted dirs (no marker) are skipped."""
    out: List[Dict[str, Any]] = []
    if not os.path.isdir(snapshot_dir):
        return out
    for d in os.listdir(snapshot_dir):
        path = os.path.join(snapshot_dir, d)
        if not (d.startswith("snap-") and os.path.isdir(path)):
            continue
        try:
            m = read_snapshot_manifest(path)
        except CheckpointCorruptionError:
            continue
        out.append({"path": path, "tag": m.get("tag", d),
                    "step": int(m.get("global_steps", -1)),
                    "emergency": bool(m.get("emergency")),
                    "ts": m.get("ts")})
    out.sort(key=lambda e: (e["step"], not e["emergency"], e["tag"]),
             reverse=True)
    return out


def choose_resume_snapshot(snapshot_dir: str,
                           client: Any = None,
                           node_id: Optional[str] = None,
                           fetch_dir: Optional[str] = None,
                           rdzv: Any = None) -> Optional[str]:
    """The policy's tier-fallback: newest LOCAL snapshot that passes the
    checksum gate; when none survives and a store client is given, pull
    the tier-2 buddy replica of ``node_id`` into ``fetch_dir`` (default:
    the snapshot dir) and validate that.  With ``rdzv`` (an
    :class:`~..elasticity.rendezvous.ElasticRendezvous`), two further
    fallbacks close the replacement-node gap: ADOPT a dead peer's
    orphaned replica (sealed-ring diff names the dead; this node re-keys
    the replica under its own id), then BOOTSTRAP from any live peer's
    replica (a scale-up joiner has no history of its own).  Returns a
    verified snapshot path or None."""
    for entry in list_snapshots(snapshot_dir):
        ok, detail = verify_snapshot(entry["path"])
        if ok:
            return entry["path"]
        logger.warning(f"resilience: skipping invalid snapshot "
                       f"{entry['path']}: {detail}")
    if client is None and rdzv is not None:
        client = rdzv.c
    if node_id is None and rdzv is not None:
        node_id = rdzv.node_id
    if client is not None and node_id:
        try:
            pulled = fetch_buddy_snapshot(client, node_id,
                                          fetch_dir or snapshot_dir)
        except Exception as e:
            logger.warning(f"resilience: buddy snapshot fetch failed: "
                           f"{e!r}")
            pulled = None
        if pulled:
            ok, detail = verify_snapshot(pulled)
            if ok:
                return pulled
            logger.warning(f"resilience: buddy replica invalid: {detail}")
    if rdzv is not None:
        adopted = adopt_orphaned_replica(rdzv, fetch_dir or snapshot_dir)
        if adopted:
            return adopted
        return bootstrap_from_peer_replica(rdzv,
                                           fetch_dir or snapshot_dir)
    return None


# ---------------------------------------------------------------------------
# replacement-node adoption + scale-up bootstrap (ROADMAP item 5)
# ---------------------------------------------------------------------------

def adopt_orphaned_replica(rdzv: Any, out_dir: str,
                           retries: int = 6,
                           retry_delay_s: float = 2.0) -> Optional[str]:
    """Replacement-node adoption: a node with a FRESH node id that
    sealed into the ring after a death walks the sealed-ring diff,
    discovers which dead peer's tier-2 replica is orphaned, fetches it,
    verifies the checksum gate, and RE-KEYS it under its own id (so its
    future buddy — and its own future restarts — find the slot where
    they expect it).  Deterministic assignment: the k-th joined node
    (sorted) adopts the k-th dead peer (sorted, wrapping), so two
    replacements never fight over one corpse.  Fetches retry briefly
    (``retries`` rounds, ``retry_delay_s`` apart): adoption runs while
    the gang is RE-FORMING, so a surviving holder may itself be
    mid-restart with its replica server not yet re-bound.  Returns the
    local adopted snapshot path, or None."""
    try:
        diff = rdzv.ring_diff()
    except Exception as e:
        logger.warning(f"resilience: sealed-ring diff failed: {e!r}")
        return None
    dead = sorted(diff.get("left") or [])
    joined = sorted(diff.get("joined") or [])
    me = rdzv.node_id
    if not dead or me not in joined:
        # a restarted SAME-id node owns its own slot (handled by the
        # plain buddy fetch above); nothing orphaned to adopt
        return None
    k = joined.index(me) % len(dead)
    candidates = dead[k:] + dead[:k]
    pulled = None
    peer = None
    for attempt in range(max(1, int(retries))):
        if attempt:
            time.sleep(retry_delay_s)
            logger.warning(f"resilience: adoption retry "
                           f"{attempt + 1}/{retries} (holders may be "
                           f"re-binding mid-reform)")
        for cand in candidates:
            try:
                got = fetch_buddy_snapshot(rdzv.c, cand, out_dir)
            except Exception as e:
                logger.warning(f"resilience: fetch of dead peer "
                               f"{cand!r}'s replica failed: {e!r}")
                continue
            if not got:
                continue  # that peer never replicated
            ok, detail = verify_snapshot(got)
            if not ok:
                logger.warning(f"resilience: dead peer {cand!r}'s "
                               f"replica invalid: {detail}")
                continue
            pulled, peer = got, cand
            break
        if pulled:
            break
    if not pulled:
        return None
    try:
        # re-key under OUR id: serve the adopted dir from our own
        # replica server (+ push to our buddy) and re-point the index
        replicate_snapshot(rdzv.c, me, pulled, rdzv=rdzv)
    except Exception as e:
        logger.warning(f"resilience: re-keying adopted replica under "
                       f"{me!r} failed (adoption still valid): {e!r}")
    from ..telemetry import get_telemetry

    get_telemetry().inc_counter(
        "resilience/replica_adoptions_total",
        help="dead peers' tier-2 replicas adopted by replacement "
             "nodes (sealed-ring diff)")
    log_dist(f"resilience: node {me} adopted dead peer {peer}'s "
             f"tier-2 replica -> {pulled}")
    return pulled


def bootstrap_from_peer_replica(rdzv: Any, out_dir: str) -> Optional[str]:
    """Scale-up bootstrap: a JOINING node with no local history and no
    orphan to adopt pulls the newest live peer's replica as its starting
    point — the reshard-on-restore path then lays it onto whatever mesh
    the new world builds.  Returns the local path, or None."""
    try:
        gang = [n for n in rdzv.sealed_ring() if n != rdzv.node_id]
    except Exception as e:
        logger.warning(f"resilience: sealed-ring read failed: {e!r}")
        return None
    best: Optional[Tuple[float, str]] = None
    for peer in gang:
        meta = rdzv.c.get(RESIL_META_KEY.format(node=peer))
        if isinstance(meta, dict):
            ts = float(meta.get("ts") or 0.0)
            if best is None or ts > best[0]:
                best = (ts, peer)
    if best is None:
        return None
    pulled = None
    for attempt in range(3):
        if attempt:
            # the gang is re-forming: the peer's replica server may be
            # re-binding — brief bounded retry, same as adoption
            time.sleep(2.0)
        try:
            pulled = fetch_buddy_snapshot(rdzv.c, best[1], out_dir)
        except Exception as e:
            logger.warning(f"resilience: bootstrap fetch from "
                           f"{best[1]!r} failed: {e!r}")
            pulled = None
        if pulled:
            break
    if not pulled:
        return None
    ok, detail = verify_snapshot(pulled)
    if not ok:
        logger.warning(f"resilience: bootstrap replica from {best[1]!r} "
                       f"invalid: {detail}")
        return None
    from ..telemetry import get_telemetry

    get_telemetry().inc_counter(
        "resilience/replica_bootstraps_total",
        help="joining nodes bootstrapped from a live peer's tier-2 "
             "replica (scale-up)")
    log_dist(f"resilience: joining node {rdzv.node_id} bootstrapped from "
             f"peer {best[1]}'s replica -> {pulled}")
    return pulled


# ---------------------------------------------------------------------------
# tier-2 transport (peer-to-peer replica servers; the store carries
# index/placement metadata only)
# ---------------------------------------------------------------------------

def replicate_snapshot(client: Any, node_id: str, snap_dir: str,
                       chunk_bytes: int = 256 * 1024,
                       max_bytes: int = 256 * 1024 * 1024,
                       rdzv: Any = None,
                       buddy: Optional[str] = None) -> Dict[str, Any]:
    """Make one committed snapshot dir fetchable by the gang:

    1. serve it from this process's replica server (started on demand);
    2. PUSH a physical copy to the buddy's replica server when one is
       reachable (``rdzv``/``buddy`` name it; its endpoint comes from
       the store's ``resil/srv/<buddy>`` slot) — the copy that survives
       this host's death;
    3. publish the INDEX metadata (tag, bytes, sha256, holder
       endpoints) under ``resil/pub/<node_id>`` — a journaled write, so
       it buffers through a store outage and re-seeds a restarted
       store.  **No snapshot bytes ever enter the store.**
    """
    import hashlib as _hashlib

    from ..telemetry.aggregator import _tar_dir
    from .replica_server import get_local_server, push_replica

    tag = os.path.basename(snap_dir.rstrip(os.sep))
    data, dropped = _tar_dir(snap_dir, max_bytes,
                             priority_file=SNAPSHOT_MANIFEST,
                             recursive=True)
    sha = _hashlib.sha256(data).hexdigest()
    server = get_local_server(
        create=True, base_dir=os.path.join(os.path.dirname(
            snap_dir.rstrip(os.sep)), "replica_store"),
        chunk_bytes=chunk_bytes, max_bytes=max_bytes)
    server.serve(node_id, tag, snap_dir, tar=(data, sha),
                 max_bytes=max_bytes)
    holders: List[Dict[str, Any]] = [
        {"node": node_id, "endpoint": server.endpoint, "path": snap_dir}]
    if buddy is None and rdzv is not None:
        try:
            buddy = rdzv.buddy()
        except Exception as e:
            logger.warning(f"resilience: buddy lookup failed: {e!r}")
            buddy = None
    if buddy and buddy != node_id:
        buddy_ep = None
        try:
            buddy_ep = client.get(RESIL_SRV_KEY.format(node=buddy))
        except (OSError, ConnectionError) as e:
            logger.warning(f"resilience: buddy endpoint lookup failed "
                           f"(store degraded?): {e!r}")
        if buddy_ep:
            try:
                held = push_replica(str(buddy_ep), node_id, tag, data,
                                    sha, chunk_bytes=chunk_bytes)
                holders.append({"node": buddy, "endpoint": str(buddy_ep),
                                "path": held})
            except Exception as e:
                # owner-only serving still covers restarts; only a
                # simultaneous owner+store loss needs the buddy copy
                logger.warning(f"resilience: replica push to buddy "
                               f"{buddy!r} ({buddy_ep}) failed: {e!r}")
        else:
            logger.warning(f"resilience: buddy {buddy!r} has no replica "
                           f"server endpoint published — replica held "
                           f"by owner only")
    meta = {"bundle": tag, "owner": node_id, "bytes": len(data),
            "sha256": sha, "dropped": dropped, "ts": time.time(),
            "holders": holders}
    try:
        client.set(RESIL_META_KEY.format(node=node_id), meta,
                   journal=True)
    except TypeError:
        # a minimal client without the journal kwarg (tests/fakes)
        client.set(RESIL_META_KEY.format(node=node_id), meta)
    return meta


def fetch_buddy_snapshot(client: Any, node_id: str,
                         out_dir: str) -> Optional[str]:
    """Pull ``node_id``'s replica using the store's INDEX metadata:
    try each holder endpoint in order (owner first, then the buddy) and
    fall through past dead peers; every fetch passes the transport
    sha256 gate.  Returns the extracted snapshot path, None when that
    node never replicated, and raises when holders exist but none could
    serve a VALID copy (all dead, or all corrupt — the caller's tier
    fallback treats that as 'no tier-2')."""
    meta = client.get(RESIL_META_KEY.format(node=node_id))
    if not isinstance(meta, dict):
        return None
    if "holders" not in meta:
        # pre-P2P publication: bytes chunked into the store
        from ..telemetry.aggregator import fetch_dir_chunked

        return fetch_dir_chunked(
            client, RESIL_META_KEY.format(node=node_id),
            RESIL_CHUNK_PREFIX.format(node=node_id), out_dir)
    from .replica_server import fetch_replica

    owner = str(meta.get("owner") or node_id)
    tag = str(meta["bundle"])
    errors: List[str] = []
    for holder in meta.get("holders") or []:
        # a holder NODE is stable; its endpoint is not (worker restarts
        # re-bind).  Prefer the holder's CURRENTLY-published server
        # endpoint, falling back to the one recorded at placement time.
        endpoints = []
        hnode = holder.get("node")
        if hnode:
            try:
                live = client.get(RESIL_SRV_KEY.format(node=hnode))
            except (OSError, ConnectionError):
                live = None  # store degraded — recorded endpoint only
            if live:
                endpoints.append(str(live))
        recorded = str(holder.get("endpoint") or "")
        if recorded and recorded not in endpoints:
            endpoints.append(recorded)
        dead_here = None
        for ep in endpoints:
            try:
                return fetch_replica(ep, owner, tag, out_dir,
                                     expect_sha=meta.get("sha256"))
            except (OSError, ConnectionError) as e:
                dead_here = e
            except CheckpointCorruptionError as e:
                dead_here = None
                errors.append(f"{hnode}@{ep}: {e}")
                break  # corrupt copy: this holder is done, move on
        if dead_here is not None:
            # dead/unreachable holder: fall through to the next
            # placement candidate
            errors.append(f"{hnode}@{endpoints}: {dead_here!r}")
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                "resilience/replica_fetch_fallthroughs_total",
                help="replica holders skipped because they were "
                     "unreachable (fetch fell through to the next "
                     "placement candidate)")
    raise CheckpointCorruptionError(
        f"tier-2 replica of {node_id!r} ({tag}) could not be fetched "
        f"from any holder: " + "; ".join(errors or ["no holder had an "
                                                    "endpoint"]))
