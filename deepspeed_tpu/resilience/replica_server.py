"""Peer-to-peer tier-2 replica transport (ISSUE 11 tentpole b).

Every node runs ONE lightweight :class:`ReplicaServer` — the same
JSON-line TCP protocol the rendezvous store speaks — that serves this
node's flushed snapshot dirs (and the replica copies peers pushed to
it) directly to the gang.  The rendezvous store carries only
**index/placement metadata** (``resil/pub/<node>``: tag, bytes, sha256,
holder endpoints — see ``snapshot.py``), never snapshot bytes, so
killing the store no longer destroys the tier: the bytes live on the
owner AND its buddy, and anyone who knows a holder endpoint can
restore with the store down (``python -m deepspeed_tpu.resilience
fetch``).

Protocol (one JSON object per line, ``op``-dispatched):

* ``index``                         — list ``{owner, tag}`` served here
* ``meta  {owner, tag}``            — prepare the tar, return
  ``{n, bytes, sha256, chunk_bytes}``
* ``chunk {owner, tag, i}``         — the i-th base64 chunk
* ``put_begin/put_chunk/put_commit``— buddy upload (owner → holder);
  commit verifies the transport sha256 BEFORE extracting — a torn or
  tampered upload never lands on disk

Fetches are checksum-gated twice: the transport sha256 over the tar
(rejects a corrupt/garbled holder) and the per-file sidecar manifest
the snapshot already carries (``verify_snapshot`` at the caller).
"""

from __future__ import annotations

import base64
import collections
import hashlib
import io
import json
import os
import socket
import socketserver
import tarfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..runtime.checkpoint_engine import CheckpointCorruptionError
from ..utils.logging import log_dist, logger

DEFAULT_CHUNK_BYTES = 256 * 1024
DEFAULT_MAX_BYTES = 256 * 1024 * 1024
#: prepared tars kept in memory (LRU) — rebuilt from the served dir on
#: a miss, so eviction costs time, never correctness
TAR_CACHE = 4


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class _ReplicaTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _ReplicaHandler(socketserver.StreamRequestHandler):
    def handle(self):
        owner: "ReplicaServer" = self.server.replica  # type: ignore
        for raw in self.rfile:
            try:
                req = json.loads(raw)
            except ValueError:
                break
            try:
                out = owner.handle_request(req)
            except Exception as e:  # a bad request must not kill the
                out = {"ok": False, "err": repr(e)}  # serving thread
            self.wfile.write((json.dumps(out) + "\n").encode())
            self.wfile.flush()


class ReplicaServer:
    """Serve snapshot dirs to peers; accept buddy uploads.

    One per process (:func:`get_local_server`).  All shared state —
    the served-dir registry, the tar LRU, in-flight uploads — is
    guarded by one lock; tar preparation happens under it too, which
    makes concurrent fetches of the same dir trivially safe (the
    second fetch waits for the first build instead of duplicating it).
    """

    def __init__(self, base_dir: str, host: str = "", port: int = 0,
                 advertise_host: Optional[str] = None,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self.chunk_bytes = max(1, int(chunk_bytes))
        self.max_bytes = int(max_bytes)
        #: (owner, tag) -> served dir path
        self._served: Dict[Tuple[str, str], str] = {}
        #: (owner, tag) -> size cap the ORIGINAL tar was built under: a
        #: rebuild (cache eviction, server restart) must apply the same
        #: cap or it could drop a different file set and produce a sha
        #: the published index no longer matches
        self._caps: Dict[Tuple[str, str], int] = {}
        #: (owner, tag) -> (b64, sha256, raw_bytes, dropped) LRU
        self._tars: "collections.OrderedDict[Tuple[str, str], tuple]" = \
            collections.OrderedDict()
        #: (owner, tag) -> in-flight upload staging
        self._uploads: Dict[Tuple[str, str], Dict[str, Any]] = {}
        #: (owner, tag) -> Event for a tar build IN PROGRESS: builds run
        #: OUTSIDE the registry lock (a multi-hundred-MB gzip must not
        #: stall uploads/probes), concurrent fetchers of the same dir
        #: wait on the event instead of duplicating the build
        self._building: Dict[Tuple[str, str], Any] = {}
        self._lock = threading.Lock()
        self._srv = _ReplicaTCPServer((host or "", port), _ReplicaHandler)
        self._srv.replica = self  # type: ignore[attr-defined]
        self.port = int(self._srv.server_address[1])
        #: the address PEERS dial — DS_ELASTIC_HOST (the operator knows
        #: the routable interface) or loopback for single-box gangs
        self.host = (advertise_host or os.environ.get("DS_ELASTIC_HOST")
                     or "127.0.0.1")
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name="ds-replica-server")
        self._thread.start()
        # a RESTARTED holder re-serves the replicas it already holds on
        # disk (recv/<owner>/<tag>): a worker teardown/restart must not
        # orphan the copies the tier's durability depends on
        recv = os.path.join(base_dir, "recv")
        if os.path.isdir(recv):
            for owner in sorted(os.listdir(recv)):
                odir = os.path.join(recv, owner)
                if not os.path.isdir(odir):
                    continue
                for tag in sorted(os.listdir(odir)):
                    tdir = os.path.join(odir, tag)
                    if os.path.isdir(tdir):
                        self._served[(owner, tag)] = tdir
                        self._caps[(owner, tag)] = 2 ** 62  # held copy
        log_dist(f"tier-2 replica server at {self.endpoint} "
                 f"({len(self._served)} held replica(s) re-served; "
                 f"store carries metadata only)")

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    # -- registry -----------------------------------------------------------

    def serve(self, owner: str, tag: str, path: str,
              tar: Optional[Tuple[bytes, str]] = None,
              max_bytes: Optional[int] = None) -> None:
        """Register ``path`` as ``owner``'s snapshot ``tag``; with
        ``tar`` (data, sha256) the prepared tar is cached so the first
        peer fetch pays no rebuild.  ``max_bytes`` records the size cap
        the original tar honored, so a rebuild drops the same (or no)
        files and reproduces the published sha."""
        with self._lock:
            self._served[(owner, tag)] = path
            if max_bytes is not None:
                self._caps[(owner, tag)] = int(max_bytes)
            if tar is not None:
                data, sha = tar
                self._cache_tar(owner, tag,
                                (base64.b64encode(data).decode("ascii"),
                                 sha, len(data), []))

    def served(self) -> List[Dict[str, str]]:
        with self._lock:
            return [{"owner": o, "tag": t, "path": p}
                    for (o, t), p in sorted(self._served.items())]

    def _cache_tar(self, owner: str, tag: str, entry: tuple) -> None:
        # caller holds the lock
        self._tars[(owner, tag)] = entry
        self._tars.move_to_end((owner, tag))
        while len(self._tars) > TAR_CACHE:
            self._tars.popitem(last=False)

    def _tar_for(self, owner: str, tag: str) -> tuple:
        """(b64, sha256, raw_bytes, dropped) for a served dir — cached,
        else rebuilt OUTSIDE the registry lock.  Concurrent fetchers of
        the same dir wait for the one in-flight build; other protocol
        ops (buddy uploads, index probes) are never stalled behind a
        gzip."""
        key = (owner, tag)
        while True:
            with self._lock:
                cached = self._tars.get(key)
                if cached is not None:
                    self._tars.move_to_end(key)
                    return cached
                building = self._building.get(key)
                if building is None:
                    building = threading.Event()
                    self._building[key] = building
                    path = self._served.get(key)
                    break  # this thread builds
            building.wait(timeout=300.0)
            # re-check the cache (or find the build failed and retry it)
        try:
            if path is None or not os.path.isdir(path):
                raise FileNotFoundError(
                    f"replica {owner}/{tag} is not served here")
            from ..telemetry.aggregator import _tar_dir
            from .snapshot import SNAPSHOT_MANIFEST

            with self._lock:
                cap = self._caps.get(key, self.max_bytes)
            data, dropped = _tar_dir(path, cap,
                                     priority_file=SNAPSHOT_MANIFEST,
                                     recursive=True)
            entry = (base64.b64encode(data).decode("ascii"),
                     _sha256(data), len(data), dropped)
            with self._lock:
                self._cache_tar(owner, tag, entry)
            return entry
        finally:
            with self._lock:
                self._building.pop(key, None)
            building.set()

    def _prune_held(self, owner: str, keep: int = 3) -> None:
        """Holder-side retention: an owner replicating every snapshot
        interval would otherwise grow this node's disk without bound —
        keep the newest ``keep`` held copies per owner (tag order is
        step order: ``snap-<zero-padded step>``)."""
        import shutil

        with self._lock:
            held = sorted(t for (o, t), p in self._served.items()
                          if o == owner
                          and p.startswith(os.path.join(self.base_dir,
                                                        "recv")))
            drop = held[:-keep] if keep > 0 else []
            paths = []
            for tag in drop:
                paths.append(self._served.pop((owner, tag)))
                self._tars.pop((owner, tag), None)
                self._caps.pop((owner, tag), None)
        for p in paths:
            shutil.rmtree(p, ignore_errors=True)

    # -- protocol -----------------------------------------------------------

    def handle_request(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "index":
            return {"ok": True, "v": self.served()}
        if op == "meta":
            b64, sha, nbytes, dropped = self._tar_for(str(req["owner"]),
                                                      str(req["tag"]))
            n = max(1, -(-len(b64) // self.chunk_bytes)) if b64 else 0
            return {"ok": True, "n": n, "bytes": nbytes, "sha256": sha,
                    "chunk_bytes": self.chunk_bytes, "dropped": dropped}
        if op == "chunk":
            b64, _sha, _nb, _dr = self._tar_for(str(req["owner"]),
                                                str(req["tag"]))
            i = int(req["i"])
            step = self.chunk_bytes
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                "resilience/replica_chunks_served_total",
                help="tier-2 replica chunks served to peers")
            return {"ok": True, "v": b64[i * step:(i + 1) * step]}
        if op == "put_begin":
            key = (str(req["owner"]), str(req["tag"]))
            if int(req.get("bytes", 0)) > self.max_bytes:
                return {"ok": False,
                        "err": f"replica exceeds max_bytes "
                               f"({self.max_bytes})"}
            with self._lock:
                # expire ABANDONED staging first: an owner killed
                # mid-push (the exact crash window this tier exists
                # for) must not leak its staged chunks in this holder
                # forever — tags are unique per step, so torn pushes
                # would otherwise accumulate without bound
                now = time.time()
                for stale in [k for k, u in self._uploads.items()
                              if now - u["ts"] > 900.0]:
                    self._uploads.pop(stale, None)
                self._uploads[key] = {"n": int(req["n"]),
                                      "sha256": str(req["sha256"]),
                                      "chunks": {}, "ts": now}
            return {"ok": True}
        if op == "put_chunk":
            key = (str(req["owner"]), str(req["tag"]))
            with self._lock:
                up = self._uploads.get(key)
                if up is None:
                    return {"ok": False, "err": "no upload in progress"}
                up["chunks"][int(req["i"])] = str(req["v"])
            return {"ok": True}
        if op == "put_commit":
            return self._commit_upload(str(req["owner"]), str(req["tag"]))
        if op == "ping":
            return {"ok": True, "v": "replica"}
        return {"ok": False, "err": f"bad op {op!r}"}

    def _commit_upload(self, owner: str, tag: str) -> Dict[str, Any]:
        with self._lock:
            up = self._uploads.pop((owner, tag), None)
        if up is None:
            return {"ok": False, "err": "no upload in progress"}
        b64 = "".join(up["chunks"].get(i, "") for i in range(up["n"]))
        data = base64.b64decode(b64)
        if _sha256(data) != up["sha256"]:
            # the checksum gate at the UPLOAD boundary: a torn or
            # tampered push never lands on the holder's disk
            return {"ok": False,
                    "err": f"upload checksum mismatch for {owner}/{tag}"}
        dest_root = os.path.join(self.base_dir, "recv", owner)
        os.makedirs(dest_root, exist_ok=True)
        from ..telemetry.aggregator import _safe_extract

        with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
            _safe_extract(tar, dest_root)
        path = os.path.join(dest_root, tag)
        # a held copy already passed the OWNER's size cap — a rebuild
        # must never drop anything or its sha diverges from the index
        self.serve(owner, tag, path, tar=(data, up["sha256"]),
                   max_bytes=2 ** 62)
        self._prune_held(owner, keep=3)
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "resilience/replica_holds_total",
            help="peer replica copies accepted and held by this node")
        log_dist(f"holding tier-2 replica {owner}/{tag} ({len(data)} "
                 f"tar bytes) at {path}")
        return {"ok": True, "path": path}


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

def _rpc(endpoint: str, requests: List[Dict[str, Any]],
         timeout: float = 60.0) -> List[Dict[str, Any]]:
    """Send ``requests`` over ONE connection; returns the replies.  No
    retries — a dead holder is a normal condition the caller falls
    through on (``ConnectionError``/``OSError`` propagate)."""
    host, _, port = endpoint.rpartition(":")
    out: List[Dict[str, Any]] = []
    with socket.create_connection((host, int(port)),
                                  timeout=timeout) as s:
        f = s.makefile("rwb")
        for req in requests:
            f.write((json.dumps(req) + "\n").encode())
            f.flush()
            line = f.readline()
            if not line:
                raise ConnectionError(
                    f"replica server {endpoint} closed the connection")
            out.append(json.loads(line))
    return out


def fetch_replica(endpoint: str, owner: str, tag: str, out_dir: str,
                  expect_sha: Optional[str] = None,
                  timeout: float = 60.0) -> str:
    """Pull ``owner``'s snapshot ``tag`` from the holder at
    ``endpoint`` into ``out_dir``.  Raises
    :class:`CheckpointCorruptionError` when the transport sha256 (the
    holder's, and ``expect_sha`` from the store index when given)
    doesn't match the bytes — a corrupt replica is rejected, never
    extracted.  Dead holder → ``ConnectionError``/``OSError`` for the
    caller's fallthrough."""
    meta = _rpc(endpoint, [{"op": "meta", "owner": owner, "tag": tag}],
                timeout=timeout)[0]
    if not meta.get("ok"):
        raise ConnectionError(f"replica server {endpoint} cannot serve "
                              f"{owner}/{tag}: {meta.get('err')}")
    reqs = [{"op": "chunk", "owner": owner, "tag": tag, "i": i}
            for i in range(int(meta["n"]))]
    replies = _rpc(endpoint, reqs, timeout=timeout) if reqs else []
    bad = [r for r in replies if not r.get("ok")]
    if bad:
        # a refused chunk (tag pruned between meta and chunk calls,
        # registry churn) is UNAVAILABILITY — it must read as a dead
        # holder the caller falls through on, never as corruption
        raise ConnectionError(
            f"replica server {endpoint} stopped serving {owner}/{tag} "
            f"mid-fetch: {bad[0].get('err')}")
    b64 = "".join(str(r.get("v") or "") for r in replies)
    data = base64.b64decode(b64)
    got = _sha256(data)
    want = expect_sha or meta.get("sha256")
    if want and got != want:
        raise CheckpointCorruptionError(
            f"tier-2 replica {owner}/{tag} from {endpoint} failed the "
            f"transport checksum gate (sha256 {got[:12]}… != expected "
            f"{str(want)[:12]}…) — replica rejected")
    os.makedirs(out_dir, exist_ok=True)
    from ..telemetry.aggregator import _safe_extract

    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
        _safe_extract(tar, out_dir)
    from ..telemetry import get_telemetry

    get_telemetry().inc_counter(
        "resilience/replica_fetches_total",
        help="tier-2 replicas fetched peer-to-peer")
    return os.path.join(out_dir, tag)


def push_replica(endpoint: str, owner: str, tag: str, data: bytes,
                 sha256: str, chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 timeout: float = 60.0) -> str:
    """Upload a prepared tar to the holder at ``endpoint`` (owner →
    buddy).  Returns the holder-side path; raises on refusal or
    checksum mismatch."""
    b64 = base64.b64encode(data).decode("ascii")
    step = max(1, int(chunk_bytes))
    chunks = [b64[i:i + step] for i in range(0, len(b64), step)] or [""]
    reqs: List[Dict[str, Any]] = [
        {"op": "put_begin", "owner": owner, "tag": tag,
         "n": len(chunks), "bytes": len(data), "sha256": sha256}]
    reqs += [{"op": "put_chunk", "owner": owner, "tag": tag, "i": i,
              "v": ch} for i, ch in enumerate(chunks)]
    reqs.append({"op": "put_commit", "owner": owner, "tag": tag})
    replies = _rpc(endpoint, reqs, timeout=timeout)
    for r in replies:
        if not r.get("ok"):
            raise RuntimeError(f"replica push of {owner}/{tag} to "
                               f"{endpoint} refused: {r.get('err')}")
    from ..telemetry import get_telemetry

    get_telemetry().inc_counter(
        "resilience/replica_pushes_total",
        help="tier-2 replicas pushed to a buddy holder peer-to-peer")
    return str(replies[-1].get("path"))


# ---------------------------------------------------------------------------
# process-local singleton
# ---------------------------------------------------------------------------

_local: Optional[ReplicaServer] = None
_local_lock = threading.Lock()


def get_local_server(create: bool = False,
                     base_dir: Optional[str] = None,
                     chunk_bytes: Optional[int] = None,
                     max_bytes: Optional[int] = None
                     ) -> Optional[ReplicaServer]:
    """This process's replica server (one per process — every engine /
    snapshot manager in the process serves through it).  ``create=True``
    starts it on first use; ``base_dir``/``chunk_bytes``/``max_bytes``
    (the configured ``resilience.buddy_*`` knobs) only seed the first
    creation — later callers share whatever the first one picked."""
    global _local
    with _local_lock:
        if _local is None and create:
            import tempfile

            root = base_dir or tempfile.mkdtemp(prefix="ds-replica-store-")
            _local = ReplicaServer(
                root,
                chunk_bytes=chunk_bytes or DEFAULT_CHUNK_BYTES,
                max_bytes=max_bytes or DEFAULT_MAX_BYTES)
        return _local


def set_local_server(server: Optional[ReplicaServer]) -> None:
    """Install/replace the process-local server (tests; a replaced
    server is shut down)."""
    global _local
    with _local_lock:
        prev, _local = _local, server
    if prev is not None and prev is not server:
        try:
            prev.shutdown()
        except OSError as e:
            logger.warning(f"replica server shutdown failed: {e!r}")
