"""Deterministic fault injection — make the recovery loop PROVABLE.

ISSUE 4 tentpole, pillar 3.  A resilience plane nobody can trigger is a
resilience plane nobody can trust: this harness injects the exact
failures the policy claims to survive, deterministically (fault specs
name a step, not a probability), driven by config
(``resilience.faults``) or the ``DS_FAULTS`` env var so CI and chaos
drills run the SAME loop production would.

Spec grammar (comma-free ``kind@step[:key=value,...]``)::

    kill_rank@120:rank=1         # worker death at step 120 on rank 1
    kill_rank@120:rank=1,mode=exit   # hard os._exit instead of raising
    nan_loss@64                  # poison step 64's batch with NaN
    stall@32:seconds=90          # stall the step path (watchdog food)
    corrupt_snapshot@40          # flip bytes in the newest tier-1 snap
    corrupt_snapshot@40:tier=0,buffers=all  # poison tier-0 host buffers
    corrupt_snapshot@40:tier=2   # garble the tier-2 buddy replica
    node_leave@200               # this host LEAVES the gang (scale-down)
    node_join@200:delay_s=5      # a host joins (harness cb / round bump)
    kill_store@80                # SIGKILL the rendezvous store process
    restart_store@90:delay_s=2   # respawn the store at its endpoint
    partition_node@100:seconds=5 # drop THIS node's store connectivity
    sigstop_hang@120:seconds=10  # SIGSTOP this worker (a real OS hang)

Faults fire ONCE (per process) at the step they name; ``rank=`` guards
restrict kill/leave/join/store/partition/hang faults to one worker.
Every firing lands in telemetry
(``resilience/faults_injected_total``) and the flight recorder, so a
chaos run's debug bundle says what was injected, where.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import logger

#: kind -> one-line operator doc (the `resilience faults` CLI prints
#: this catalogue; KINDS derives from it so the two can't drift)
FAULT_DOCS = {
    "kill_rank": "worker death (raise InjectedFault, or os._exit(113) "
                 "with mode=exit); params: rank=, mode=raise|exit",
    "kill": "alias of kill_rank",
    "nan_loss": "poison the step's batch with NaN (drives the rollback "
                "loop)",
    "nan_params": "NaN one layer's weights in the live param tree "
                  "(drives the numerics plane's NaN-origin forensics: "
                  "the report must name THIS layer); params: layer=",
    "stall": "stall the step path (watchdog food); params: seconds=",
    "corrupt_snapshot": "defeat a snapshot tier's integrity gate; "
                        "params: tier=0|1|2, buffers=all (tier 0), "
                        "dir= (tier 1), node= (tier 2)",
    "node_leave": "this host leaves the gang gracefully (scale-down); "
                  "params: rank=",
    "node_join": "a host joins after delay_s (harness callback, else a "
                 "round bump — a join attempt IS a reseal); params: "
                 "delay_s=, rank=",
    "kill_store": "SIGKILL the rendezvous store process (pid= param, "
                  "DS_STORE_PID env, or the on_store_kill harness "
                  "callback); training must continue DEGRADED",
    "restart_store": "respawn the store at its endpoint after delay_s "
                     "(on_store_restart callback, else spawn `python -m "
                     "deepspeed_tpu.elasticity.store` detached); "
                     "params: delay_s=, endpoint=",
    "partition_node": "drop THIS node's store connectivity for "
                      "seconds= (client-side blackhole: every live "
                      "RendezvousClient in the process); params: "
                      "seconds=, rank=",
    "sigstop_hang": "SIGSTOP this worker process for seconds= (a "
                    "helper re-CONTs it) — a genuine OS-level hang the "
                    "gang's heartbeat-ttl machinery must catch; "
                    "params: seconds=, rank=",
}

KINDS = tuple(FAULT_DOCS)


class InjectedFault(RuntimeError):
    """A kill fault fired in ``raise`` mode — the supervisor (elastic
    agent) sees a worker failure exactly as it would a real crash."""


#: exit code a SUBPROCESS worker uses to signal a graceful node leave —
#: a typed exception cannot cross the process boundary, so the agent's
#: _run_subprocess maps this code back to NodeLeaveRequested instead of
#: a budgeted crash-restart (which would replay the run, re-fire the
#: fault, and burn the whole restart budget on a deliberate scale-down)
NODE_LEAVE_EXIT_CODE = 114


class NodeLeaveRequested(Exception):
    """A ``node_leave`` fault fired: this host is LEAVING the gang
    permanently (scale-down chaos), not crashing.  The elastic agent
    catches it, leaves the rendezvous gracefully, bumps the round so the
    survivors reseal at the smaller world, and exits its supervision
    loop instead of restarting."""


class Fault:
    __slots__ = ("kind", "step", "params", "fired")

    def __init__(self, kind: str, step: int, params: Dict[str, str]):
        self.kind = kind
        self.step = int(step)
        self.params = params
        self.fired = False

    def __repr__(self):
        kv = ",".join(f"{k}={v}" for k, v in self.params.items())
        return f"{self.kind}@{self.step}" + (f":{kv}" if kv else "")


def parse_fault(spec: str) -> Fault:
    """``kind@step[:k=v,...]`` → :class:`Fault`; raises ``ValueError``
    with the offending spec on any malformation (a chaos drill with a
    typo'd spec must fail loudly, not silently not inject)."""
    text = spec.strip()
    head, _, tail = text.partition(":")
    kind, at, step_s = head.partition("@")
    if not at or not kind or not step_s:
        raise ValueError(f"fault spec {spec!r}: expected kind@step[:k=v,...]")
    if kind not in KINDS:
        raise ValueError(f"fault spec {spec!r}: unknown kind {kind!r} "
                         f"(known: {', '.join(KINDS)})")
    try:
        step = int(step_s)
    except ValueError:
        raise ValueError(f"fault spec {spec!r}: step {step_s!r} is not an "
                         f"integer")
    params: Dict[str, str] = {}
    if tail:
        for part in tail.split(","):
            k, eq, v = part.partition("=")
            if not eq or not k:
                raise ValueError(f"fault spec {spec!r}: bad param "
                                 f"{part!r} (expected key=value)")
            params[k.strip()] = v.strip()
    return Fault("kill_rank" if kind == "kill" else kind, step, params)


def parse_faults(specs: List[str], env: Optional[str] = None) -> List[Fault]:
    """Config specs + the ``DS_FAULTS`` env var (``;``-separated)."""
    merged = list(specs or [])
    env_val = os.environ.get(env or "DS_FAULTS", "")
    merged += [s for s in env_val.split(";") if s.strip()]
    return [parse_fault(s) for s in merged]


class FaultInjector:
    """Engine-driven: ``apply(step, batch)`` runs at the top of every
    ``train_step`` and fires any fault scheduled for that step."""

    def __init__(self, faults: List[Fault], rank: Optional[int] = None,
                 recorder: Any = None,
                 sleep: Any = time.sleep):
        self.faults = list(faults)
        #: explicit rank wins; else resolved lazily from the launcher
        #: env at fire time (the elastic agent exports PROCESS_ID after
        #: rendezvous, which may be AFTER engine construction)
        self._rank = rank
        self.recorder = recorder
        self._sleep = sleep
        self.injected = 0
        #: ``node_join`` harness hook: cb(delay_s) launches the joining
        #: node (a chaos-test thread, an operator script).  Without one
        #: the fault falls back to bumping the rendezvous round after
        #: ``delay_s`` — to the running gang a join ATTEMPT and a flap
        #: look identical (a reseal), which is exactly what the settle
        #: window chaos tests need.
        self._node_join_cb: Optional[Any] = None
        #: ``kill_store``/``restart_store`` harness hooks — without
        #: them the faults act directly (SIGKILL the pid from params/
        #: DS_STORE_PID; spawn the standalone store module)
        self._store_kill_cb: Optional[Any] = None
        self._store_restart_cb: Optional[Any] = None

    def on_node_join(self, cb: Any) -> None:
        """Register the ``node_join`` callback: ``cb(delay_s)`` runs on
        a daemon timer thread when the fault fires."""
        self._node_join_cb = cb

    def on_store_kill(self, cb: Any) -> None:
        """Register the ``kill_store`` callback: ``cb()`` kills the
        store (in-process harnesses shut their server object down)."""
        self._store_kill_cb = cb

    def on_store_restart(self, cb: Any) -> None:
        """Register the ``restart_store`` callback: ``cb()`` brings the
        store back at its endpoint."""
        self._store_restart_cb = cb

    @classmethod
    def from_config(cls, rcfg: Any, recorder: Any = None
                    ) -> Optional["FaultInjector"]:
        faults = parse_faults(list(rcfg.faults or []))
        if not faults:
            return None
        return cls(faults, recorder=recorder)

    def rank(self) -> int:
        if self._rank is not None:
            return int(self._rank)
        env = os.environ.get("PROCESS_ID")
        if env:
            try:
                return int(env)
            except ValueError:
                pass  # malformed launcher env — fall through
        try:
            import jax

            return int(jax.process_index())
        except Exception:
            return 0

    # -- firing ------------------------------------------------------------

    def _record(self, fault: Fault) -> None:
        fault.fired = True
        self.injected += 1
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "resilience/faults_injected_total",
            help="deterministic faults fired by the injection harness")
        if self.recorder is not None:
            try:
                self.recorder.annotate("fault_injected",
                                       {"fault": repr(fault)})
            except Exception as e:  # annotation must not mask the fault
                from ..utils.logging import debug_once

                debug_once("faults/annotate",
                           f"fault annotation failed ({e!r})")
        logger.warning(f"fault injection: firing {fault!r}")

    def apply(self, step: int, batch: Any, engine: Any = None) -> Any:
        """Fire every not-yet-fired fault scheduled for ``step``;
        returns the (possibly poisoned) batch."""
        for fault in self.faults:
            if fault.fired or fault.step != step:
                continue
            if fault.kind in ("kill_rank", "node_leave", "node_join",
                              "kill_store", "restart_store",
                              "partition_node", "sigstop_hang"):
                want = fault.params.get("rank")
                if want is not None and int(want) != self.rank():
                    fault.fired = True  # this step is this fault's only shot
                    continue
            if fault.kind == "kill_rank":
                self._record(fault)
                if fault.params.get("mode", "raise") == "exit":
                    # a real SIGKILL-ish death: no cleanup, exit code 113
                    # for the supervisor to count as a failure
                    os._exit(113)
                raise InjectedFault(
                    f"injected worker death at step {step} "
                    f"(rank {self.rank()})")
            if fault.kind == "node_leave":
                self._record(fault)
                if os.environ.get("DS_ELASTIC_SUBPROCESS") == "1":
                    # supervised subprocess: a raised exception would
                    # surface as exit code 1 (a budgeted failure) — use
                    # the well-known leave code the agent maps back
                    os._exit(NODE_LEAVE_EXIT_CODE)
                raise NodeLeaveRequested(
                    f"injected node leave at step {step} "
                    f"(rank {self.rank()})")
            if fault.kind == "stall":
                self._record(fault)
                self._sleep(float(fault.params.get("seconds", 60.0)))
            elif fault.kind == "kill_store":
                self._record(fault)
                self._fire_kill_store(fault)
            elif fault.kind == "restart_store":
                self._record(fault)
                self._fire_restart_store(fault)
            elif fault.kind == "partition_node":
                self._record(fault)
                self._fire_partition(
                    float(fault.params.get("seconds", 10.0)))
            elif fault.kind == "sigstop_hang":
                self._record(fault)
                self._fire_sigstop(
                    float(fault.params.get("seconds", 5.0)))
            elif fault.kind == "nan_loss":
                self._record(fault)
                batch = _poison_batch(batch)
            elif fault.kind == "nan_params":
                self._record(fault)
                _poison_params(engine,
                               int(fault.params.get("layer", 0)))
            elif fault.kind == "node_join":
                self._record(fault)
                self._fire_node_join(
                    float(fault.params.get("delay_s", 0.0)), engine)
            elif fault.kind == "corrupt_snapshot":
                self._record(fault)
                self._fire_corrupt_snapshot(fault, engine)
        return batch

    def _fire_node_join(self, delay_s: float, engine: Any) -> None:
        """Launch the join after ``delay_s`` on a daemon timer: the
        registered harness callback when present, else a rendezvous
        round bump through the engine's attached store client (a join
        attempt IS a reseal to the running gang)."""
        import threading

        cb = self._node_join_cb
        rdzv = None
        if cb is None:
            snaps = getattr(engine, "snapshots", None) \
                if engine is not None else None
            rdzv = getattr(snaps, "_rdzv", None) if snaps else None
            if rdzv is None:
                logger.warning(
                    "fault injection: node_join fired but no harness "
                    "callback is registered (FaultInjector.on_node_join) "
                    "and the engine has no rendezvous — fault had no "
                    "effect")
                return

        def fire():
            try:
                if cb is not None:
                    cb(delay_s)
                else:
                    rdzv.bump_round("injected node_join")
            except Exception as e:
                logger.warning(f"fault injection: node_join action "
                               f"failed: {e!r}")

        t = threading.Timer(max(delay_s, 0.0), fire)
        t.daemon = True
        t.start()

    # -- process-level chaos (ISSUE 11 tentpole c) --------------------------

    def _fire_kill_store(self, fault: Fault) -> None:
        """``kill_store``: SIGKILL the rendezvous store process — the
        exact failure the store-failover tentpole exists for.  The gang
        must keep training (degraded mode) and re-seed a restarted
        store from its write-journals."""
        if self._store_kill_cb is not None:
            try:
                self._store_kill_cb()
            except Exception as e:
                logger.warning(f"fault injection: kill_store callback "
                               f"failed: {e!r}")
            return
        pid_s = fault.params.get("pid") or os.environ.get("DS_STORE_PID")
        if not pid_s:
            logger.warning("fault injection: kill_store needs a pid= "
                           "param, DS_STORE_PID, or an on_store_kill "
                           "callback — fault had no effect")
            return
        try:
            os.kill(int(pid_s), signal.SIGKILL)
            logger.warning(f"fault injection: SIGKILLed rendezvous "
                           f"store pid {pid_s}")
        except (OSError, ValueError) as e:
            logger.warning(f"fault injection: kill_store pid {pid_s!r} "
                           f"failed: {e!r}")

    def _fire_restart_store(self, fault: Fault) -> None:
        """``restart_store``: bring the store back at its endpoint
        after ``delay_s`` — the other half of the kill_store drill
        (journal replay re-seeds it from the survivors)."""
        import threading

        delay_s = float(fault.params.get("delay_s", 0.0))
        cb = self._store_restart_cb
        endpoint = (fault.params.get("endpoint")
                    or os.environ.get("DS_RDZV_ENDPOINT"))

        def fire():
            try:
                if cb is not None:
                    cb()
                    return
                if not endpoint:
                    logger.warning(
                        "fault injection: restart_store has no endpoint "
                        "(param/DS_RDZV_ENDPOINT) and no callback — "
                        "fault had no effect")
                    return
                # detached so the store outlives this worker; its own
                # readiness line goes to the worker's log
                subprocess.Popen(
                    [sys.executable, "-m",
                     "deepspeed_tpu.elasticity.store",
                     "--endpoint", str(endpoint)],
                    start_new_session=True)
                logger.warning(f"fault injection: respawned rendezvous "
                               f"store at {endpoint}")
            except Exception as e:
                logger.warning(f"fault injection: restart_store failed: "
                               f"{e!r}")

        t = threading.Timer(max(delay_s, 0.0), fire)
        t.daemon = True
        t.start()

    def _fire_partition(self, seconds: float) -> None:
        """``partition_node``: blackhole every live store client in
        THIS process for ``seconds`` — the node trains on, blind; its
        peers see its heartbeat go stale."""
        from ..elasticity.rendezvous import partition_all

        n = partition_all(seconds)
        if n:
            logger.warning(f"fault injection: partitioned {n} store "
                           f"client(s) for {seconds}s")
        else:
            logger.warning("fault injection: partition_node found no "
                           "live store client — fault had no effect")

    def _fire_sigstop(self, seconds: float) -> None:
        """``sigstop_hang``: a GENUINE OS-level hang — SIGSTOP this
        process (heartbeat threads included), with a detached helper
        re-CONTing it after ``seconds``.  Unlike ``stall`` (one thread
        sleeps), this freezes everything: exactly what a peer's
        heartbeat-ttl machinery must catch."""
        pid = os.getpid()
        try:
            subprocess.Popen(
                ["/bin/sh", "-c",
                 f"sleep {max(seconds, 0.1)}; kill -CONT {pid}"],
                start_new_session=True)
        except OSError as e:
            logger.warning(f"fault injection: sigstop_hang helper spawn "
                           f"failed ({e!r}) — NOT stopping (nobody "
                           f"would resume us)")
            return
        logger.warning(f"fault injection: SIGSTOPping pid {pid} for "
                       f"{seconds}s")
        os.kill(pid, signal.SIGSTOP)

    def _fire_corrupt_snapshot(self, fault: Fault, engine: Any) -> None:
        """``corrupt_snapshot[:tier=0|1|2]`` — tier 1 (default) flips
        bytes in the newest committed flush; tier 0 poisons the newest
        in-memory buffer (the capture the next rollback restores first);
        tier 2 garbles the buddy replica in the store.  Together the
        three tiers prove the checksum/health-gated 0→1→2 fallback
        chain end to end."""
        tier = str(fault.params.get("tier", "1"))
        snaps = getattr(engine, "snapshots", None) if engine is not None \
            else None
        if tier == "0":
            if snaps is None:
                logger.warning("fault injection: corrupt_snapshot tier=0 "
                               "needs a live engine with snapshots — "
                               "fault had no effect")
                return
            corrupt_tier0_snapshot(
                snaps,
                all_buffers=fault.params.get("buffers") == "all")
            return
        if tier == "2":
            rdzv = getattr(snaps, "_rdzv", None) if snaps else None
            if rdzv is None:
                logger.warning("fault injection: corrupt_snapshot tier=2 "
                               "needs an attached rendezvous (buddy "
                               "tier) — fault had no effect")
                return
            if snaps is not None:
                snaps.wait()  # corrupt a COMMITTED replication
            corrupt_tier2_replica(rdzv.c,
                                  fault.params.get("node") or rdzv.node_id)
            return
        snap_dir = None
        if snaps is not None:
            snaps.wait()  # corrupt a COMMITTED flush
            snap_dir = snaps.snapshot_dir
        corrupt_newest_snapshot(fault.params.get("dir") or snap_dir or "")


def _poison_batch(batch: Any) -> Any:
    """NaN the first floating leaf — the loss of any reasonable model
    goes NaN with it, which is exactly the anomaly the health monitor
    and the recovery policy key on."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(batch)
    for i, leaf in enumerate(leaves):
        dt = getattr(leaf, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.inexact):
            leaves[i] = leaf * jnp.float32(float("nan")).astype(dt)
            return jax.tree.unflatten(treedef, leaves)
    logger.warning("fault injection: nan_loss found no floating batch "
                   "leaf to poison — fault had no effect")
    return batch


def _poison_params(engine: Any, layer: int) -> None:
    """NaN layer ``layer``'s slice of every stacked [L, ...] floating
    leaf under ``params["layers"]`` — the poison enters mid-model, so
    the numerics forensic capture must localize it to exactly this
    layer's first probe (the NaN-injection acceptance test's setup)."""
    import jax
    import jax.numpy as jnp

    st = getattr(engine, "state", None) if engine is not None else None
    params = getattr(st, "params", None)
    layers = params.get("layers") if isinstance(params, dict) else None
    if layers is None:
        logger.warning("fault injection: nan_params needs a live engine "
                       "with stacked params['layers'] — fault had no "
                       "effect")
        return
    poisoned = 0

    def poison(leaf):
        nonlocal poisoned
        dt = getattr(leaf, "dtype", None)
        if (dt is not None and jnp.issubdtype(dt, jnp.inexact)
                and getattr(leaf, "ndim", 0) >= 1
                and 0 <= layer < leaf.shape[0]):
            poisoned += 1
            return leaf.at[layer].set(jnp.float32(float("nan"))
                                      .astype(dt))
        return leaf

    new_layers = jax.tree.map(poison, layers)
    if not poisoned:
        logger.warning(f"fault injection: nan_params layer={layer} "
                       f"matched no stacked leaf — fault had no effect")
        return
    engine.state = st._replace(params=dict(params, layers=new_layers))
    logger.warning(f"fault injection: NaN'd layer {layer} across "
                   f"{poisoned} stacked param leaves")


def corrupt_tier0_snapshot(snapshots: Any,
                           all_buffers: bool = False) -> bool:
    """Poison tier-0 host buffers IN PLACE (NaN every floating leaf —
    params included, so the restored state is guaranteed
    un-trainable); ``all_buffers`` poisons BOTH double-buffer slots so
    a chaos run proves the full tier-0 -> tier-1 fallback.  Tier 0 has
    no checksum — the policy's unproven-restore machinery is the gate:
    a poisoned restore fails its first step, the buffer is discarded,
    and the NEXT rollback digs deeper.  Returns True when a buffer was
    poisoned."""
    import numpy as _np

    targets = snapshots.buffered() if all_buffers else \
        [snapshots.latest()]
    targets = [s for s in targets if s is not None]
    if not targets:
        logger.warning("fault injection: no tier-0 snapshot buffer to "
                       "corrupt — fault had no effect")
        return False
    import jax

    poisoned = 0

    def poison(leaf):
        nonlocal poisoned
        arr = _np.asarray(leaf)
        if _np.issubdtype(arr.dtype, _np.floating) and arr.size:
            poisoned += 1
            return _np.full_like(arr, _np.nan)  # device_get arrays can
        return leaf                             # be read-only: rebuild

    for snap in targets:
        snap.state = jax.tree.map(poison, snap.state)
    if poisoned:
        logger.warning(
            f"fault injection: poisoned {len(targets)} tier-0 buffer(s) "
            f"(newest step {targets[0].global_steps}, {poisoned} leaves)")
        return True
    logger.warning("fault injection: tier-0 buffer has no floating leaf "
                   "to poison — fault had no effect")
    return False


def corrupt_tier2_replica(client: Any, node_id: str) -> bool:
    """Garble ``node_id``'s tier-2 replica so every fetch fails the
    checksum gate and the resume path falls back cleanly (tier-2 is the
    LAST tier — a corrupt replica means 'no snapshot', never a crash).

    P2P layout: the store holds only index metadata, so the chaos
    poisons the published transport sha256 (every holder then fails the
    gate — the same observable failure as rotten bytes on every holder)
    AND, where a holder's copy is reachable on this filesystem (buddy
    ``recv/`` trees in single-box chaos runs), flips real bytes in it.
    Legacy store-chunk publications get their first chunk garbled as
    before.  Returns True when a replica existed."""
    import base64

    from .snapshot import RESIL_CHUNK_PREFIX, RESIL_META_KEY

    meta = client.get(RESIL_META_KEY.format(node=node_id))
    if not isinstance(meta, dict):
        logger.warning(f"fault injection: node {node_id!r} has no tier-2 "
                       f"replica in the store to corrupt")
        return False
    if "holders" in meta:
        poisoned = dict(meta)
        poisoned["sha256"] = "0" * 64
        try:
            client.set(RESIL_META_KEY.format(node=node_id), poisoned,
                       journal=True)
        except TypeError:
            client.set(RESIL_META_KEY.format(node=node_id), poisoned)
        # rot the buddy's physical copy too when it is reachable here
        # (never the owner's own dir — that would ALSO corrupt tier 1)
        for holder in meta.get("holders") or []:
            path = str(holder.get("path") or "")
            if holder.get("node") == meta.get("owner") or not path:
                continue
            if os.sep + "recv" + os.sep in path and os.path.isdir(path):
                corrupt_newest_snapshot(os.path.dirname(path))
        logger.warning(f"fault injection: corrupted tier-2 replica of "
                       f"{node_id!r} (transport checksum poisoned)")
        return True
    key = RESIL_CHUNK_PREFIX.format(node=node_id) + "/0"
    chunk = client.get(key) or ""
    garbage = base64.b64encode(os.urandom(max(len(chunk) // 2, 16))
                               ).decode("ascii")
    client.set(key, garbage)
    logger.warning(f"fault injection: corrupted tier-2 replica of "
                   f"{node_id!r} (chunk 0)")
    return True


def corrupt_newest_snapshot(snapshot_dir: str) -> Optional[str]:
    """Flip bytes in the newest committed snapshot's LARGEST payload
    file (never the manifests — the point is that the CHECKSUM catches
    it, not that the marker disappears).  Returns the corrupted file."""
    from .snapshot import SNAPSHOT_MANIFEST, list_snapshots

    snaps = list_snapshots(snapshot_dir)
    if not snaps:
        logger.warning(f"fault injection: no committed snapshot under "
                       f"{snapshot_dir!r} to corrupt")
        return None
    root = snaps[0]["path"]
    candidates = []
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            if f in (SNAPSHOT_MANIFEST, "ds_manifest.json"):
                continue
            p = os.path.join(dirpath, f)
            candidates.append((os.path.getsize(p), p))
    if not candidates:
        return None
    _, victim = max(candidates)
    with open(victim, "r+b") as fh:
        data = fh.read(64)
        fh.seek(0)
        fh.write(bytes(b ^ 0xFF for b in data))
    logger.warning(f"fault injection: corrupted {victim}")
    return victim
