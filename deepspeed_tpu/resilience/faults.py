"""Deterministic fault injection — make the recovery loop PROVABLE.

ISSUE 4 tentpole, pillar 3.  A resilience plane nobody can trigger is a
resilience plane nobody can trust: this harness injects the exact
failures the policy claims to survive, deterministically (fault specs
name a step, not a probability), driven by config
(``resilience.faults``) or the ``DS_FAULTS`` env var so CI and chaos
drills run the SAME loop production would.

Spec grammar (comma-free ``kind@step[:key=value,...]``)::

    kill_rank@120:rank=1         # worker death at step 120 on rank 1
    kill_rank@120:rank=1,mode=exit   # hard os._exit instead of raising
    nan_loss@64                  # poison step 64's batch with NaN
    stall@32:seconds=90          # stall the step path (watchdog food)
    corrupt_snapshot@40          # flip bytes in the newest tier-1 snap

Faults fire ONCE (per process) at the step they name; ``rank=`` guards
restrict kill faults to one worker.  Every firing lands in telemetry
(``resilience/faults_injected_total``) and the flight recorder, so a
chaos run's debug bundle says what was injected, where.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import logger

KINDS = ("kill_rank", "kill", "nan_loss", "stall", "corrupt_snapshot")


class InjectedFault(RuntimeError):
    """A kill fault fired in ``raise`` mode — the supervisor (elastic
    agent) sees a worker failure exactly as it would a real crash."""


class Fault:
    __slots__ = ("kind", "step", "params", "fired")

    def __init__(self, kind: str, step: int, params: Dict[str, str]):
        self.kind = kind
        self.step = int(step)
        self.params = params
        self.fired = False

    def __repr__(self):
        kv = ",".join(f"{k}={v}" for k, v in self.params.items())
        return f"{self.kind}@{self.step}" + (f":{kv}" if kv else "")


def parse_fault(spec: str) -> Fault:
    """``kind@step[:k=v,...]`` → :class:`Fault`; raises ``ValueError``
    with the offending spec on any malformation (a chaos drill with a
    typo'd spec must fail loudly, not silently not inject)."""
    text = spec.strip()
    head, _, tail = text.partition(":")
    kind, at, step_s = head.partition("@")
    if not at or not kind or not step_s:
        raise ValueError(f"fault spec {spec!r}: expected kind@step[:k=v,...]")
    if kind not in KINDS:
        raise ValueError(f"fault spec {spec!r}: unknown kind {kind!r} "
                         f"(known: {', '.join(KINDS)})")
    try:
        step = int(step_s)
    except ValueError:
        raise ValueError(f"fault spec {spec!r}: step {step_s!r} is not an "
                         f"integer")
    params: Dict[str, str] = {}
    if tail:
        for part in tail.split(","):
            k, eq, v = part.partition("=")
            if not eq or not k:
                raise ValueError(f"fault spec {spec!r}: bad param "
                                 f"{part!r} (expected key=value)")
            params[k.strip()] = v.strip()
    return Fault("kill_rank" if kind == "kill" else kind, step, params)


def parse_faults(specs: List[str], env: Optional[str] = None) -> List[Fault]:
    """Config specs + the ``DS_FAULTS`` env var (``;``-separated)."""
    merged = list(specs or [])
    env_val = os.environ.get(env or "DS_FAULTS", "")
    merged += [s for s in env_val.split(";") if s.strip()]
    return [parse_fault(s) for s in merged]


class FaultInjector:
    """Engine-driven: ``apply(step, batch)`` runs at the top of every
    ``train_step`` and fires any fault scheduled for that step."""

    def __init__(self, faults: List[Fault], rank: Optional[int] = None,
                 recorder: Any = None,
                 sleep: Any = time.sleep):
        self.faults = list(faults)
        #: explicit rank wins; else resolved lazily from the launcher
        #: env at fire time (the elastic agent exports PROCESS_ID after
        #: rendezvous, which may be AFTER engine construction)
        self._rank = rank
        self.recorder = recorder
        self._sleep = sleep
        self.injected = 0

    @classmethod
    def from_config(cls, rcfg: Any, recorder: Any = None
                    ) -> Optional["FaultInjector"]:
        faults = parse_faults(list(rcfg.faults or []))
        if not faults:
            return None
        return cls(faults, recorder=recorder)

    def rank(self) -> int:
        if self._rank is not None:
            return int(self._rank)
        env = os.environ.get("PROCESS_ID")
        if env:
            try:
                return int(env)
            except ValueError:
                pass  # malformed launcher env — fall through
        try:
            import jax

            return int(jax.process_index())
        except Exception:
            return 0

    # -- firing ------------------------------------------------------------

    def _record(self, fault: Fault) -> None:
        fault.fired = True
        self.injected += 1
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "resilience/faults_injected_total",
            help="deterministic faults fired by the injection harness")
        if self.recorder is not None:
            try:
                self.recorder.annotate("fault_injected",
                                       {"fault": repr(fault)})
            except Exception as e:  # annotation must not mask the fault
                from ..utils.logging import debug_once

                debug_once("faults/annotate",
                           f"fault annotation failed ({e!r})")
        logger.warning(f"fault injection: firing {fault!r}")

    def apply(self, step: int, batch: Any, engine: Any = None) -> Any:
        """Fire every not-yet-fired fault scheduled for ``step``;
        returns the (possibly poisoned) batch."""
        for fault in self.faults:
            if fault.fired or fault.step != step:
                continue
            if fault.kind == "kill_rank":
                want = fault.params.get("rank")
                if want is not None and int(want) != self.rank():
                    fault.fired = True  # this step is this fault's only shot
                    continue
                self._record(fault)
                if fault.params.get("mode", "raise") == "exit":
                    # a real SIGKILL-ish death: no cleanup, exit code 113
                    # for the supervisor to count as a failure
                    os._exit(113)
                raise InjectedFault(
                    f"injected worker death at step {step} "
                    f"(rank {self.rank()})")
            if fault.kind == "stall":
                self._record(fault)
                self._sleep(float(fault.params.get("seconds", 60.0)))
            elif fault.kind == "nan_loss":
                self._record(fault)
                batch = _poison_batch(batch)
            elif fault.kind == "corrupt_snapshot":
                self._record(fault)
                snap_dir = None
                if engine is not None and getattr(engine, "snapshots",
                                                  None) is not None:
                    engine.snapshots.wait()  # corrupt a COMMITTED flush
                    snap_dir = engine.snapshots.snapshot_dir
                corrupt_newest_snapshot(
                    fault.params.get("dir") or snap_dir or "")
        return batch


def _poison_batch(batch: Any) -> Any:
    """NaN the first floating leaf — the loss of any reasonable model
    goes NaN with it, which is exactly the anomaly the health monitor
    and the recovery policy key on."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(batch)
    for i, leaf in enumerate(leaves):
        dt = getattr(leaf, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.inexact):
            leaves[i] = leaf * jnp.float32(float("nan")).astype(dt)
            return jax.tree.unflatten(treedef, leaves)
    logger.warning("fault injection: nan_loss found no floating batch "
                   "leaf to poison — fault had no effect")
    return batch


def corrupt_newest_snapshot(snapshot_dir: str) -> Optional[str]:
    """Flip bytes in the newest committed snapshot's LARGEST payload
    file (never the manifests — the point is that the CHECKSUM catches
    it, not that the marker disappears).  Returns the corrupted file."""
    from .snapshot import SNAPSHOT_MANIFEST, list_snapshots

    snaps = list_snapshots(snapshot_dir)
    if not snaps:
        logger.warning(f"fault injection: no committed snapshot under "
                       f"{snapshot_dir!r} to corrupt")
        return None
    root = snaps[0]["path"]
    candidates = []
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            if f in (SNAPSHOT_MANIFEST, "ds_manifest.json"):
                continue
            p = os.path.join(dirpath, f)
            candidates.append((os.path.getsize(p), p))
    if not candidates:
        return None
    _, victim = max(candidates)
    with open(victim, "r+b") as fh:
        data = fh.read(64)
        fh.seek(0)
        fh.write(bytes(b ^ 0xFF for b in data))
    logger.warning(f"fault injection: corrupted {victim}")
    return victim
