"""Self-healing resilience plane (ISSUE 4 + ISSUE 11).

Three pillars that turn the observability stack's DETECTIONS (watchdog
trips, NaN'd losses, dead peers) into a bounded amount of lost work:

* :mod:`.snapshot` — tiered async snapshots of the full training state
  (tier 0 host memory, tier 1 checksummed disk flush through the
  checkpoint engine, tier 2 **peer-to-peer** replication: each node's
  :mod:`.replica_server` serves its flushed dirs and pushes a copy to
  its ring buddy; the rendezvous store carries index/placement metadata
  only, so store loss never invalidates the tier).
* :mod:`.policy` — the automatic recovery state machine: rollback on
  NaN/loss-scale collapse with the offending data window skipped,
  emergency-save on watchdog trip, resume-from-newest-valid-snapshot on
  elastic restart, capped backoff + give-up budget.
* :mod:`.faults` — deterministic, config/env-driven fault injection
  (kill a rank, stall a step, NaN the loss, corrupt a snapshot tier,
  kill/restart the rendezvous store, partition a node, SIGSTOP-hang a
  worker) so the whole loop — control plane included — is provable in
  CI.

Operator CLI: ``python -m deepspeed_tpu.resilience
{ls,verify,replicas,fetch,faults}``.
"""

from .faults import (FAULT_DOCS, Fault, FaultInjector, InjectedFault,
                     NodeLeaveRequested, corrupt_newest_snapshot,
                     corrupt_tier0_snapshot, corrupt_tier2_replica,
                     parse_fault, parse_faults)
from .policy import (RecoveryPolicy, ResilienceGiveUp, ST_GAVE_UP,
                     ST_RECOVERING, ST_RUNNING)
from .replica_server import (ReplicaServer, fetch_replica,
                             get_local_server, push_replica,
                             set_local_server)
from .snapshot import (MeshMismatchError, Snapshot, SnapshotManager,
                       SnapshotUnsupportedError, adopt_orphaned_replica,
                       bootstrap_from_peer_replica, check_reshardable,
                       check_snapshot_support, choose_resume_snapshot,
                       fetch_buddy_snapshot, format_topology,
                       list_snapshots, replicate_snapshot, verify_snapshot)

__all__ = [
    "Snapshot", "SnapshotManager", "SnapshotUnsupportedError",
    "MeshMismatchError", "check_reshardable", "format_topology",
    "check_snapshot_support", "choose_resume_snapshot",
    "adopt_orphaned_replica", "bootstrap_from_peer_replica",
    "list_snapshots", "verify_snapshot", "replicate_snapshot",
    "fetch_buddy_snapshot",
    "ReplicaServer", "get_local_server", "set_local_server",
    "fetch_replica", "push_replica",
    "RecoveryPolicy", "ResilienceGiveUp",
    "ST_RUNNING", "ST_RECOVERING", "ST_GAVE_UP",
    "Fault", "FaultInjector", "InjectedFault", "NodeLeaveRequested",
    "FAULT_DOCS", "parse_fault", "parse_faults",
    "corrupt_newest_snapshot", "corrupt_tier0_snapshot",
    "corrupt_tier2_replica",
]
