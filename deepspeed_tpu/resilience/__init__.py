"""Self-healing resilience plane (ISSUE 4).

Three pillars that turn the observability stack's DETECTIONS (watchdog
trips, NaN'd losses, dead peers) into a bounded amount of lost work:

* :mod:`.snapshot` — tiered async snapshots of the full training state
  (tier 0 host memory, tier 1 checksummed disk flush through the
  checkpoint engine, tier 2 buddy-host replication over the rendezvous
  store).
* :mod:`.policy` — the automatic recovery state machine: rollback on
  NaN/loss-scale collapse with the offending data window skipped,
  emergency-save on watchdog trip, resume-from-newest-valid-snapshot on
  elastic restart, capped backoff + give-up budget.
* :mod:`.faults` — deterministic, config/env-driven fault injection
  (kill a rank, stall a step, NaN the loss, corrupt a snapshot) so the
  whole loop is provable in CI.

Operator CLI: ``python -m deepspeed_tpu.resilience {ls,verify}``.
"""

from .faults import (Fault, FaultInjector, InjectedFault,
                     NodeLeaveRequested, corrupt_newest_snapshot,
                     corrupt_tier0_snapshot, corrupt_tier2_replica,
                     parse_fault, parse_faults)
from .policy import (RecoveryPolicy, ResilienceGiveUp, ST_GAVE_UP,
                     ST_RECOVERING, ST_RUNNING)
from .snapshot import (MeshMismatchError, Snapshot, SnapshotManager,
                       SnapshotUnsupportedError, adopt_orphaned_replica,
                       bootstrap_from_peer_replica, check_reshardable,
                       check_snapshot_support, choose_resume_snapshot,
                       fetch_buddy_snapshot, format_topology,
                       list_snapshots, replicate_snapshot, verify_snapshot)

__all__ = [
    "Snapshot", "SnapshotManager", "SnapshotUnsupportedError",
    "MeshMismatchError", "check_reshardable", "format_topology",
    "check_snapshot_support", "choose_resume_snapshot",
    "adopt_orphaned_replica", "bootstrap_from_peer_replica",
    "list_snapshots", "verify_snapshot", "replicate_snapshot",
    "fetch_buddy_snapshot",
    "RecoveryPolicy", "ResilienceGiveUp",
    "ST_RUNNING", "ST_RECOVERING", "ST_GAVE_UP",
    "Fault", "FaultInjector", "InjectedFault", "NodeLeaveRequested",
    "parse_fault", "parse_faults", "corrupt_newest_snapshot",
    "corrupt_tier0_snapshot", "corrupt_tier2_replica",
]
