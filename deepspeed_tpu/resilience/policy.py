"""Automatic recovery policy — the state machine that ACTS on failures.

ISSUE 4 tentpole, pillar 2.  PRs 1–3 can *name* a failure (watchdog
trip, NaN'd loss, desynced collective, dead peer); this module turns
the detection into a bounded amount of lost work:

* **NaN/Inf loss or fp16 loss-scale collapse** → roll back to the last
  good snapshot (tier 0 → tier 1 → tier 2 fallback, checksum-gated) and
  SKIP the offending data window — the batches consumed between the
  snapshot and the failure are not refed, because refeeding the batch
  that NaN'd the loss would NaN it again.
* **Hang (watchdog trip)** → emergency-save-if-responsive: flush the
  newest tier-0 host copy through a SYNC writer from the watchdog
  thread, so the supervisor's kill that usually follows a trip costs at
  most ``snapshot_interval`` steps.
* **Crash / worker exit** → the elastic agent restarts the worker
  (capped exponential backoff); on re-entry
  :meth:`RecoveryPolicy.resume_if_restarted` loads the newest VALID
  snapshot — falling back across tiers when the newest is torn or
  corrupt — and training continues from there.

Every recovery consumes a budget: capped exponential backoff between
recoveries, and after ``max_recoveries`` within the reset window the
policy raises :class:`ResilienceGiveUp` — at some point a human has to
look.  All transitions land in telemetry counters and flight-recorder
annotations, so the debug bundle of a recovered run TELLS the story.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import log_dist, logger
from .snapshot import Snapshot, SnapshotManager, choose_resume_snapshot

#: policy states (exposed for tests/operators; the machine is linear)
ST_RUNNING = "running"
ST_RECOVERING = "recovering"
ST_GAVE_UP = "gave_up"


class ResilienceGiveUp(RuntimeError):
    """The recovery budget is exhausted (or no valid snapshot exists) —
    the run needs a human."""


class RecoveryPolicy:
    """Subscribed to the engine's step metrics/health events and the
    watchdog's trip edge; owns rollback, resume, backoff, and give-up."""

    def __init__(self, engine: Any, snapshots: SnapshotManager, cfg: Any,
                 recorder: Any = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.engine = engine
        self.snapshots = snapshots
        self.cfg = cfg
        self.recorder = recorder
        self._clock = clock
        self._sleep = sleep
        self.rollback_on = set(cfg.rollback_on or [])
        self.max_recoveries = int(cfg.max_recoveries)
        self.backoff_base_s = float(cfg.backoff_base_s)
        self.backoff_max_s = float(cfg.backoff_max_s)
        self.recovery_reset_steps = int(cfg.recovery_reset_steps)
        self.state = ST_RUNNING
        self.recoveries = 0        # within the current reset window
        self.rollbacks_total = 0
        self.resumes_total = 0
        self._last_recovery_step = -1
        #: True between a rollback and the next HEALTHY step: a second
        #: failure in that window means the restored snapshot itself is
        #: suspect (e.g. params already NaN under a still-finite loss)
        #: and the next rollback must dig DEEPER instead of re-restoring
        #: the same poisoned capture until the budget burns out
        self._unproven_restore = False

    # -- budget ------------------------------------------------------------

    def _charge_recovery(self, kind: str) -> None:
        """One recovery against the budget: capped exponential backoff,
        then give up past ``max_recoveries``.  The budget re-arms after
        ``recovery_reset_steps`` healthy steps (a run that hits one NaN
        a week must not die on the 4th week)."""
        self.recoveries += 1
        if self.recoveries > self.max_recoveries:
            self.state = ST_GAVE_UP
            self._annotate("resilience_give_up",
                           {"trigger": kind, "recoveries": self.recoveries})
            self._counter("resilience/give_ups_total",
                          "recovery budget exhaustions")
            raise ResilienceGiveUp(
                f"resilience: giving up after {self.recoveries - 1} "
                f"recoveries within {self.recovery_reset_steps} steps "
                f"(last trigger: {kind}) — the failure is not transient")
        delay = min(self.backoff_base_s * (2 ** (self.recoveries - 1)),
                    self.backoff_max_s)
        log_dist(f"resilience: recovery #{self.recoveries} ({kind}); "
                 f"backing off {delay:.2f}s")
        self._sleep(delay)
        self._last_recovery_step = self.engine.global_steps

    def _maybe_rearm(self) -> None:
        if (self.recoveries
                and self.engine.global_steps - self._last_recovery_step
                >= self.recovery_reset_steps):
            self.recoveries = 0

    # -- step observation (engine hot path) --------------------------------

    def observe_step(self, metrics: Dict[str, Any],
                     health_events: Optional[List[Any]] = None) -> bool:
        """Called by ``train_step`` after every optimizer step.  Returns
        True when the step triggered a rollback (the engine then skips
        its post-step snapshot — the state was just REWOUND).

        The loss check pulls the scalar (a device sync): resilience
        deliberately trades dispatch/execute overlap for the ability to
        catch the NaN before it propagates another ``snapshot_interval``
        steps.
        """
        if self.state == ST_GAVE_UP:
            return False
        self._maybe_rearm()
        trigger = None
        if "nan_loss" in self.rollback_on:
            loss = float(metrics.get("loss", 0.0))
            if not math.isfinite(loss):
                detail = f"non-finite loss {loss}"
                # the numerics plane's forensic capture (run by the
                # engine before this observe) localized the poison —
                # the rollback NAMES the first bad layer
                report = getattr(self.engine, "_last_nonfinite_report",
                                 None)
                if report is not None and getattr(report, "first_layer",
                                                  ""):
                    detail += (f"; first non-finite tensor: "
                               f"'{report.report.get('first_nonfinite')}'"
                               f" (layer {report.first_layer})")
                trigger = ("nan_loss", detail)
        if trigger is None and health_events:
            for ev in health_events:
                kind = getattr(ev, "kind", None)
                if kind in self.rollback_on:
                    trigger = (kind, getattr(ev, "message", kind))
                    break
        if trigger is None:
            self._unproven_restore = False  # a healthy step vindicates it
            return False
        self.rollback(kind=trigger[0], detail=trigger[1])
        return True

    # -- rollback ----------------------------------------------------------

    def rollback(self, kind: str = "manual", detail: str = "") -> None:
        """Restore the last good snapshot and skip the offending data
        window.  Tier fallback: tier-0 buffers (newest first) → newest
        valid tier-1 dir → tier-2 buddy replica."""
        eng = self.engine
        failed_step = eng.global_steps
        t_rollback0 = self._clock()
        self.state = ST_RECOVERING
        if self._unproven_restore:
            # the snapshot restored by the PREVIOUS rollback failed
            # again without a single healthy step in between — burn it
            # and fall back to the next-older capture
            burned = self.snapshots.discard_newest()
            if burned is not None:
                logger.warning(
                    f"resilience: snapshot at step {burned.global_steps} "
                    f"failed immediately after restore — discarding it "
                    f"and falling back to an older one")
        # locate the snapshot BEFORE charging the budget: when nothing
        # is restorable there is no point sleeping a backoff first
        snap, applied = self._best_snapshot()
        if snap is None:
            self.state = ST_GAVE_UP
            raise ResilienceGiveUp(
                "resilience: rollback requested but no valid snapshot "
                "exists in any tier (memory/disk/buddy)")
        self._charge_recovery(kind)  # may raise ResilienceGiveUp
        if not applied:  # tier-1/2 loads land applied; don't re-put
            self.snapshots.restore(snap)
        self._unproven_restore = True
        skipped = failed_step - eng.global_steps
        if getattr(eng, "health", None) is not None:
            # the health windows saw the anomaly; replayed steps must be
            # judged against a fresh baseline
            eng.health.reset_windows()
        self.rollbacks_total += 1
        self._counter("resilience/rollbacks_total",
                      "automatic rollbacks to a snapshot")
        self._counter("resilience/steps_skipped_total",
                      "training steps lost to rollbacks (the skipped "
                      "data window)", v=max(skipped, 0))
        self._charge_goodput_recovery(failed_step, skipped, t_rollback0)
        ann = {
            "trigger": kind, "detail": detail, "failed_step": failed_step,
            "restored_step": eng.global_steps,
            "skipped_window": [eng.global_steps + 1, failed_step]}
        report = getattr(eng, "_last_nonfinite_report", None)
        if kind == "nan_loss" and report is not None:
            # forensic localization rides the annotation (and was already
            # dumped as numerics.json in the forensics bundle)
            ann["first_nonfinite"] = report.report.get("first_nonfinite", "")
            ann["first_layer"] = report.first_layer
            ann["numerics_bundle"] = report.bundle_path
            eng._last_nonfinite_report = None  # consumed by this rollback
        self._annotate("resilience_rollback", ann)
        logger.warning(
            f"resilience: rolled back {kind} at step {failed_step} -> "
            f"step {eng.global_steps}; data window "
            f"({eng.global_steps + 1}..{failed_step}) skipped")
        self.state = ST_RUNNING

    def _charge_goodput_recovery(self, failed_step: int, skipped: int,
                                 t_rollback0: float) -> None:
        """Account the rollback in the goodput ledger (telemetry/perf):
        the rollback/backoff wall time goes to the ``recovery`` bucket,
        and the skipped window's step time — charged ``productive`` as
        those steps ran — is RECLASSIFIED to ``recovery``: the rollback
        just proved that work was lost."""
        try:
            from ..telemetry.perf import get_goodput_ledger

            gp = get_goodput_ledger()
            if not gp.enabled:
                return
            gp.add("recovery", max(self._clock() - t_rollback0, 0.0))
            lost_prod_s = lost_compile_s = 0.0
            records = getattr(self.engine, "step_records", None) or []
            window = {failed_step - i for i in range(max(skipped, 0))}
            for rec in records:
                if rec.step not in window:
                    continue
                # split like add_step did: the compile share of a lost
                # step was charged "compile", not "productive" — each
                # bucket gives back exactly what it was credited
                step_s = float(rec.step_time_ms) / 1e3
                comp_s = min(float(rec.extra.get("compile_ms", 0.0) or 0.0)
                             / 1e3, step_s)
                lost_compile_s += comp_s
                lost_prod_s += step_s - comp_s
            if lost_prod_s > 0.0:
                gp.reclassify("productive", "recovery", lost_prod_s)
            if lost_compile_s > 0.0:
                gp.reclassify("compile", "recovery", lost_compile_s)
        except Exception as e:
            logger.debug(f"resilience: goodput accounting failed: {e!r}")

    def _best_snapshot(self) -> tuple:
        """Newest restorable snapshot across tiers, as ``(snap,
        applied)`` — ``applied`` is True when locating it ALREADY loaded
        it into the engine (the disk path restores in place; repeating
        the multi-GB device_put and the restore hooks would double
        recovery cost)."""
        for snap in self.snapshots.buffered():  # tier 0, newest first
            return snap, False
        path = self._choose_disk_snapshot()
        if path is not None:
            try:
                return self.snapshots.load_from_disk(path), True
            except Exception as e:
                logger.error(f"resilience: tier-1 restore of {path} "
                             f"failed: {e!r}")
        return None, False

    def _choose_disk_snapshot(self) -> Optional[str]:
        self.snapshots.wait()  # join any in-flight flush first
        rdzv = self.snapshots._rdzv
        # rdzv unlocks the replacement-node fallbacks (adopt a dead
        # peer's orphaned replica via the sealed-ring diff, bootstrap a
        # scale-up joiner from a live peer) — the adopted snapshot lands
        # in the local dir, so the policy treats it exactly as local
        return choose_resume_snapshot(
            self.snapshots.snapshot_dir,
            client=getattr(rdzv, "c", None),
            node_id=getattr(rdzv, "node_id", None),
            rdzv=rdzv if hasattr(rdzv, "ring_diff") else None)

    # -- restart/resume path ------------------------------------------------

    def resume_if_restarted(self, force: bool = False) -> Optional[str]:
        """Entry-point hook for the elastic restart path: when this
        worker is a RESTART (``DS_ELASTIC_RESTART_COUNT`` > 0, exported
        by the agent), a scale-up JOINER into a running gang
        (``DS_ELASTIC_JOINED_RUNNING``, exported when the rendezvous had
        to bump a sealed round to admit us) — or ``force`` — load the
        policy-chosen newest VALID snapshot from disk (buddy/adoption/
        bootstrap fallbacks included) and resume.  The load path
        reshards a snapshot taken on a different mesh onto the current
        one.  Returns the snapshot path used, or None (fresh start)."""
        restarts = int(os.environ.get("DS_ELASTIC_RESTART_COUNT", "0") or 0)
        joined = os.environ.get("DS_ELASTIC_JOINED_RUNNING", "") == "1"
        if not (force or restarts > 0 or joined):
            return None
        path = self._choose_disk_snapshot()
        if path is None:
            logger.warning(
                "resilience: restarted worker found NO valid snapshot "
                "in any tier — starting from step 0")
            self._annotate("resilience_resume",
                           {"restarts": restarts, "snapshot": None})
            return None
        self.snapshots.load_from_disk(path)
        self.resumes_total += 1
        self._counter("resilience/resumes_total",
                      "restarted workers resumed from a snapshot")
        self._annotate("resilience_resume", {
            "restarts": restarts, "snapshot": path,
            "resumed_step": self.engine.global_steps})
        log_dist(f"resilience: restart #{restarts} resumed from {path} "
                 f"at step {self.engine.global_steps}")
        return path

    # -- watchdog trip ------------------------------------------------------

    def on_watchdog_trip(self, reason: str,
                         bundle: Optional[str] = None) -> None:
        """Trip-edge listener (runs on the watchdog thread, BEFORE its
        configured action): the host is responsive enough to run this,
        so make the newest tier-0 copy durable — the supervisor kill
        that usually follows then costs ≤ one snapshot interval."""
        if not self.cfg.emergency_save_on_trip:
            return
        try:
            path = self.snapshots.emergency_flush()
            if path:
                log_dist(f"resilience: emergency snapshot at watchdog "
                         f"trip -> {path}")
        except Exception as e:
            logger.error(f"resilience: emergency save failed: {e!r}")

    # -- plumbing -----------------------------------------------------------

    def _counter(self, name: str, help_: str, v: float = 1.0) -> None:
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(name, v=v, help=help_)

    def _annotate(self, kind: str, payload: Dict[str, Any]) -> None:
        if self.recorder is not None:
            try:
                self.recorder.annotate(kind, payload)
            except Exception as e:  # diagnostics must not block recovery
                from ..utils.logging import debug_once

                debug_once("resilience/annotate",
                           f"recovery annotation '{kind}' failed ({e!r})")
