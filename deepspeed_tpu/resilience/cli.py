"""Operator CLI — ``python -m deepspeed_tpu.resilience <cmd>``.

The 3am read side of the resilience plane:

* ``ls <dir>``      — inventory the snapshot dir: tag, step, age,
  bytes, and whether each snapshot passes the checksum gate.
* ``verify <path>`` — full integrity check of one snapshot dir, or of
  every snapshot under a root dir.  Exit codes are scriptable: 0 when
  the NEWEST snapshot is valid, 3 when the newest is corrupt but an
  older valid one exists (a resume would silently lose extra steps —
  worth an alert), 4 when nothing restorable remains.

Both commands are plain-directory reads — no store, no engine, no
device needed beyond importing the package.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional

from .snapshot import list_snapshots, verify_snapshot


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 2


def _dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, f))
            except OSError:
                pass
    return total


def _is_snapshot(path: str) -> bool:
    from .snapshot import SNAPSHOT_MANIFEST

    return os.path.exists(os.path.join(path, SNAPSHOT_MANIFEST))


def cmd_ls(args: argparse.Namespace) -> int:
    snaps = list_snapshots(args.dir)
    if not snaps:
        print(f"no committed snapshots under {args.dir}")
        return 0
    now = time.time()
    print(f"{'TAG':<24} {'STEP':>8} {'AGE':>10} {'SIZE':>10}  STATUS")
    for entry in snaps:
        ok, detail = verify_snapshot(entry["path"])
        age = now - float(entry.get("ts") or now)
        size = _dir_bytes(entry["path"])
        status = "valid" if ok else f"CORRUPT — {detail}"
        print(f"{entry['tag']:<24} {entry['step']:>8} "
              f"{age:>9.0f}s {size / 2**20:>9.1f}M  {status}")
    return 0


def cmd_verify(args: argparse.Namespace) -> int:
    path = args.path
    if _is_snapshot(path):
        ok, detail = verify_snapshot(path)
        print(f"{path}: {'valid' if ok else 'CORRUPT'} — {detail}")
        return 0 if ok else 4
    if not os.path.isdir(path):
        return _fail(f"{path}: not a snapshot dir or snapshot root")
    snaps = list_snapshots(path)
    if not snaps:
        print(f"{path}: no committed snapshots")
        return 4
    results = [(entry, *verify_snapshot(entry["path"])) for entry in snaps]
    for entry, ok, detail in results:
        print(f"{entry['tag']}: {'valid' if ok else 'CORRUPT'} — {detail}")
    newest_ok = results[0][1]
    any_ok = any(ok for _e, ok, _d in results)
    if newest_ok:
        return 0
    if any_ok:
        print("WARNING: newest snapshot is corrupt; a resume would fall "
              "back to an older one (extra lost work)")
        return 3
    print("FATAL: no restorable snapshot remains")
    return 4


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.resilience",
        description="resilience plane operator CLI: inventory and "
                    "verify tiered training-state snapshots")
    sub = p.add_subparsers(dest="cmd", required=True)

    ls = sub.add_parser("ls", help="list committed snapshots with "
                                   "validity status")
    ls.add_argument("dir", nargs="?", default="resilience_snapshots")
    ls.set_defaults(fn=cmd_ls)

    v = sub.add_parser("verify",
                       help="checksum-verify one snapshot or a whole "
                            "snapshot dir (exit 0 newest-valid / 3 "
                            "fallback-only / 4 none)")
    v.add_argument("path")
    v.set_defaults(fn=cmd_verify)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)
