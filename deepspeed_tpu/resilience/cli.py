"""Operator CLI — ``python -m deepspeed_tpu.resilience <cmd>``.

The 3am read side of the resilience plane:

* ``ls <dir>``      — inventory the snapshot dir: tag, step, age,
  bytes, ORIGIN MESH (world@device [axes] from the manifest's topology
  stamp), and whether each snapshot passes the checksum gate.
* ``verify <path>`` — full integrity check of one snapshot dir, or of
  every snapshot under a root dir.  Exit codes are scriptable: 0 when
  the NEWEST snapshot is valid, 3 when the newest is corrupt but an
  older valid one exists (a resume would silently lose extra steps —
  worth an alert), 4 when nothing restorable remains.  With
  ``--target-mesh AxB`` the reshardability pre-check answers "can I
  resume this on that mesh?" OFFLINE — both topologies, the per-tier
  verdict, and the recorded state leaves' layout at the target dp —
  exit 3 when incompatible.
* ``replicas <dir>`` — inventory the peer-to-peer tier-2 replicas HELD
  under a replica-store root (own serving registrations live in the
  running process; this reads the on-disk ``recv/<owner>/<tag>`` trees
  plus any snapshot dirs), checksum-verifying each.  Exit 3 when any
  held replica is corrupt, 4 when none exists.
* ``fetch --endpoint H:P --owner NODE out_dir`` — pull a replica
  straight from a peer's replica server, **no store required**: the
  proof that tier 2 remains restorable with the store down.
* ``faults`` — the chaos catalogue: every fault kind the injection
  harness speaks (``kind@step[:k=v,...]``) with its parameters.

``ls``/``verify``/``replicas`` are plain-directory reads — no store, no
engine, no device needed beyond importing the package.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Any, Dict, List, Optional

from .snapshot import (check_reshardable, format_topology, list_snapshots,
                       read_snapshot_manifest, reshard_tier_report,
                       verify_snapshot)


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 2


def parse_target_mesh(spec: str) -> Dict[str, Any]:
    """``--target-mesh`` grammar → a target topology dict.  Accepts
    ``N`` (pure-DP world of N), ``AxB`` (data=A, tensor=B), or five
    ``x``-separated sizes in mesh axis order (pipe, expert, data, seq,
    tensor).  Raises ValueError on anything else."""
    from ..parallel.mesh import MESH_AXIS_ORDER

    try:
        dims = [int(d) for d in spec.lower().split("x")]
    except ValueError:
        raise ValueError(f"--target-mesh {spec!r}: expected N, AxB, or "
                         f"five x-separated axis sizes")
    if any(d < 1 for d in dims):
        raise ValueError(f"--target-mesh {spec!r}: axis sizes must be >= 1")
    if len(dims) == 1:
        axes = {"pipe": 1, "expert": 1, "data": dims[0], "seq": 1,
                "tensor": 1}
    elif len(dims) == 2:
        axes = {"pipe": 1, "expert": 1, "data": dims[0], "seq": 1,
                "tensor": dims[1]}
    elif len(dims) == len(MESH_AXIS_ORDER):
        axes = {a: d for a, d in zip(MESH_AXIS_ORDER, dims)}
    else:
        raise ValueError(f"--target-mesh {spec!r}: give 1, 2, or "
                         f"{len(MESH_AXIS_ORDER)} axis sizes")
    world = 1
    for d in axes.values():
        world *= d
    return {"axes": axes, "world_size": world, "host_coverage": "full",
            "device_kind": "<target>"}


def _mesh_column(path: str) -> str:
    """Compact origin-mesh cell for ``ls``: ``world@kind axes`` — or
    ``-`` for pre-reshard snapshots with no stamp."""
    try:
        meta = read_snapshot_manifest(path).get("meta") or {}
    except Exception:
        return "-"
    topo = meta.get("mesh")
    if not isinstance(topo, dict):
        return "-"
    axes = topo.get("axes") or {}
    ax = "x".join(str(s) for s in axes.values()) or "?"
    return (f"{topo.get('world_size', '?')}@"
            f"{topo.get('device_kind', '?')} [{ax}]")


def _dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, f))
            except OSError:
                pass
    return total


def _is_snapshot(path: str) -> bool:
    from .snapshot import SNAPSHOT_MANIFEST

    return os.path.exists(os.path.join(path, SNAPSHOT_MANIFEST))


def cmd_ls(args: argparse.Namespace) -> int:
    snaps = list_snapshots(args.dir)
    if not snaps:
        print(f"no committed snapshots under {args.dir}")
        return 0
    now = time.time()
    print(f"{'TAG':<24} {'STEP':>8} {'AGE':>10} {'SIZE':>10} "
          f"{'MESH':<20}  STATUS")
    for entry in snaps:
        ok, detail = verify_snapshot(entry["path"])
        age = now - float(entry.get("ts") or now)
        size = _dir_bytes(entry["path"])
        status = "valid" if ok else f"CORRUPT — {detail}"
        print(f"{entry['tag']:<24} {entry['step']:>8} "
              f"{age:>9.0f}s {size / 2**20:>9.1f}M "
              f"{_mesh_column(entry['path']):<20}  {status}")
    return 0


def _check_target_mesh(path: str, target: Dict[str, Any]) -> int:
    """The offline reshardability pre-check ("can I resume this on 3
    hosts?" without starting an engine): exit 0 compatible, 3 not."""
    meta = read_snapshot_manifest(path).get("meta") or {}
    origin = meta.get("mesh")
    ok, reason = check_reshardable(meta, target)
    print(f"origin: {format_topology(origin)}")
    print(f"target: {format_topology(target)}")
    print(f"reshardable: {'YES' if ok else 'NO'} — {reason}")
    if not ok:
        for tier, verdict in reshard_tier_report(meta, target).items():
            print(f"  {tier}: {verdict}")
        return 3
    shapes = meta.get("state_shapes")
    if shapes:
        from ..runtime.zero.sharder import reshard_layout_report

        axes = target.get("axes") or {}
        dp = int(axes.get("data", 1)) * int(axes.get("expert", 1))
        rep = reshard_layout_report(shapes, dp)
        print(f"layout at dp={dp}: {rep['sharded_count']} leaves "
              f"DP-shard, {rep['replicated_count']} replicate")
        for name in rep["replicated"][:8]:
            print(f"  replicated: {name}")
    return 0


def cmd_verify(args: argparse.Namespace) -> int:
    path = args.path
    target = None
    if getattr(args, "target_mesh", None):
        try:
            target = parse_target_mesh(args.target_mesh)
        except ValueError as e:
            return _fail(str(e))
    if _is_snapshot(path):
        ok, detail = verify_snapshot(path)
        print(f"{path}: {'valid' if ok else 'CORRUPT'} — {detail}")
        if not ok:
            return 4
        return _check_target_mesh(path, target) if target else 0
    if not os.path.isdir(path):
        return _fail(f"{path}: not a snapshot dir or snapshot root")
    snaps = list_snapshots(path)
    if not snaps:
        print(f"{path}: no committed snapshots")
        return 4
    results = [(entry, *verify_snapshot(entry["path"])) for entry in snaps]
    for entry, ok, detail in results:
        print(f"{entry['tag']}: {'valid' if ok else 'CORRUPT'} — {detail}")
    newest_ok = results[0][1]
    any_ok = any(ok for _e, ok, _d in results)
    if newest_ok:
        if target:
            # the pre-check answers for the snapshot a resume would pick
            return _check_target_mesh(results[0][0]["path"], target)
        return 0
    if any_ok:
        print("WARNING: newest snapshot is corrupt; a resume would fall "
              "back to an older one (extra lost work)")
        return 3
    print("FATAL: no restorable snapshot remains")
    return 4


def _held_replicas(root: str) -> List[Dict[str, Any]]:
    """Every snapshot dir under ``root`` (any depth — covers the
    ``recv/<owner>/<tag>`` trees a holder keeps and plain snapshot
    roots), with the owner inferred from the path."""
    from .snapshot import SNAPSHOT_MANIFEST

    out: List[Dict[str, Any]] = []
    for dirpath, dirs, files in os.walk(root):
        if SNAPSHOT_MANIFEST not in files:
            continue
        dirs[:] = []  # a snapshot dir never nests another
        rel = os.path.relpath(dirpath, root)
        parts = rel.split(os.sep)
        owner = parts[-2] if len(parts) >= 2 else "<local>"
        out.append({"path": dirpath, "owner": owner,
                    "tag": os.path.basename(dirpath)})
    out.sort(key=lambda e: (e["owner"], e["tag"]))
    return out


def cmd_replicas(args: argparse.Namespace) -> int:
    if not os.path.isdir(args.dir):
        return _fail(f"{args.dir}: not a directory")
    held = _held_replicas(args.dir)
    if not held:
        print(f"no held replicas under {args.dir}")
        return 4
    bad = 0
    print(f"{'OWNER':<16} {'TAG':<24} {'SIZE':>10} "
          f"{'MESH':<20}  STATUS")
    for entry in held:
        ok, detail = verify_snapshot(entry["path"])
        bad += 0 if ok else 1
        size = _dir_bytes(entry["path"])
        status = "valid" if ok else f"CORRUPT — {detail}"
        print(f"{entry['owner']:<16} {entry['tag']:<24} "
              f"{size / 2**20:>9.1f}M "
              f"{_mesh_column(entry['path']):<20}  {status}")
    return 3 if bad else 0


def cmd_fetch(args: argparse.Namespace) -> int:
    """Peer-to-peer restore with NO store: dial the holder's replica
    server directly (`--endpoint` from the index metadata, a journal,
    or the operator's notes), pull, checksum-verify, report."""
    from .replica_server import _rpc, fetch_replica

    tag = args.tag
    if tag is None:
        try:
            idx = _rpc(args.endpoint, [{"op": "index"}])[0].get("v") or []
        except (OSError, ConnectionError) as e:
            return _fail(f"replica server {args.endpoint} unreachable: "
                         f"{e!r}")
        mine = sorted(e["tag"] for e in idx if e.get("owner") == args.owner)
        if not mine:
            print(f"{args.endpoint} holds no replica of {args.owner!r} "
                  f"(serves: "
                  f"{sorted(set(e.get('owner') for e in idx))})")
            return 4
        tag = mine[-1]  # newest by tag ordering (snap-<step>)
    from ..runtime.checkpoint_engine import CheckpointCorruptionError

    try:
        path = fetch_replica(args.endpoint, args.owner, tag, args.out_dir)
    except CheckpointCorruptionError as e:
        # the transport sha gate rejected the holder's copy — the exact
        # condition this command exists to diagnose: report, exit 4
        print(f"{args.endpoint} {args.owner}/{tag}: CORRUPT — {e}")
        return 4
    except (OSError, ConnectionError) as e:
        return _fail(f"fetch from {args.endpoint} failed: {e!r}")
    ok, detail = verify_snapshot(path)
    print(f"{path}: {'valid' if ok else 'CORRUPT'} — {detail}")
    return 0 if ok else 4


def cmd_faults(args: argparse.Namespace) -> int:
    from .faults import FAULT_DOCS

    print("fault spec grammar: kind@step[:key=value,...]  "
          "(config resilience.faults or DS_FAULTS, ';'-separated)")
    for kind, doc in FAULT_DOCS.items():
        print(f"  {kind:<18} {doc}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.resilience",
        description="resilience plane operator CLI: inventory and "
                    "verify tiered training-state snapshots")
    sub = p.add_subparsers(dest="cmd", required=True)

    ls = sub.add_parser("ls", help="list committed snapshots with "
                                   "validity status")
    ls.add_argument("dir", nargs="?", default="resilience_snapshots")
    ls.set_defaults(fn=cmd_ls)

    v = sub.add_parser("verify",
                       help="checksum-verify one snapshot or a whole "
                            "snapshot dir (exit 0 newest-valid / 3 "
                            "fallback-only-or-incompatible / 4 none)")
    v.add_argument("path")
    v.add_argument("--target-mesh", default=None,
                   help="pre-check reshardability onto a target mesh "
                        "WITHOUT starting an engine: N (pure-DP world), "
                        "AxB (data x tensor), or five x-separated axis "
                        "sizes (pipe x expert x data x seq x tensor); "
                        "exit 3 when the snapshot cannot serve it")
    v.set_defaults(fn=cmd_verify)

    r = sub.add_parser("replicas",
                       help="inventory + checksum-verify the tier-2 "
                            "replicas held under a replica-store root "
                            "(exit 3 any corrupt / 4 none)")
    r.add_argument("dir")
    r.set_defaults(fn=cmd_replicas)

    f = sub.add_parser("fetch",
                       help="pull a replica straight from a peer's "
                            "replica server — no rendezvous store "
                            "needed (tier-2 stays restorable with the "
                            "store down)")
    f.add_argument("--endpoint", required=True,
                   help="host:port of the HOLDER's replica server")
    f.add_argument("--owner", required=True,
                   help="node id whose snapshot to pull")
    f.add_argument("--tag", default=None,
                   help="snapshot tag (default: the newest the holder "
                        "serves for that owner)")
    f.add_argument("out_dir")
    f.set_defaults(fn=cmd_fetch)

    fl = sub.add_parser("faults",
                        help="list every chaos fault kind the "
                             "injection harness speaks (incl. "
                             "kill_store / restart_store / "
                             "partition_node / sigstop_hang)")
    fl.set_defaults(fn=cmd_faults)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)
