"""Per-collective device timing from a profiler trace.

Reference: the ``comms_logger`` timing wrapper (``deepspeed/comm/comm.py``
[K], SURVEY §2.4) times every collective at the call site.  Under XLA the
hot-path collectives live INSIDE compiled programs where Python cannot
time them, so the equivalent is trace-sourced: run the step under
``jax.profiler.trace`` and aggregate the device lanes' collective op
durations (VERDICT round-2 missing #8).

Works wherever the profiler emits device/XLA op events (TPU-VMs, the CPU
backend used by the test suite).  On a tunneled/remote chip the device
trace may be empty — the helper then returns ``{}`` and logs once; eager
verbs (``comm.all_reduce`` etc. with ``comms_logger.configure(True)``)
and the ``ds_bench`` CLI remain the measured-latency paths there.
"""

from __future__ import annotations

import collections
import contextlib
import glob
import gzip
import json
import os
import re
import tempfile
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax

from ..utils.logging import logger

#: substrings of HLO/op names that identify collectives across backends
#: (TPU HLO names like "all-reduce.3"; CPU lanes use lowered primitive
#: names like "psum.7")
COLLECTIVE_PATTERNS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective", "psum", "pmean", "pmax",
    "all_gather", "all_to_all", "ppermute", "send", "recv",
)


# ---------------------------------------------------------------------------
# shared profiler session
# ---------------------------------------------------------------------------
# ``jax.profiler.trace`` sessions DO NOT NEST — opening a second one
# raises.  Every trace consumer in this repo (the exec-order census, the
# anatomy capture, ad-hoc ``profile_collectives``) therefore goes
# through ONE shared session: the first opener owns the real
# ``jax.profiler.trace`` context, nested openers reuse its output dir,
# and work that needs the *written* trace files (they only exist after
# the owning session closes) registers an ``on_session_close`` hook.

_session_lock = threading.Lock()
_active_session: Optional[Dict[str, Any]] = None  # {"dir": str, "post": []}


def active_trace_session() -> Optional[str]:
    """The output dir of the currently open shared session, or None."""
    with _session_lock:
        return _active_session["dir"] if _active_session else None


def on_session_close(fn: Callable[[str], Any]) -> bool:
    """Run ``fn(trace_dir)`` when the open shared session closes (trace
    files are on disk by then).  Returns False — and does nothing — when
    no session is open (caller should act immediately instead)."""
    with _session_lock:
        if _active_session is None:
            return False
        _active_session["post"].append(fn)
        return True


@contextlib.contextmanager
def shared_trace_session(trace_dir: Optional[str] = None):
    """ONE ``jax.profiler.trace`` for however many consumers are
    stacked.  The outermost caller opens (and later closes) the real
    profiler session; nested callers get the same dir and never open a
    second session (which would raise).  Yields the trace output dir."""
    global _active_session
    with _session_lock:
        if _active_session is not None:
            nested_dir = _active_session["dir"]
        else:
            nested_dir = None
            tmp = trace_dir or tempfile.mkdtemp(prefix="ds_anatomy_trace_")
            _active_session = {"dir": tmp, "post": []}
    if nested_dir is not None:
        yield nested_dir
        return
    try:
        with jax.profiler.trace(tmp):
            yield tmp
    finally:
        with _session_lock:
            posts = _active_session["post"] if _active_session else []
            _active_session = None
        for fn in posts:
            try:
                fn(tmp)
            except Exception as e:  # a post-hook must not mask the trace
                logger.warning(
                    f"shared trace session: close hook failed ({e!r})")


def begin_shared_session(trace_dir: Optional[str] = None) -> Optional[str]:
    """Open the shared profiler session WITHOUT a context manager — the
    fleet profiler plane arms at one train step and disarms N steps
    later, so the open and the close live in different calls.

    Returns the trace output dir when THIS caller became the owner, or
    ``None`` when a session is already open (the caller must not close
    it — re-arm after the owner finishes instead).  Pair every non-None
    return with :func:`end_shared_session`."""
    global _active_session
    with _session_lock:
        if _active_session is not None:
            return None
        tmp = trace_dir or tempfile.mkdtemp(prefix="ds_fleet_trace_")
        _active_session = {"dir": tmp, "post": []}
    try:
        jax.profiler.start_trace(tmp)
    except Exception:
        with _session_lock:
            _active_session = None
        raise
    return tmp


def end_shared_session() -> Optional[str]:
    """Close a session opened with :func:`begin_shared_session`: stop the
    profiler, run the registered close hooks (trace files are on disk),
    and return the trace dir — or ``None`` when no session was open."""
    global _active_session
    with _session_lock:
        if _active_session is None:
            return None
        tmp = _active_session["dir"]
        posts = list(_active_session["post"])
        _active_session = None
    try:
        jax.profiler.stop_trace()
    finally:
        for fn in posts:
            try:
                fn(tmp)
            except Exception as e:  # a post-hook must not mask the trace
                logger.warning(
                    f"shared trace session: close hook failed ({e!r})")
    return tmp


#: XLA HLO instruction names: lowercase identifier, optional dashes and
#: dotted suffixes — nothing host-side matches this shape
_HLO_NAME_RE = re.compile(r"[a-z][a-z0-9_.\-]*")


def parse_trace_events(trace_dir: str,
                       patterns: Optional[Sequence[str]]
                       = COLLECTIVE_PATTERNS
                       ) -> list:
    """Individual collective op events from a ``jax.profiler.trace``
    output dir, in device-timestamp order →
    ``[{ts_us, dur_us, name, lane}, ...]``.  Only events on device/XLA
    lanes count — host Python frames are excluded.  ``patterns=None``
    keeps EVERY device-lane op (the anatomy plane's full-timeline view);
    the default keeps collectives only.

    The ordering is what makes this the EXECUTION-order source: within
    one device lane, XLA runs a compiled program's thunks in a
    deterministic sequence, so two ranks executing the same SPMD
    program see the same collective order here — unlike the
    ``comms_logger`` execution probes, whose host callbacks interleave
    arbitrarily across device shards."""
    files = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    out = []
    for fp in files:
        with gzip.open(fp) as f:
            tr = json.load(f)
        events = tr.get("traceEvents", [])
        lanes = {e["pid"]: e.get("args", {}).get("name", "")
                 for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        for e in events:
            if e.get("ph") != "X":
                continue
            lane = lanes.get(e.get("pid"), "")
            # device lanes: '/device:TPU:0', '/host:CPU' XLA lane; skip
            # pure-python lanes ('/host:python' frames carry $file refs)
            if not (lane.startswith("/device")
                    or lane.startswith("/host:CPU")):
                continue
            name = e.get("name", "")
            low = name.lower()
            if low.startswith("end:") or name.startswith("$"):
                continue  # CPU tracer end markers / python source refs
            # the CPU tracer folds host-side spans into the '/host:CPU'
            # process — on SOME builds onto the very thread the XLA
            # thunks report on, so thread names can't separate them.
            # Shape does: XLA thunk names are lowercase HLO identifiers
            # ('dot.4', 'multiply_add_fusion', 'all-reduce.3') while
            # host spans carry call syntax or CamelCase
            # ('PjitFunction(jit(f))', 'TfrtCpuExecutable::Execute',
            # 'np.asarray(jax.Array)')
            if lane.startswith("/host:CPU") \
                    and not _HLO_NAME_RE.fullmatch(name):
                continue
            if patterns is None or any(p in low for p in patterns):
                out.append({"ts_us": float(e.get("ts", 0.0)),
                            "dur_us": float(e.get("dur", 0.0)),
                            "name": name, "lane": lane})
    out.sort(key=lambda ev: (ev["ts_us"], ev["name"]))
    return out


def parse_device_events(trace_dir: str) -> List[Dict[str, Any]]:
    """EVERY device-lane op event from a profiler trace dir, timestamp
    ordered — the anatomy classifier's input (collectives + compute +
    infeed/host waits, not just the collective subset)."""
    return parse_trace_events(trace_dir, patterns=None)


def parse_trace(trace_dir: str,
                patterns: Sequence[str] = COLLECTIVE_PATTERNS
                ) -> Dict[str, Dict[str, float]]:
    """Aggregate collective op durations from a ``jax.profiler.trace``
    output dir → ``{op_name: {count, total_us, mean_us}}``.  Only events
    on device/XLA lanes count — host Python frames are excluded."""
    durs: Dict[str, float] = collections.defaultdict(float)
    counts: collections.Counter = collections.Counter()
    for ev in parse_trace_events(trace_dir, patterns):
        durs[ev["name"]] += ev["dur_us"]
        counts[ev["name"]] += 1
    return {n: {"count": float(counts[n]), "total_us": round(durs[n], 1),
                "mean_us": round(durs[n] / max(counts[n], 1), 2)}
            for n in durs}


def feed_exec_census(trace_dir: str, ledger: Optional[Any] = None,
                     patterns: Sequence[str] = COLLECTIVE_PATTERNS,
                     dedupe_lanes: bool = True) -> int:
    """Opt-in execution-order census (ROADMAP item): replay a profiler
    trace's device-lane collective events, in timestamp order, into the
    :class:`~..telemetry.collective_ledger.CollectiveLedger` EXEC lane.

    The exec chain hashes only op identity (timings differ across ranks
    by nature), so two ranks that ran the same compiled program under
    the profiler agree on ``exec_tail_hash`` — this lane IS cross-rank
    comparable, unlike the unordered ``record_exec`` probe feed.  With
    ``dedupe_lanes`` (default) only the first device lane is replayed:
    in a single-process multi-device mesh every shard's lane shows the
    same program, and feeding all of them would count each collective
    ``local_device_count`` times.  Returns the number of entries fed.
    """
    if ledger is None:
        from ..telemetry.collective_ledger import get_collective_ledger

        ledger = get_collective_ledger()
    if not ledger.enabled:
        # calling the census IS the opt-in: an offline post-mortem
        # process never ran telemetry config, and a disabled ledger
        # would silently swallow every record_exec while this function
        # still reported N entries fed
        ledger.configure(enabled=True)
    events = parse_trace_events(trace_dir, patterns)
    if not events:
        logger.warning(
            "feed_exec_census: no device collective events in the trace "
            "(remote/tunneled chips may not export device lanes)")
        return 0
    if dedupe_lanes:
        first_lane = events[0]["lane"]
        events = [ev for ev in events if ev["lane"] == first_lane]
    for ev in events:
        ledger.record_exec(ev["name"], 0, dur_us=ev["dur_us"],
                           ts_us=ev["ts_us"], source="exec_trace")
    return len(events)


def collect_exec_census(fn: Callable[..., Any], *args,
                        iters: int = 1,
                        ledger: Optional[Any] = None,
                        trace_dir: Optional[str] = None,
                        patterns: Sequence[str] = COLLECTIVE_PATTERNS,
                        **kwargs) -> int:
    """Run ``fn(*args)`` under the SHARED profiler session and feed the
    execution-order census from the resulting trace.

    This is the session-safe wrapper around :func:`feed_exec_census`:
    when another consumer (the anatomy capture) already holds the shared
    session, no second ``jax.profiler.trace`` is opened — the steps run
    inside the existing window and the census feed is deferred to the
    owning session's close (the trace files exist only then).  Returns
    the entries fed, or ``-1`` when the feed was deferred."""
    out = fn(*args, **kwargs)  # warmup/compile outside the window
    jax.block_until_ready(out)
    nested = active_trace_session() is not None
    with shared_trace_session(trace_dir) as tdir:
        for _ in range(max(int(iters), 1)):
            out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        if nested:
            on_session_close(
                lambda d: feed_exec_census(d, ledger=ledger,
                                           patterns=patterns))
            return -1
    return feed_exec_census(tdir, ledger=ledger, patterns=patterns)


def profile_collectives(fn: Callable[..., Any], *args,
                        iters: int = 3,
                        trace_dir: Optional[str] = None,
                        patterns: Sequence[str] = COLLECTIVE_PATTERNS,
                        **kwargs) -> Dict[str, Dict[str, float]]:
    """Run ``fn(*args)`` ``iters`` times under the profiler and return the
    per-collective device-time table.  ``fn`` should be the compiled step
    (compile OUTSIDE the trace window: the first call is warmed here)."""
    out = fn(*args, **kwargs)  # warmup/compile outside the trace
    jax.block_until_ready(out)
    tmp = trace_dir or tempfile.mkdtemp(prefix="ds_comms_trace_")
    with shared_trace_session(tmp) as tmp:
        for _ in range(iters):
            out = fn(*args, **kwargs)
        jax.block_until_ready(out)
    table = parse_trace(tmp, patterns)
    if not table:
        logger.warning(
            "profile_collectives: no device collective events in the trace "
            "(remote/tunneled chips may not export device lanes) — use "
            "eager comm verbs with comms_logger or the ds_bench CLI for "
            "measured latencies")
    return table
