"""Per-collective device timing from a profiler trace.

Reference: the ``comms_logger`` timing wrapper (``deepspeed/comm/comm.py``
[K], SURVEY §2.4) times every collective at the call site.  Under XLA the
hot-path collectives live INSIDE compiled programs where Python cannot
time them, so the equivalent is trace-sourced: run the step under
``jax.profiler.trace`` and aggregate the device lanes' collective op
durations (VERDICT round-2 missing #8).

Works wherever the profiler emits device/XLA op events (TPU-VMs, the CPU
backend used by the test suite).  On a tunneled/remote chip the device
trace may be empty — the helper then returns ``{}`` and logs once; eager
verbs (``comm.all_reduce`` etc. with ``comms_logger.configure(True)``)
and the ``ds_bench`` CLI remain the measured-latency paths there.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import tempfile
from typing import Any, Callable, Dict, Optional, Sequence

import jax

from ..utils.logging import logger

#: substrings of HLO/op names that identify collectives across backends
#: (TPU HLO names like "all-reduce.3"; CPU lanes use lowered primitive
#: names like "psum.7")
COLLECTIVE_PATTERNS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective", "psum", "pmean", "pmax",
    "all_gather", "all_to_all", "ppermute", "send", "recv",
)


def parse_trace(trace_dir: str,
                patterns: Sequence[str] = COLLECTIVE_PATTERNS
                ) -> Dict[str, Dict[str, float]]:
    """Aggregate collective op durations from a ``jax.profiler.trace``
    output dir → ``{op_name: {count, total_us, mean_us}}``.  Only events
    on device/XLA lanes count — host Python frames are excluded."""
    files = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    durs: Dict[str, float] = collections.defaultdict(float)
    counts: collections.Counter = collections.Counter()
    for fp in files:
        with gzip.open(fp) as f:
            tr = json.load(f)
        events = tr.get("traceEvents", [])
        lanes = {e["pid"]: e.get("args", {}).get("name", "")
                 for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        for e in events:
            if e.get("ph") != "X":
                continue
            lane = lanes.get(e.get("pid"), "")
            # device lanes: '/device:TPU:0', '/host:CPU' XLA lane; skip
            # pure-python lanes ('/host:python' frames carry $file refs)
            if not (lane.startswith("/device")
                    or lane.startswith("/host:CPU")):
                continue
            name = e.get("name", "")
            low = name.lower()
            if low.startswith("end:"):
                continue  # CPU tracer emits paired end markers
            if any(p in low for p in patterns):
                durs[name] += float(e.get("dur", 0.0))
                counts[name] += 1
    return {n: {"count": float(counts[n]), "total_us": round(durs[n], 1),
                "mean_us": round(durs[n] / max(counts[n], 1), 2)}
            for n in durs}


def profile_collectives(fn: Callable[..., Any], *args,
                        iters: int = 3,
                        trace_dir: Optional[str] = None,
                        patterns: Sequence[str] = COLLECTIVE_PATTERNS,
                        **kwargs) -> Dict[str, Dict[str, float]]:
    """Run ``fn(*args)`` ``iters`` times under the profiler and return the
    per-collective device-time table.  ``fn`` should be the compiled step
    (compile OUTSIDE the trace window: the first call is warmed here)."""
    out = fn(*args, **kwargs)  # warmup/compile outside the trace
    jax.block_until_ready(out)
    tmp = trace_dir or tempfile.mkdtemp(prefix="ds_comms_trace_")
    with jax.profiler.trace(tmp):
        for _ in range(iters):
            out = fn(*args, **kwargs)
        jax.block_until_ready(out)
    table = parse_trace(tmp, patterns)
    if not table:
        logger.warning(
            "profile_collectives: no device collective events in the trace "
            "(remote/tunneled chips may not export device lanes) — use "
            "eager comm verbs with comms_logger or the ds_bench CLI for "
            "measured latencies")
    return table
