"""Flops profiler — XLA cost analysis instead of module hooks.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py`` [K] —
``FlopsProfiler`` (module-hook MAC counting, per-module latency table at
``profile_step``) and standalone ``get_model_profile()``; engine config group
``flops_profiler.{enabled,profile_step,module_depth,top_modules,detailed,
output_file}`` (SURVEY §5.1).

TPU-first: a jitted function's exact FLOPs/bytes come from the COMPILER —
``jax.jit(fn).lower(...).compile().cost_analysis()`` — so no hook walking,
and the numbers are the post-fusion truth rather than an analytic estimate.
Wall-clock from timed replay gives achieved FLOP/s and MFU.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ...utils.logging import log_dist, logger

#: published dense bf16 peak per chip by device kind (spec sheets)
PEAK_BF16_BY_KIND = (
    ("v6", 918e12),     # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)

#: fallback peak per backend when the device kind is unrecognized
DEFAULT_PEAK_FLOPS = {
    "tpu": 197e12,
    "cpu": 1e12,
    "gpu": 312e12,
}


def peak_flops_per_chip() -> float:
    """bf16 peak for THIS chip (kind-matched, backend fallback)."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for tag, peak in PEAK_BF16_BY_KIND:
        if tag in kind:
            return peak
    return DEFAULT_PEAK_FLOPS.get(jax.default_backend(), 1e12)


def _compiled_cost(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns [dict]
        costs = costs[0] if costs else {}
    return dict(costs or {})


class FlopsProfiler:
    """Profile a jitted step function (or an engine's train step)."""

    def __init__(self, model: Any = None, ds_engine: Any = None):
        self.engine = ds_engine if ds_engine is not None else model
        self.profile: Dict[str, float] = {}

    # -- step-function profiling ------------------------------------------

    def profile_fn(self, fn: Callable, *args, runs: int = 3,
                   **kwargs) -> Dict[str, float]:
        costs = _compiled_cost(fn, *args, **kwargs)
        flops = float(costs.get("flops", 0.0))
        jitted = jax.jit(fn)
        out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(runs):
            out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        latency = (time.perf_counter() - t0) / runs
        backend = jax.default_backend()
        peak = peak_flops_per_chip()
        achieved = flops / latency if latency > 0 else 0.0
        self.profile = {
            "flops": flops,
            "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
            "latency_s": latency,
            "achieved_flops_per_s": achieved,
            "mfu": achieved / (peak * jax.device_count()),
            "backend": backend,
        }
        return self.profile

    # -- engine hook surface (reference API names) ------------------------

    def start_profile(self, ignore_list=None) -> None:
        self._t0 = time.perf_counter()

    def stop_profile(self) -> None:
        self.profile.setdefault("latency_s", time.perf_counter() - self._t0)

    def get_total_flops(self, as_string: bool = False):
        v = self.profile.get("flops", 0.0)
        return _num_to_string(v, "FLOPs") if as_string else v

    def get_total_duration(self, as_string: bool = False):
        v = self.profile.get("latency_s", 0.0)
        return f"{v * 1e3:.2f} ms" if as_string else v

    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 1, detailed: bool = True,
                            output_file: Optional[str] = None) -> None:
        lines = ["-" * 60, "DeepSpeed-TPU Flops Profiler",
                 "-" * 60]
        for k, v in self.profile.items():
            lines.append(f"{k:>24}: {v}")
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text)
        else:
            log_dist(text)

    def end_profile(self) -> None:
        self.profile = {}


def _num_to_string(num: float, unit: str) -> str:
    for scale, prefix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if num >= scale:
            return f"{num / scale:.2f} {prefix}{unit}"
    return f"{num:.2f} {unit}"


def get_model_profile(model: Any = None, input_shape: Tuple[int, ...] = None,
                      args: Tuple = (), kwargs: Optional[Dict] = None,
                      print_profile: bool = True, detailed: bool = True,
                      module_depth: int = -1, top_modules: int = 1,
                      warm_up: int = 1, as_string: bool = True,
                      output_file: Optional[str] = None,
                      ignore_modules=None,
                      fn: Optional[Callable] = None):
    """Standalone profile (reference ``get_model_profile`` shape).

    TPU adaptation: pass ``fn`` + ``args`` (a pure function and its inputs);
    ``model`` objects with ``.loss``/``.forward`` are profiled through that.
    Returns (flops, macs, params) like the reference — macs = flops/2.
    """
    if fn is None:
        if model is None:
            raise ValueError("need fn or model")
        fn = model.forward if hasattr(model, "forward") else model
    prof = FlopsProfiler()
    result = prof.profile_fn(fn, *args, **(kwargs or {}))
    params = 0
    if args:
        try:
            params = sum(int(x.size) for x in jax.tree.leaves(args[0]))
        except Exception:
            params = 0
    if print_profile:
        prof.print_model_profile(output_file=output_file)
    flops = result["flops"]
    macs = flops / 2
    if as_string:
        return (_num_to_string(flops, "FLOPs"), _num_to_string(macs, "MACs"),
                _num_to_string(params, ""))
    return flops, macs, params
