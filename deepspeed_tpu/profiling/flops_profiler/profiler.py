"""Flops profiler — XLA cost analysis instead of module hooks.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py`` [K] —
``FlopsProfiler`` (module-hook MAC counting, per-module latency table at
``profile_step``) and standalone ``get_model_profile()``; engine config group
``flops_profiler.{enabled,profile_step,module_depth,top_modules,detailed,
output_file}`` (SURVEY §5.1).

TPU-first: a jitted function's exact FLOPs/bytes come from the COMPILER —
``jax.jit(fn).lower(...).compile().cost_analysis()`` — so no hook walking,
and the numbers are the post-fusion truth rather than an analytic estimate.
Wall-clock from timed replay gives achieved FLOP/s and MFU.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ...utils.logging import log_dist, logger

#: per-chip peaks by device kind (spec sheets): dense bf16 FLOP/s, HBM
#: bandwidth (bytes/s), aggregate ICI/interconnect bandwidth (bytes/s).
#: Substring-matched against ``device_kind`` first-match-wins, so the
#: more specific tag ("v5p", "v6e") must precede its prefix ("v5", "v6").
PEAK_TABLE = (
    # (kind tag,   flops,   hbm B/s,  ici B/s)
    ("v6e",     918e12,  1640e9,  448e9),   # Trillium
    ("v6",      918e12,  1640e9,  448e9),
    ("v5p",     459e12,  2765e9,  600e9),
    ("v5e",     197e12,   819e9,  200e9),
    ("v5 lite", 197e12,   819e9,  200e9),
    ("v4",      275e12,  1228e9,  300e9),
    ("v3",      123e12,   900e9,  175e9),
    ("v2",       46e12,   700e9,   62e9),
)

#: published dense bf16 peak per chip by device kind (back-compat view
#: of PEAK_TABLE; ``peak_for_device`` is the lookup new code uses)
PEAK_BF16_BY_KIND = tuple((tag, flops) for tag, flops, _, _ in PEAK_TABLE)

#: fallback peak per backend when the device kind is unrecognized
DEFAULT_PEAK_FLOPS = {
    "tpu": 197e12,
    "cpu": 1e12,
    "gpu": 312e12,
}

#: (flops, hbm B/s, ici B/s) backend fallbacks for the full peak lookup
DEFAULT_PEAKS = {
    "tpu": (197e12, 819e9, 200e9),
    "gpu": (312e12, 2039e9, 300e9),
    "cpu": (1e12, 50e9, 10e9),
}


@dataclasses.dataclass(frozen=True)
class DevicePeak:
    """One chip's roofline ceilings.  ``source`` is ``"spec"`` when the
    device kind matched the spec-sheet table, ``"backend_default"`` when
    only the backend fallback applied (CPU, unknown kinds)."""

    kind: str
    flops_per_s: float
    hbm_bytes_per_s: float
    ici_bytes_per_s: float
    source: str = "spec"

    @property
    def critical_intensity(self) -> float:
        """FLOPs/byte above which this chip is compute-bound."""
        return self.flops_per_s / max(self.hbm_bytes_per_s, 1.0)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["critical_intensity"] = round(self.critical_intensity, 2)
        return d


def peak_for_device(device: Any = None) -> DevicePeak:
    """THE peak lookup — the single source the MFU math, the anatomy
    plane's roofline model, and any future bandwidth accounting share.
    Kind-matched against the spec table, backend fallback otherwise."""
    dev = device if device is not None else jax.devices()[0]
    kind = getattr(dev, "device_kind", "") or ""
    low = kind.lower()
    for tag, flops, hbm, ici in PEAK_TABLE:
        if tag in low:
            return DevicePeak(kind=kind, flops_per_s=flops,
                              hbm_bytes_per_s=hbm, ici_bytes_per_s=ici)
    backend = (getattr(dev, "platform", None) or jax.default_backend())
    flops, hbm, ici = DEFAULT_PEAKS.get(str(backend), DEFAULT_PEAKS["cpu"])
    return DevicePeak(kind=kind or str(backend), flops_per_s=flops,
                      hbm_bytes_per_s=hbm, ici_bytes_per_s=ici,
                      source="backend_default")


def peak_flops_per_chip() -> float:
    """bf16 peak for THIS chip — ``peak_for_device().flops_per_s``, kept
    as the narrow helper the MFU call sites read."""
    peak = peak_for_device()
    if peak.source == "spec":
        return peak.flops_per_s
    return DEFAULT_PEAK_FLOPS.get(jax.default_backend(), 1e12)


def _compiled_cost(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns [dict]
        costs = costs[0] if costs else {}
    return dict(costs or {})


class FlopsProfiler:
    """Profile a jitted step function (or an engine's train step)."""

    def __init__(self, model: Any = None, ds_engine: Any = None):
        self.engine = ds_engine if ds_engine is not None else model
        self.profile: Dict[str, float] = {}

    # -- step-function profiling ------------------------------------------

    def profile_fn(self, fn: Callable, *args, runs: int = 3,
                   **kwargs) -> Dict[str, float]:
        costs = _compiled_cost(fn, *args, **kwargs)
        flops = float(costs.get("flops", 0.0))
        jitted = jax.jit(fn)
        out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(runs):
            out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        latency = (time.perf_counter() - t0) / runs
        backend = jax.default_backend()
        peak = peak_flops_per_chip()
        achieved = flops / latency if latency > 0 else 0.0
        self.profile = {
            "flops": flops,
            "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
            "latency_s": latency,
            "achieved_flops_per_s": achieved,
            "mfu": achieved / (peak * jax.device_count()),
            "backend": backend,
        }
        return self.profile

    # -- engine hook surface (reference API names) ------------------------

    def start_profile(self, ignore_list=None) -> None:
        self._t0 = time.perf_counter()

    def stop_profile(self) -> None:
        self.profile.setdefault("latency_s", time.perf_counter() - self._t0)

    def get_total_flops(self, as_string: bool = False):
        v = self.profile.get("flops", 0.0)
        return _num_to_string(v, "FLOPs") if as_string else v

    def get_total_duration(self, as_string: bool = False):
        v = self.profile.get("latency_s", 0.0)
        return f"{v * 1e3:.2f} ms" if as_string else v

    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 1, detailed: bool = True,
                            output_file: Optional[str] = None) -> None:
        lines = ["-" * 60, "DeepSpeed-TPU Flops Profiler",
                 "-" * 60]
        for k, v in self.profile.items():
            lines.append(f"{k:>24}: {v}")
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text)
        else:
            log_dist(text)

    def end_profile(self) -> None:
        self.profile = {}


def profile_model_modules(model: Any, params: Any, batch: Any,
                          module_depth: int = -1, top_modules: int = 0,
                          runs: int = 3) -> Dict[str, Dict[str, float]]:
    """PER-MODULE flops/params/latency table (reference FlopsProfiler's
    ``module_depth``/``top_modules`` per-module breakdown, SURVEY §2.5).

    TPU-first: instead of module hooks, each piece of the model's
    layer-streamable protocol compiles separately and its cost comes from
    the COMPILER (``cost_analysis``) plus a timed on-device replay —
    "which layer burns the FLOPs" answered with post-fusion truth:

    * depth 1 — ``embed``, ``layers`` (one decoder layer × L), ``head``
    * depth 2 — inside one decoder layer, whatever the model's
      ``profile_submodules()`` exposes (attn/mlp for the Llama family)

    Returns ``{module: {flops, macs, params, latency_s, pct_latency,
    tflops_per_s, count}}``; ``latency_s`` is the per-call forward time,
    ``pct_latency`` weights by ``count`` (layers run L times per step).
    """
    needed = ("embed_fwd", "decoder_layer", "head_loss", "batch_labels")
    if not all(callable(getattr(model, m, None)) for m in needed):
        raise ValueError(
            "per-module profiling needs the layer-streamable protocol "
            f"(embed_fwd/decoder_layer/head_loss); {type(model).__name__} "
            "does not implement it")
    ids, _ = model.batch_labels(batch)
    L = int(model.config.num_layers)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    resident = {k: v for k, v in params.items() if k != "layers"}

    def timed(fn, *args) -> Tuple[float, float]:
        costs = _compiled_cost(fn, *args)
        jitted = jax.jit(fn)
        jax.block_until_ready(jitted(*args))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(runs):
            out = jitted(*args)
        jax.block_until_ready(out)
        return float(costs.get("flops", 0.0)), \
            (time.perf_counter() - t0) / runs

    def n_params(tree) -> int:
        return sum(int(x.size) for x in jax.tree.leaves(tree))

    x = jax.jit(model.embed_fwd)(resident, ids)
    rows: Dict[str, Dict[str, float]] = {}

    def add(name, fn, args, count, params_of, depth):
        flops, lat = timed(fn, *args)
        rows[name] = {"flops": flops, "macs": flops / 2.0,
                      "params": n_params(params_of), "latency_s": lat,
                      "count": count, "depth": depth,
                      "tflops_per_s": (flops / lat / 1e12) if lat else 0.0}

    add("embed", model.embed_fwd, (resident, ids), 1,
        {k: v for k, v in resident.items() if k == "embed"}, 1)
    add("layers", lambda l, a: model.decoder_layer(l, a)[0], (lp, x), L,
        params["layers"], 1)
    add("head", model.head_loss, (resident, x, batch), 1,
        {k: v for k, v in resident.items() if k != "embed"}, 1)
    if (module_depth < 0 or module_depth >= 2) and callable(
            getattr(model, "profile_submodules", None)):
        for name, fn in model.profile_submodules().items():
            add(f"layers.{name}", fn, (lp, x), L,
                {}, 2)  # params attributed at depth 1
    total = sum(r["latency_s"] * r["count"] for r in rows.values()
                if r["depth"] == 1)
    for r in rows.values():
        r["pct_latency"] = 100.0 * r["latency_s"] * r["count"] / total \
            if total else 0.0
    if top_modules and top_modules > 0:
        keep = set()
        for d in (1, 2):
            at_d = sorted((n for n, r in rows.items() if r["depth"] == d),
                          key=lambda n: -rows[n]["pct_latency"])
            keep.update(at_d[:top_modules])
        rows = {n: r for n, r in rows.items() if n in keep}
    return rows


def format_module_table(rows: Dict[str, Dict[str, float]]) -> str:
    """Reference-style top-modules table."""
    lines = ["-" * 78,
             f"{'module':<16}{'params':>12}{'MACs':>14}{'fwd latency':>14}"
             f"{'% latency':>11}{'TFLOP/s':>10}",
             "-" * 78]
    for name, r in sorted(rows.items(),
                          key=lambda kv: (kv[1]['depth'],
                                          -kv[1]['pct_latency'])):
        pad = "  " if r["depth"] == 2 else ""
        cnt = f" x{int(r['count'])}" if r["count"] > 1 else ""
        lines.append(
            f"{pad + name + cnt:<16}"
            f"{_num_to_string(r['params'], ''):>12}"
            f"{_num_to_string(r['macs'], 'MACs'):>14}"
            f"{r['latency_s'] * 1e3:>11.2f} ms"
            f"{r['pct_latency']:>10.1f}%"
            f"{r['tflops_per_s']:>10.2f}")
    lines.append("-" * 78)
    return "\n".join(lines)


def _num_to_string(num: float, unit: str) -> str:
    for scale, prefix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if num >= scale:
            return f"{num / scale:.2f} {prefix}{unit}"
    return f"{num:.2f} {unit}"


def get_model_profile(model: Any = None, input_shape: Tuple[int, ...] = None,
                      args: Tuple = (), kwargs: Optional[Dict] = None,
                      print_profile: bool = True, detailed: bool = True,
                      module_depth: int = -1, top_modules: int = 1,
                      warm_up: int = 1, as_string: bool = True,
                      output_file: Optional[str] = None,
                      ignore_modules=None,
                      fn: Optional[Callable] = None):
    """Standalone profile (reference ``get_model_profile`` shape).

    TPU adaptation: pass ``fn`` + ``args`` (a pure function and its inputs);
    ``model`` objects with ``.loss``/``.forward`` are profiled through that.
    Returns (flops, macs, params) like the reference — macs = flops/2.
    """
    if fn is None:
        if model is None:
            raise ValueError("need fn or model")
        fn = model.forward if hasattr(model, "forward") else model
    prof = FlopsProfiler()
    result = prof.profile_fn(fn, *args, **(kwargs or {}))
    params = 0
    if args:
        try:
            params = sum(int(x.size) for x in jax.tree.leaves(args[0]))
        except Exception:
            params = 0
    if print_profile:
        prof.print_model_profile(output_file=output_file)
    flops = result["flops"]
    macs = flops / 2
    if as_string:
        return (_num_to_string(flops, "FLOPs"), _num_to_string(macs, "MACs"),
                _num_to_string(params, ""))
    return flops, macs, params
