from .profiler import (DevicePeak, FlopsProfiler, get_model_profile,
                       peak_flops_per_chip, peak_for_device)

__all__ = ["DevicePeak", "FlopsProfiler", "get_model_profile",
           "peak_flops_per_chip", "peak_for_device"]
