"""Auto-apply — ``initialize()`` consults the best-known-config store.

Called from ``runtime/entry.py`` after the mesh is built and BEFORE
``resolve_batch_sizes`` (assignment marks pydantic fields as set, so the
pin check must run first).  The contract:

* only ``promoted`` entries apply (a search candidate that never passed
  the perf sentinel stays advisory);
* a knob the user pinned explicitly in their ds_config (or through
  ``DS_AUTOTUNING_CONFIG_OVERRIDE``) is NEVER overridden — pinned means
  "present in the validated model's ``model_fields_set`` with a
  non-``auto`` value";
* any batch-family knob pinned ⇒ no batch-family override applies (a
  half-applied batch triple would trip the batch invariant);
* ``model.*`` overrides are reported but not applied — ``initialize``
  never rebuilds the caller's model (bench/search harnesses apply them
  at model construction);
* what happened is stamped into every future debug bundle
  (``context.tuning``) and readable via :func:`applied_info` /
  :func:`tuned_config_source` (bench stamps the latter into the gated
  artifact).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ..utils.logging import debug_once, log_dist, logger
from .space import MODEL_KEY_PREFIX
from .store import (BestConfigStore, current_device_kind, fingerprint_of,
                    mesh_signature, resolve_store_path)

_BATCH_KEYS = ("train_batch_size", "train_micro_batch_size_per_gpu",
               "gradient_accumulation_steps")

_lock = threading.Lock()
_applied: Optional[Dict[str, Any]] = None


def applied_info() -> Optional[Dict[str, Any]]:
    """What the last ``initialize()`` consult did (None = no store hit)."""
    with _lock:
        return dict(_applied) if _applied is not None else None


def tuned_config_source() -> str:
    """The provenance string bench artifacts carry as
    ``tuned_config_source`` ("none" when nothing matched)."""
    info = applied_info()
    if info is None:
        return "none"
    return f"{info['store']}::{info['key']}"


def reset_applied() -> None:
    with _lock:
        global _applied
        _applied = None


def _set_applied(info: Dict[str, Any]) -> None:
    with _lock:
        global _applied
        _applied = info
    try:
        from ..telemetry import get_flight_recorder

        get_flight_recorder().register_context("tuning", applied_info)
    except Exception as e:  # bundle context is best-effort
        debug_once("tuning/recorder_context",
                   f"tuning bundle context unavailable ({e!r})")


def _is_pinned(cfg: Any, dotted: str) -> bool:
    """Did the USER set this dotted key?  Walks pydantic submodels;
    a field present in ``model_fields_set`` with a non-"auto",
    non-None value is pinned.  Unknown paths count as pinned (never
    guess into config we don't understand)."""
    from ..runtime.config_utils import is_auto

    node = cfg
    parts = dotted.split(".")
    for p in parts[:-1]:
        nxt = getattr(node, p, None)
        if nxt is None or not hasattr(nxt, "model_fields_set"):
            extra = getattr(node, "model_extra", None) or {}
            if p in extra:
                return True  # free-form extra subtree the user wrote
            return False  # subtree untouched by the user: not pinned
        if p not in node.model_fields_set and p not in (
                getattr(node, "model_extra", None) or {}):
            # the whole subgroup is defaulted — nothing under it is pinned
            return False
        node = nxt
    leaf = parts[-1]
    if leaf in (getattr(node, "model_extra", None) or {}):
        return True
    if leaf not in getattr(node, "model_fields_set", ()):  # defaulted
        return False
    value = getattr(node, leaf, None)
    return not (value is None or is_auto(value))


def _apply_one(cfg: Any, dotted: str, value: Any) -> bool:
    node = cfg
    parts = dotted.split(".")
    for p in parts[:-1]:
        nxt = getattr(node, p, None)
        if nxt is None or not hasattr(nxt, "model_fields_set"):
            return False  # not a modeled config path — refuse to invent it
        node = nxt
    if not hasattr(node, parts[-1]):
        return False
    try:
        setattr(node, parts[-1], value)  # validate_assignment re-checks type
    except Exception as e:
        logger.warning(f"tuning: stored override {dotted}={value!r} "
                       f"rejected by config validation ({e}); skipped")
        return False
    return True


def maybe_apply_tuned_config(cfg: Any, model: Any = None,
                             model_parameters: Any = None,
                             mesh: Any = None) -> Optional[Dict[str, Any]]:
    """Consult the store and apply a promoted entry's overrides into the
    validated ``DeepSpeedConfig`` in place.  Returns the applied-info
    dict (also stored process-globally) or None on a miss.  Never
    raises — a corrupt store must not kill ``initialize``."""
    # a miss must not leave a PREVIOUS initialize()'s hit readable —
    # debug bundles and tuned_config_source describe the LAST consult
    reset_applied()
    try:
        fp = fingerprint_of(model=model, model_parameters=model_parameters)
        if fp is None or mesh is None:
            return None
        store = BestConfigStore(resolve_store_path(
            getattr(cfg.tuning, "store_path", "")))
        hit = store.lookup(fp, mesh_signature(mesh), current_device_kind(),
                           promoted_only=True)
        if hit is None:
            return None
        key, entry = hit
        overrides = dict(entry.get("overrides", {}))
        model_overrides = dict(entry.get("model_overrides", {}))
        # legacy entries may carry model.* inside overrides
        for k in [k for k in overrides if k.startswith(MODEL_KEY_PREFIX)]:
            model_overrides[k[len(MODEL_KEY_PREFIX):]] = overrides.pop(k)

        batch_pinned = [k for k in _BATCH_KEYS if _is_pinned(cfg, k)]
        applied: Dict[str, Any] = {}
        skipped: Dict[str, str] = {}
        for dotted, value in overrides.items():
            if dotted.startswith("tuning."):
                skipped[dotted] = "search-harness knob"
                continue
            if dotted in _BATCH_KEYS and batch_pinned:
                skipped[dotted] = (f"batch family pinned by user "
                                   f"({', '.join(batch_pinned)})")
                continue
            if _is_pinned(cfg, dotted):
                skipped[dotted] = "pinned by user config"
                continue
            if _apply_one(cfg, dotted, value):
                applied[dotted] = value
            else:
                skipped[dotted] = "not a modeled config path"
        info = {
            "store": store.source_of(key),
            "key": key,
            "status": entry.get("status"),
            "applied": applied,
            "skipped": skipped,
            "model_overrides_unapplied": model_overrides,
            "scores": entry.get("scores", {}),
            "stale_jax": entry.get("stale_jax"),
        }
        _set_applied(info)
        if applied:
            log_dist("tuning: applied best-known config "
                     f"{key} -> {applied}"
                     + (f" (skipped pinned: {sorted(skipped)})"
                        if skipped else ""))
        else:
            log_dist(f"tuning: best-known config {key} matched but every "
                     f"override was pinned/unapplicable "
                     f"({sorted(skipped) or 'empty entry'})")
        if model_overrides:
            log_dist(f"tuning: entry carries model overrides "
                     f"{model_overrides} — initialize() cannot rebuild the "
                     f"model; apply them at model construction")
        return info
    except Exception as e:
        logger.warning(f"tuning: best-known-config consult failed ({e}); "
                       f"continuing with the user config")
        return None
