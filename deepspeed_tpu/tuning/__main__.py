"""``python -m deepspeed_tpu.tuning`` — operator CLI entry point."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
