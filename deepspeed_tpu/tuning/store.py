"""Best-known-config store — versioned JSON keyed by what actually
determines performance.

A tuned config is only valid for the exact situation it was tuned in:
the *model* (fingerprinted over its parameter tree — leaf paths,
shapes, dtypes), the *mesh shape* it trains on, the *device kind* the
chips report, and (loosely) the *jax version* that compiled it.  Keys
are the pipe-joined normalization of those four parts; a lookup with a
different mesh or device kind MUST miss (a v5e-tuned micro-batch on a
v4 is a lie), while a jax-version-only mismatch falls back with a
``stale_jax`` note — config knobs don't change meaning across jax
minors, but the provenance should say the scores predate this compiler.

Entries carry full provenance (who searched, with what budget, scoring
which metric, from which bench artifact) and a ``status``:
``candidate`` entries come out of a search; only ``promoted`` entries —
the ones that passed the perf sentinel (:mod:`.promote`) — are applied
by ``initialize()``.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import debug_once, logger

STORE_VERSION = 1

#: the checked-in, package-shipped store (seeded best-known configs —
#: e.g. the TPU v5 lite headline entry derived from the
#: ``zero3_remat_shape_tuned`` bench variant)
PACKAGE_STORE_BASENAME = "best_known_configs.json"

#: env override for the operator/user store location
STORE_ENV = "DS_TUNING_STORE"


# ---------------------------------------------------------------------------
# key parts
# ---------------------------------------------------------------------------


def model_fingerprint(tree_or_shapes: Any) -> str:
    """Stable fingerprint of a parameter tree: sha1 over the sorted
    (path, shape, dtype) triples of its leaves.  Works on concrete
    arrays and on ``jax.eval_shape`` results alike (both carry
    ``.shape``/``.dtype``)."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree_or_shapes)[0]
    triples = []
    for path, leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        triples.append((jax.tree_util.keystr(path), list(shape), dtype))
    triples.sort()
    digest = hashlib.sha1(
        json.dumps(triples, separators=(",", ":")).encode()).hexdigest()
    return digest[:12]


def fingerprint_of(model: Any = None, model_parameters: Any = None
                   ) -> Optional[str]:
    """Fingerprint from whatever the caller has: a concrete param tree,
    or a model exposing ``init_params`` (traced abstractly — no arrays
    are materialized).  None when neither is usable."""
    import jax

    if model_parameters is not None:
        try:
            return model_fingerprint(model_parameters)
        except Exception as e:
            debug_once("tuning/fingerprint_params",
                       f"param-tree fingerprint failed ({e!r})")
    if model is not None and callable(getattr(model, "init_params", None)):
        try:
            shapes = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
            return model_fingerprint(shapes)
        except Exception as e:
            debug_once("tuning/fingerprint_model",
                       f"init_params shape trace failed ({e!r})")
    return None


def mesh_signature(mesh: Any) -> str:
    """``devices=<n>[,axis=k...]`` over the >1-sized axes — stable under
    axis reordering and all-ones meshes."""
    try:
        shape = dict(mesh.shape)
    except Exception:
        return "devices=?"
    total = 1
    for n in shape.values():
        total *= int(n)
    parts = [f"devices={total}"]
    parts += [f"{a}={int(n)}" for a, n in sorted(shape.items())
              if int(n) > 1]
    return ",".join(parts)


def current_device_kind() -> str:
    import jax

    try:
        devs = jax.local_devices()
        return str(devs[0].device_kind) if devs else "unknown"
    except Exception as e:
        debug_once("tuning/device_kind",
                   f"device_kind unavailable ({e!r})")
        return "unknown"


def jax_version_key() -> str:
    import jax

    return "jax" + ".".join(jax.__version__.split(".")[:2])


def store_key(fingerprint: str, mesh_sig: str, device_kind: str,
              jax_version: Optional[str] = None) -> str:
    return "|".join([fingerprint, mesh_sig, device_kind,
                     jax_version or jax_version_key()])


def split_key(key: str) -> Tuple[str, str, str, str]:
    parts = key.split("|")
    if len(parts) != 4:
        raise ValueError(f"malformed store key {key!r} "
                         f"(want fingerprint|mesh|device_kind|jaxver)")
    return tuple(parts)  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# store paths
# ---------------------------------------------------------------------------


def package_store_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        PACKAGE_STORE_BASENAME)


def resolve_store_path(configured: str = "") -> str:
    """Operator store precedence: explicit config path > DS_TUNING_STORE
    env > the per-user default."""
    if configured:
        return os.path.expanduser(configured)
    env = os.environ.get(STORE_ENV)
    if env:
        return os.path.expanduser(env)
    return os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu",
                        PACKAGE_STORE_BASENAME)


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------


class BestConfigStore:
    """One JSON document: ``{"version": 1, "entries": {key: entry}}``.

    ``fallback`` (default: the package-shipped store) is consulted
    read-only when a key misses the primary file — a fresh machine gets
    the checked-in seeds without copying anything."""

    def __init__(self, path: str, fallback: Optional[str] = "__package__"):
        self.path = os.path.expanduser(path)
        if fallback == "__package__":
            fallback = package_store_path()
        self.fallback = (None if not fallback
                         or os.path.abspath(fallback)
                         == os.path.abspath(self.path)
                         else fallback)
        self._doc = self._load(self.path)
        # the fallback is read-only for our lifetime — parse it once, not
        # on every get()/entries() (lookup() alone would hit disk twice)
        self._fallback_doc = (self._load(self.fallback)
                              if self.fallback else {"entries": {}})

    @staticmethod
    def _load(path: str) -> Dict[str, Any]:
        if not os.path.exists(path):
            return {"version": STORE_VERSION, "entries": {}}
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            logger.warning(f"tuning store {path}: unreadable ({e}); "
                           f"treating as empty")
            return {"version": STORE_VERSION, "entries": {}}
        if not isinstance(doc, dict) or "entries" not in doc:
            logger.warning(f"tuning store {path}: not a store document; "
                           f"treating as empty")
            return {"version": STORE_VERSION, "entries": {}}
        if int(doc.get("version", 0)) > STORE_VERSION:
            logger.warning(
                f"tuning store {path}: version {doc.get('version')} is "
                f"newer than this runtime understands ({STORE_VERSION}); "
                f"reading best-effort")
        return doc

    def save(self) -> None:
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        # never downgrade a document written by a newer runtime — its
        # entries may carry semantics this version doesn't know about
        self._doc["version"] = max(
            int(self._doc.get("version", 0) or 0), STORE_VERSION)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._doc, fh, indent=2, sort_keys=True)
        os.replace(tmp, self.path)  # atomic: readers never see a torn file

    # -- access ------------------------------------------------------------

    def entries(self, include_fallback: bool = True
                ) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        if include_fallback and self.fallback:
            out.update(self._fallback_doc.get("entries", {}))
        out.update(self._doc.get("entries", {}))
        return out

    def has_local(self, key: str) -> bool:
        """True when the key lives in THIS store file (not the read-only
        fallback)."""
        return key in self._doc.get("entries", {})

    def source_of(self, key: str) -> str:
        """The file a key resolves from — the provenance path stamped
        into ``tuned_config_source`` and the bench artifact."""
        if self.has_local(key) or not self.fallback:
            return self.path
        return self.fallback

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        entry = self._doc.get("entries", {}).get(key)
        if entry is None and self.fallback:
            entry = self._fallback_doc.get("entries", {}).get(key)
        return entry

    def lookup(self, fingerprint: str, mesh_sig: str, device_kind: str,
               jax_version: Optional[str] = None,
               promoted_only: bool = False
               ) -> Optional[Tuple[str, Dict[str, Any]]]:
        """Exact key first; a jax-version-only mismatch falls back to
        the newest matching entry with ``stale_jax`` set in the
        returned entry copy.  Mesh / device_kind NEVER fall back."""
        jv = jax_version or jax_version_key()
        want = store_key(fingerprint, mesh_sig, device_kind, jv)
        entry = self.get(want)
        if entry is not None and (not promoted_only
                                  or entry.get("status") == "promoted"):
            return want, dict(entry)
        if promoted_only and self.fallback:
            # a local CANDIDATE must not shadow the fallback's promoted
            # entry for the same key — a fresh search would otherwise
            # turn off the shipped known-good config until promotion
            fb = self._fallback_doc.get("entries", {}).get(want)
            if fb is not None and fb.get("status") == "promoted":
                return want, dict(fb)
        # scan local then fallback SEPARATELY: in the merged view a local
        # candidate would hide the fallback's promoted entry at the same
        # key — a qualifying local entry still wins (local listed first)
        sources = [self._doc.get("entries", {})]
        if self.fallback:
            sources.append(self._fallback_doc.get("entries", {}))
        candidates: List[Tuple[str, Dict[str, Any]]] = []
        taken = set()
        for src in sources:
            for key, e in src.items():
                if key in taken:
                    continue
                try:
                    fp, mesh, kind, ejv = split_key(key)
                except ValueError:
                    continue
                if (fp, mesh, kind) != (fingerprint, mesh_sig, device_kind):
                    continue
                if ejv == jv:
                    continue  # exact-jax case handled above
                if promoted_only and e.get("status") != "promoted":
                    continue
                taken.add(key)
                candidates.append((key, e))
        if not candidates:
            return None
        key, e = max(candidates, key=lambda ke: str(
            ke[1].get("provenance", {}).get("created_utc", "")))
        out = dict(e)
        out["stale_jax"] = (f"entry tuned under {split_key(key)[3]}, "
                            f"running {jv}")
        return key, out

    # -- mutation ----------------------------------------------------------

    def put(self, key: str, entry: Dict[str, Any],
            save: bool = True) -> Dict[str, Any]:
        split_key(key)  # validate shape early
        entry = dict(entry)
        entry.setdefault("status", "candidate")
        entry.setdefault("provenance", {})
        entry["provenance"].setdefault(
            "created_utc", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        fp, mesh, kind, jv = split_key(key)
        entry["key_parts"] = {"model_fingerprint": fp, "mesh": mesh,
                              "device_kind": kind, "jax_version": jv}
        self._doc.setdefault("entries", {})[key] = entry
        if save:
            self.save()
        return entry

    def mark_promoted(self, key: str, check_report: Optional[str] = None,
                      artifact_sha1: Optional[str] = None,
                      save: bool = True) -> Dict[str, Any]:
        entry = self._doc.get("entries", {}).get(key)
        if entry is None:
            # promoting a fallback (package) entry copies it into the
            # writable store first
            entry = self.get(key)
            if entry is None:
                raise KeyError(f"no store entry {key!r}")
            entry = self.put(key, dict(entry), save=False)
        entry["status"] = "promoted"
        entry.setdefault("provenance", {})["promoted_utc"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        if check_report:
            entry["provenance"]["perf_check"] = check_report
        if artifact_sha1:
            entry["provenance"]["artifact_sha1"] = artifact_sha1
        if save:
            self.save()
        return entry


def artifact_sha1(path: str) -> str:
    """Provenance hash of a bench artifact file."""
    h = hashlib.sha1()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(65536), b""):
            h.update(chunk)
    return h.hexdigest()[:16]
