"""Operator CLI — ``python -m deepspeed_tpu.tuning <cmd>``.

* ``search``  — run a search and write the winner as a ``candidate``
  store entry.  ``--synthetic`` runs the built-in deterministic cost
  model (CI smoke / demo — no device needed); real-model searches use
  the Python API (``tuning.SearchEngine`` with an
  ``EngineTrialRunner``) or the bench harness, which own model/mesh
  construction.
* ``show``    — list store entries (key, status, scores, provenance).
* ``apply``   — merge an entry's overrides into a ds_config JSON and
  print the result (what ``initialize()`` would do, made inspectable).
* ``promote`` — the sentinel gate: candidate + run artifact + baseline
  → promoted on a clean ``perf check``, exit 3 on regression.
* ``explain`` — how the plane fits together, or one entry's provenance.

Exit codes follow the telemetry CLI convention: 0 ok, 2 structural
error, 3 gate verdict (regression blocked the promotion).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from .memory_model import CalibratedMemoryModel
from .promote import promote_entry
from .search import GridStrategy, SearchEngine, SuccessiveHalvingStrategy
from .space import CandidateSpace, Dimension, apply_overrides
from .store import (BestConfigStore, jax_version_key, resolve_store_path,
                    store_key)
from .trial import SyntheticTrialRunner


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 2


# ---------------------------------------------------------------------------
# the synthetic landscape (CI smoke / demo)
# ---------------------------------------------------------------------------

#: the planted optimum the deterministic search must find — includes the
#: kernel plane (ISSUE 12: every kernel is a searchable dimension, so
#: the smoke landscape exercises kernel on/off × block granularity ×
#: overlap chunk count end-to-end through search → store → apply)
SYNTHETIC_BEST = {"train_micro_batch_size_per_gpu": 8,
                  "gradient_accumulation_steps": 1,
                  "zero_optimization.stage": 3,
                  "model.attn_impl": "flash",
                  "kernels.fused_adam": True,
                  "kernels.overlap_chunks": 4}


def synthetic_space() -> CandidateSpace:
    return (CandidateSpace()
            .register(Dimension("train_micro_batch_size_per_gpu",
                                [1, 2, 4, 8, 16]))
            .register(Dimension("gradient_accumulation_steps", [1, 2]))
            .register(Dimension("zero_optimization.stage", [0, 2, 3]))
            .register(Dimension("model.attn_impl", ["xla", "flash"]))
            .register(Dimension("kernels.fused_adam", [False, True]))
            .register(Dimension("kernels.overlap_chunks", [2, 4, 8])))


def synthetic_cost_model(cand: Dict[str, Any]) -> Dict[str, float]:
    """Separable deterministic landscape, argmax at SYNTHETIC_BEST;
    micro-batch 16 OOMs below stage 3 (the pruning path is exercised)."""
    mb = int(cand["train_micro_batch_size_per_gpu"])
    gas = int(cand["gradient_accumulation_steps"])
    stage = int(cand["zero_optimization.stage"])
    if mb >= 16 and stage < 3:
        return {"oom": True}
    mb_gain = {1: 0.4, 2: 0.7, 4: 0.9, 8: 1.0, 16: 0.95}[mb]
    gas_gain = {1: 1.0, 2: 0.9}[gas]
    stage_gain = {0: 0.8, 2: 0.9, 3: 1.0}[stage]
    attn_gain = {"xla": 0.85, "flash": 1.0}[cand.get("model.attn_impl",
                                                     "xla")]
    fused_gain = 1.0 if cand.get("kernels.fused_adam", False) else 0.97
    chunk_gain = {2: 0.92, 4: 1.0, 8: 0.96}[
        int(cand.get("kernels.overlap_chunks", 4))]
    tps = (10000.0 * mb_gain * gas_gain * stage_gain * attn_gain
           * fused_gain * chunk_gain)
    return {"tokens_per_sec": round(tps, 1),
            "mfu": round(tps / 20000.0, 4),
            "measured_state_bytes": float((16 >> min(stage, 3)) * 10**6)}


def _synthetic_key(args: argparse.Namespace) -> str:
    return store_key(args.fingerprint, args.mesh, args.device_kind,
                     jax_version_key())


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------


def cmd_search(args: argparse.Namespace) -> int:
    if not args.synthetic:
        return _fail(
            "only --synthetic searches run from the CLI (no model/mesh "
            "context here); drive real searches through the Python API — "
            "deepspeed_tpu.tuning.SearchEngine with an EngineTrialRunner "
            "(see README 'Autotuning') — or the bench harness")
    mm = CalibratedMemoryModel()  # disabled: the synthetic OOM path covers
    runner = SyntheticTrialRunner(synthetic_cost_model, memory_model=mm)
    # 0 = the strategy's own default (grid 3, halving rung-0 2)
    kw = {"timed_steps": args.timed_steps} if args.timed_steps else {}
    strategy = (SuccessiveHalvingStrategy(**kw)
                if args.strategy == "successive_halving"
                else GridStrategy(**kw))
    eng = SearchEngine(runner, synthetic_space(), strategy=strategy,
                       metric=args.metric,
                       max_candidates=args.max_candidates)
    result = eng.search()
    if result.best is None:
        return _fail("search produced no feasible candidate")
    key = _synthetic_key(args)
    store = BestConfigStore(resolve_store_path(args.store))
    entry = result.to_store_entry()
    entry["provenance"]["source"] = "cli --synthetic"
    store.put(key, entry)
    print(json.dumps({"best": result.best.candidate,
                      "score": {args.metric:
                                result.best.score(args.metric)},
                      "trials_run": result.trials_run,
                      "infeasible": result.infeasible,
                      "store": store.path, "key": key,
                      "status": "candidate"}, indent=2))
    return 0


def _fmt_entry(key: str, e: Dict[str, Any], verbose: bool) -> str:
    scores = ", ".join(f"{k}={v:g}" for k, v in
                       sorted(e.get("scores", {}).items()))
    lines = [f"{key}", f"  status: {e.get('status', '?')}"
             + (f"  scores: {scores}" if scores else "")]
    if verbose:
        lines.append("  overrides: "
                     + json.dumps(e.get("overrides", {}), sort_keys=True))
        if e.get("model_overrides"):
            lines.append("  model_overrides: "
                         + json.dumps(e["model_overrides"], sort_keys=True))
        prov = e.get("provenance", {})
        if prov:
            lines.append("  provenance: "
                         + json.dumps(prov, sort_keys=True))
    return "\n".join(lines)


def cmd_show(args: argparse.Namespace) -> int:
    store = BestConfigStore(resolve_store_path(args.store))
    entries = store.entries()
    if args.key:
        e = store.get(args.key)
        if e is None:
            return _fail(f"no store entry {args.key!r} in {store.path}"
                         + (f" (fallback {store.fallback})"
                            if store.fallback else ""))
        if args.keys_only:
            print(args.key)
        else:
            print(_fmt_entry(args.key, e, verbose=True))
        return 0
    if not entries:
        print(f"store {store.path}: empty"
              + (f" (fallback {store.fallback}: empty too)"
                 if store.fallback else ""))
        return 0
    for key in sorted(entries):
        if args.keys_only:
            print(key)
        else:
            print(_fmt_entry(key, entries[key], verbose=args.verbose))
    return 0


def cmd_apply(args: argparse.Namespace) -> int:
    store = BestConfigStore(resolve_store_path(args.store))
    entry = store.get(args.key)
    if entry is None:
        return _fail(f"no store entry {args.key!r} in {store.path}")
    base: Dict[str, Any] = {}
    if args.config:
        try:
            with open(args.config) as fh:
                base = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            return _fail(f"cannot read config {args.config}: {e}")
    try:
        merged = apply_overrides(base, entry.get("overrides", {}))
    except ValueError as e:
        return _fail(str(e))
    doc: Dict[str, Any] = dict(merged)
    if entry.get("model_overrides"):
        # surfaced, not merged: model knobs belong to model construction
        doc["_tuning_model_overrides"] = entry["model_overrides"]
    print(json.dumps(doc, indent=2, sort_keys=True))
    return 0


def cmd_promote(args: argparse.Namespace) -> int:
    from ..telemetry.perf.baseline import parse_tolerances

    try:
        tol = parse_tolerances(args.tol)
    except ValueError as e:
        return _fail(str(e))
    store = BestConfigStore(resolve_store_path(args.store))
    code, report = promote_entry(store, args.key, args.run, args.baseline,
                                 tolerances=tol)
    print(report)
    return code


EXPLAIN = """\
The autotuning plane (deepspeed_tpu/tuning/) in one pass:

  search   A candidate space (micro-batch x grad-accumulation x remat x
           donation x sharding; offload/ZeRO-stage pluggable) is pruned
           by a LEDGER-CALIBRATED memory model (analytic ZeRO estimate x
           a scale learned from measured pool bytes; drift is the
           tuning/memory_model_drift_frac gauge), then explored by grid
           or successive-halving trials.  Each trial runs a few steps
           in-process and is scored from TELEMETRY: device-fenced
           StepRecords (tok/s, MFU, step-time p50), the compile tracker
           (compile cost, charged to the goodput `compile` bucket), and
           the memory ledger (peak HBM, headroom).  An OOM candidate is
           recorded infeasible with its memory breakdown.

  store    The winner lands in a versioned JSON store as a `candidate`,
           keyed (model fingerprint | mesh shape | device kind | jax
           version) with full provenance (strategy, budget, scores,
           artifact hash).  Different mesh/device NEVER match; a jax-
           version-only mismatch applies with a `stale_jax` note.

  promote  `tuning promote` gates the candidate through `telemetry perf
           check` against the current baseline: any regression beyond
           tolerance exits 3 and the entry stays a candidate.  A clean
           check flips it to `promoted`.

  apply    `initialize()` consults the store (promoted entries only)
           and applies the overrides UNLESS the user pinned the knob in
           their ds_config; what was applied/skipped rides every debug
           bundle (context.tuning) and the bench artifact
           (`tuned_config_source`).
"""


def cmd_explain(args: argparse.Namespace) -> int:
    if args.key:
        store = BestConfigStore(resolve_store_path(args.store))
        e = store.get(args.key)
        if e is None:
            return _fail(f"no store entry {args.key!r}")
        print(_fmt_entry(args.key, e, verbose=True))
        if e.get("stale_jax"):
            print(f"  note: {e['stale_jax']}")
        return 0
    print(EXPLAIN)
    return 0


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.tuning",
        description="telemetry-driven autotuning: search, best-known-"
                    "config store, sentinel-gated promotion")
    sub = p.add_subparsers(dest="cmd", required=True)

    def add_store(sp):
        sp.add_argument("--store", default="",
                        help="store path (default: $DS_TUNING_STORE or "
                             "~/.cache/deepspeed_tpu/best_known_configs"
                             ".json; the package-shipped store is the "
                             "read-only fallback)")

    s = sub.add_parser("search", help="run a search, write the winner as "
                                      "a candidate store entry")
    add_store(s)
    s.add_argument("--synthetic", action="store_true",
                   help="deterministic built-in cost model (CI smoke)")
    s.add_argument("--strategy", choices=["grid", "successive_halving"],
                   default="grid")
    s.add_argument("--metric", default="tokens_per_sec")
    s.add_argument("--timed-steps", type=int, default=0,
                   help="trial length (rung-0 length for "
                        "successive_halving); 0 = strategy default")
    s.add_argument("--max-candidates", type=int, default=0)
    s.add_argument("--fingerprint", default="synthetic-demo",
                   help="model-fingerprint key part for the entry")
    s.add_argument("--mesh", default="devices=1",
                   help="mesh-signature key part")
    s.add_argument("--device-kind", default="synthetic",
                   help="device-kind key part")
    s.set_defaults(fn=cmd_search)

    w = sub.add_parser("show", help="list store entries")
    add_store(w)
    w.add_argument("--key", default="", help="show one entry in full")
    w.add_argument("--keys-only", action="store_true")
    w.add_argument("-v", "--verbose", action="store_true")
    w.set_defaults(fn=cmd_show)

    a = sub.add_parser("apply", help="merge an entry's overrides into a "
                                     "ds_config JSON, print the result")
    add_store(a)
    a.add_argument("--key", required=True)
    a.add_argument("--config", default="",
                   help="base ds_config JSON file ({} when omitted)")
    a.set_defaults(fn=cmd_apply)

    m = sub.add_parser("promote", help="perf-check gate a candidate; "
                                       "exit 3 on regression")
    add_store(m)
    m.add_argument("--key", required=True)
    m.add_argument("--run", required=True,
                   help="the candidate's bench/run artifact JSON")
    m.add_argument("--baseline", required=True,
                   help="the current perf baseline file")
    m.add_argument("--tol", action="append", default=[],
                   metavar="metric=frac",
                   help="tolerance override (repeatable)")
    m.set_defaults(fn=cmd_promote)

    e = sub.add_parser("explain", help="how the plane works, or one "
                                       "entry's provenance")
    add_store(e)
    e.add_argument("--key", default="")
    e.set_defaults(fn=cmd_explain)
    return p


def _logs_to_stderr() -> None:
    """Every subcommand's stdout is one machine-readable document (the
    suite smoke pipes it into json.load); the package logger defaults to
    stdout, so trial-progress lines would corrupt it."""
    import logging

    from ..utils.logging import logger as ds_logger

    for h in ds_logger.handlers:
        if (isinstance(h, logging.StreamHandler)
                and getattr(h, "stream", None) is sys.stdout):
            h.setStream(sys.stderr)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    _logs_to_stderr()
    return int(args.fn(args))
