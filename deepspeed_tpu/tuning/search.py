"""Search strategies + the search engine.

Two strategies over the (memory-pruned) candidate list:

* :class:`GridStrategy` — measure everything at full trial length
  (the reference ``GridSearchTuner``).
* :class:`SuccessiveHalvingStrategy` — measure everything briefly,
  keep the top ``1/eta`` per rung, re-measure survivors with
  ``eta×`` the steps: the measurement budget concentrates on the
  frontier (the reference ``ModelBasedTuner``'s role, but driven by
  measurements rather than a fitted curve — on TPU a short trial is a
  real compile+run, so cheap low-fidelity rungs exist naturally).

The engine pre-prunes candidates through the calibrated memory model
(analytic estimate × ledger-learned scale) so hopeless configs never
compile, and assembles a :class:`SearchResult` whose ``to_store_entry``
is exactly what the best-known-config store persists.
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.logging import log_dist
from .memory_model import CalibratedMemoryModel
from .space import CandidateSpace
from .trial import TrialResult, TrialRunner

#: score metrics where SMALLER wins — ranking negates these (the perf
#: sentinel's PERF_METRICS encodes the same directions)
LOWER_IS_BETTER = {"step_time_p50_ms", "peak_hbm_bytes"}


def ranked_score(result: TrialResult, metric: str) -> Optional[float]:
    """The metric value oriented so that bigger is always better."""
    s = result.score(metric)
    if s is None:
        return None
    return -s if metric in LOWER_IS_BETTER else s


def roofline_tiebreak(result: TrialResult) -> float:
    """Secondary ranking key (anatomy plane): LOWER roofline headroom
    wins a score tie — a candidate running near its roofline is fast
    because of the hardware limit, not because an unexplained stall
    happened to go quiet during its short trial.  Trials without the
    metric rank last among ties."""
    v = (result.metrics or {}).get("roofline_headroom")
    try:
        return float(v) if v is not None else float("inf")
    except (TypeError, ValueError):
        return float("inf")


@dataclass
class SearchResult:
    best: Optional[TrialResult]
    metric: str
    strategy: str
    records: List[Dict[str, Any]] = field(default_factory=list)
    trials_run: int = 0
    candidates_total: int = 0
    pruned_memory: int = 0
    infeasible: int = 0
    wall_s: float = 0.0
    memory_model: Dict[str, Any] = field(default_factory=dict)

    def to_store_entry(self) -> Dict[str, Any]:
        """The store payload for the winning candidate (raises when the
        search found nothing feasible)."""
        if self.best is None:
            raise RuntimeError("search produced no feasible candidate")
        from .space import split_overrides

        overrides, model_overrides = split_overrides(self.best.candidate)
        return {
            "overrides": overrides,
            "model_overrides": model_overrides,
            "scores": {k: round(float(v), 4)
                       for k, v in self.best.metrics.items()},
            "status": "candidate",
            "provenance": {
                "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             time.gmtime()),
                "strategy": self.strategy,
                "score_metric": self.metric,
                "search_budget": {"trials_run": self.trials_run,
                                  "candidates_total": self.candidates_total,
                                  "pruned_memory": self.pruned_memory,
                                  "infeasible": self.infeasible,
                                  "wall_s": round(self.wall_s, 2)},
                "score_source": self.best.source,
            },
        }


class GridStrategy:
    name = "grid"

    def __init__(self, timed_steps: int = 3):
        self.timed_steps = max(int(timed_steps), 1)

    def run(self, runner: TrialRunner,
            candidates: List[Dict[str, Any]], metric: str
            ) -> List[TrialResult]:
        results = []
        for cand in candidates:
            r = runner.run(cand, timed_steps=self.timed_steps)
            score = r.score(metric)
            log_dist(f"tuning[grid] {cand} -> "
                     + ("INFEASIBLE" if not r.feasible
                        else f"{metric}={score:.2f}" if score is not None
                        else "no score"))
            results.append(r)
        return results


class SuccessiveHalvingStrategy:
    """Rung 0 measures every candidate at ``timed_steps``; each rung
    keeps the top ``ceil(n/eta)`` by score and multiplies the steps by
    ``eta``, until one survivor (or an infeasible wipe-out) remains.
    Every measurement lands in the result list — later rungs simply
    append a fresh (longer) result for the surviving candidates."""

    name = "successive_halving"

    def __init__(self, timed_steps: int = 2, eta: int = 2,
                 max_rungs: int = 4):
        self.timed_steps = max(int(timed_steps), 1)
        self.eta = max(int(eta), 2)
        self.max_rungs = max(int(max_rungs), 1)

    def run(self, runner: TrialRunner,
            candidates: List[Dict[str, Any]], metric: str
            ) -> List[TrialResult]:
        results: List[TrialResult] = []
        alive = list(candidates)
        steps = self.timed_steps
        for rung in range(self.max_rungs):
            scored: List[tuple[float, TrialResult]] = []
            for cand in alive:
                r = runner.run(cand, timed_steps=steps)
                results.append(r)
                score = r.score(metric)
                log_dist(f"tuning[halving r{rung} steps={steps}] {cand} -> "
                         + ("INFEASIBLE" if not r.feasible
                            else f"{metric}={score:.2f}"
                            if score is not None else "no score"))
                oriented = ranked_score(r, metric)
                if r.feasible and oriented is not None:
                    scored.append((oriented, r))
            if len(scored) <= 1:
                break
            keep = max(1, math.ceil(len(scored) / self.eta))
            scored.sort(key=lambda t: (-t[0], roofline_tiebreak(t[1])))
            alive = [r.candidate for _, r in scored[:keep]]
            steps *= self.eta
            if keep == 1:
                # confirmation rung: the winner's deciding score must not
                # stay a short-trial fluke — one longer re-measurement
                # supersedes its rung score in the engine's best-selection
                r = runner.run(alive[0], timed_steps=steps)
                results.append(r)
                score = r.score(metric)
                log_dist(f"tuning[halving confirm steps={steps}] "
                         f"{alive[0]} -> "
                         + ("INFEASIBLE" if not r.feasible
                            else f"{metric}={score:.2f}"
                            if score is not None else "no score"))
                break
        return results


class SearchEngine:
    """Memory-prune → strategy → best, with a full record trail."""

    def __init__(self, runner: TrialRunner, space: CandidateSpace,
                 strategy: Any = None, metric: str = "tokens_per_sec",
                 memory_model: Optional[CalibratedMemoryModel] = None,
                 max_candidates: int = 0):
        self.runner = runner
        self.space = space
        self.strategy = strategy if strategy is not None else GridStrategy()
        self.metric = metric
        self.memory_model = memory_model
        self.max_candidates = int(max_candidates)

    @classmethod
    def from_config(cls, runner: TrialRunner, space: CandidateSpace,
                    tuning: Any,
                    memory_model: Optional[CalibratedMemoryModel] = None
                    ) -> "SearchEngine":
        """Build a SearchEngine from the ``tuning.*`` config group (the
        validated ``TuningConfig`` model or a plain dict): ``strategy``,
        ``timed_steps``, ``max_candidates``, ``score``;
        ``hbm_margin_frac`` lands on the memory model and
        ``warmup_steps`` on the runner when they carry those knobs."""
        get = (tuning.get if isinstance(tuning, dict)
               else lambda k, d=None: getattr(tuning, k, d))
        timed = max(int(get("timed_steps", 3) or 3), 1)
        name = str(get("strategy", "successive_halving"))
        strategy = (GridStrategy(timed_steps=timed) if name == "grid"
                    else SuccessiveHalvingStrategy(timed_steps=timed))
        if memory_model is not None and get("hbm_margin_frac") is not None:
            memory_model.margin_frac = float(get("hbm_margin_frac"))
        if hasattr(runner, "warmup_steps") and get("warmup_steps") is not None:
            runner.warmup_steps = max(int(get("warmup_steps")), 0)
        return cls(runner, space, strategy=strategy,
                   metric=str(get("score", "tokens_per_sec")),
                   memory_model=memory_model,
                   max_candidates=int(get("max_candidates", 0) or 0))

    def search(self) -> SearchResult:
        t0 = time.perf_counter()
        result = SearchResult(best=None, metric=self.metric,
                              strategy=getattr(self.strategy, "name",
                                               type(self.strategy).__name__))
        survivors: List[Dict[str, Any]] = []
        for cand in self.space.candidates():
            result.candidates_total += 1
            reason = (self.memory_model.prune_reason(cand)
                      if self.memory_model is not None else None)
            if reason is not None:
                result.pruned_memory += 1
                result.records.append({"candidate": dict(cand),
                                       "pruned": "memory_model",
                                       "reason": reason})
                log_dist(f"tuning {cand} -> PRUNED ({reason})")
                continue
            survivors.append(cand)
        if self.max_candidates and len(survivors) > self.max_candidates:
            dropped = len(survivors) - self.max_candidates
            survivors = survivors[:self.max_candidates]
            result.records.append({"budget_truncated": dropped})
            log_dist(f"tuning: candidate budget keeps "
                     f"{self.max_candidates}, drops {dropped}")

        trials = self.strategy.run(self.runner, survivors, self.metric)
        result.trials_run = len(trials)
        # a candidate may be measured at several fidelities (halving
        # rungs); rank on each candidate's HIGHEST-fidelity result only,
        # or a noisy short rung-0 score of an eliminated candidate could
        # beat the survivor's longer re-measurement
        final: Dict[str, TrialResult] = {}
        for r in trials:
            result.records.append(r.to_record())
            if not r.feasible:
                result.infeasible += 1
                continue
            ckey = json.dumps(r.candidate, sort_keys=True, default=str)
            prev = final.get(ckey)
            if prev is None or r.timed_steps >= prev.timed_steps:
                final[ckey] = r
        best: Optional[TrialResult] = None
        best_oriented = -float("inf")
        for r in final.values():
            oriented = ranked_score(r, self.metric)
            if oriented is None:
                continue
            if (oriented > best_oriented
                    or (best is not None and oriented == best_oriented
                        and roofline_tiebreak(r)
                        < roofline_tiebreak(best))):
                best, best_oriented = r, oriented
        result.best = best
        result.wall_s = time.perf_counter() - t0
        if self.memory_model is not None:
            result.memory_model = self.memory_model.snapshot()
        if best is not None:
            log_dist(f"tuning best: {best.candidate} at "
                     f"{self.metric}={best.score(self.metric):.2f} "
                     f"({result.trials_run} trials, "
                     f"{result.pruned_memory} memory-pruned, "
                     f"{result.infeasible} infeasible)")
        return result
