"""Trial runners — run one candidate for a few steps, score from telemetry.

The old autotuner timed ``time.time()`` around unfenced dispatches; on a
tunneled TPU that measures host queueing, not the device.  Here every
timed step is device-fenced (the loss scalar fetch IS the fence) and the
score comes from the engine's own device-fenced StepRecords when the
candidate engine runs with telemetry — the same numbers the bench and
the perf sentinel read, so a tune can never disagree with them.  Compile
cost is read from the compile tracker (and the engine already charges it
to the goodput ``compile`` bucket, so a tune's compiles never trip the
``throughput_regression`` health rule), and the memory ledger supplies
``peak_hbm_bytes`` / ``hbm_headroom_frac`` per candidate.

A candidate that OOMs is caught via ``is_oom_error`` and recorded as
*infeasible* with its memory breakdown — a data point for the calibrated
memory model, never a crash of the search.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import debug_once, logger
from .space import apply_overrides, split_overrides


@dataclass
class TrialResult:
    candidate: Dict[str, Any]
    feasible: bool = True
    #: score metrics (tokens_per_sec / samples_per_sec / mfu / ...)
    metrics: Dict[str, float] = field(default_factory=dict)
    #: how the score was measured: "telemetry" (device-fenced
    #: StepRecords) or "wall_clock" (fenced loop timing fallback)
    source: str = "wall_clock"
    timed_steps: int = 0
    oom: bool = False
    pruned: Optional[str] = None
    error: Optional[str] = None
    #: per-pool HBM breakdown at failure/completion (memory ledger)
    memory: Dict[str, Any] = field(default_factory=dict)
    compile_s: float = 0.0
    compile_events: int = 0

    def score(self, metric: str) -> Optional[float]:
        v = self.metrics.get(metric)
        return None if v is None else float(v)

    def to_record(self) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"candidate": dict(self.candidate),
                               "feasible": self.feasible,
                               "source": self.source,
                               "timed_steps": self.timed_steps}
        if self.metrics:
            rec["metrics"] = {k: round(float(v), 4)
                              for k, v in self.metrics.items()}
        if self.compile_events:
            rec["compile_s"] = round(self.compile_s, 3)
            rec["compile_events"] = self.compile_events
        if self.pruned:
            rec["pruned"] = self.pruned
        if self.oom:
            rec["oom"] = True
        if self.error:
            rec["error"] = self.error[:300]
        if self.memory:
            rec["memory"] = self.memory
        return rec


class TrialRunner:
    """Interface: ``run(candidate, timed_steps) -> TrialResult``."""

    def run(self, candidate: Dict[str, Any],
            timed_steps: int = 3) -> TrialResult:
        raise NotImplementedError


class EngineTrialRunner(TrialRunner):
    """Build a candidate engine in-process and measure a few steps.

    ``engine_factory(config_dict, model_overrides) -> engine`` and
    ``batch_factory(config_dict) -> batch`` own model/params/mesh so the
    runner stays generic (the legacy one-arg ``engine_factory(config)``
    shape is accepted too).  A factory that declares a ``candidate=``
    keyword additionally receives the full candidate dict — the only way
    to read ``tuning.*`` harness knobs (donation, mesh layout), which
    never enter the DS config.  Engines that expose the ``trial_run``
    hook (DeepSpeedEngine) are measured through it — telemetry-sourced
    numbers; anything else falls back to a fenced wall-clock loop.
    """

    def __init__(self, engine_factory: Callable[..., Any],
                 batch_factory: Callable[[Dict[str, Any]], Any],
                 base_config: Dict[str, Any],
                 warmup_steps: int = 1,
                 memory_model: Optional[Any] = None,
                 teardown: Optional[Callable[[Any], None]] = None):
        self.engine_factory = engine_factory
        self.batch_factory = batch_factory
        self.base_config = dict(base_config)
        self.warmup_steps = max(int(warmup_steps), 0)
        self.memory_model = memory_model
        self.teardown = teardown

    # -- plumbing ----------------------------------------------------------

    def _build(self, candidate: Dict[str, Any]):
        config_over, model_over = split_overrides(candidate)
        # tuning.* keys are search-harness knobs (donation, mesh layout),
        # not DS-config keys the engine validates — factories that care
        # declare a ``candidate=`` keyword and get the full dict
        config_over = {k: v for k, v in config_over.items()
                       if not k.startswith("tuning.")}
        cfg = apply_overrides(self.base_config, config_over)
        shape = self._factory_positional()
        kwargs = ({"candidate": dict(candidate)}
                  if shape["takes_candidate"] else {})
        # the second positional is treated as the model_overrides slot
        # only when it is REQUIRED, is *args, or is NAMED for the role —
        # an unrelated optional second positional (cfg, model_cls=None)
        # must never silently receive the overrides dict
        overrides_slot = (shape["required"] >= 2 or shape["varargs"]
                          or shape["second_name"] in ("model_overrides",
                                                      "model_over",
                                                      "overrides"))
        if model_over:
            if not overrides_slot:
                raise ValueError(
                    f"candidate carries model overrides {model_over} but "
                    f"the engine factory takes only (config) — give it a "
                    f"(config, model_overrides) signature")
            engine = self.engine_factory(cfg, model_over, **kwargs)
        elif shape["required"] >= 2:
            engine = self.engine_factory(cfg, {}, **kwargs)
        else:
            # legacy one-arg factory — a factory with an OPTIONAL second
            # positional (e.g. (cfg, model_cls=...)) keeps its default
            engine = self.engine_factory(cfg, **kwargs)
        return engine, cfg

    def _factory_positional(self) -> Dict[str, Any]:
        """Shape of the engine factory's signature: ``required``
        positional count, the ``second_name`` of its second positional
        (None when absent), ``varargs``, and whether it ``takes_candidate``
        as a keyword.  Unknown signatures count as legacy one-arg."""
        import inspect

        shape: Dict[str, Any] = {"required": 1, "second_name": None,
                                 "varargs": False, "takes_candidate": False}
        try:
            sig = inspect.signature(self.engine_factory)
        except (TypeError, ValueError):
            return shape  # builtins/partials without signatures
        shape["required"] = 0
        capacity = 0
        for p in sig.parameters.values():
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                capacity += 1
                if capacity == 2:
                    shape["second_name"] = p.name
                if p.default is p.empty:
                    shape["required"] += 1
            elif p.kind is p.VAR_POSITIONAL:
                shape["varargs"] = True
            if p.name == "candidate" and (
                    p.kind is p.KEYWORD_ONLY
                    or (p.kind is p.POSITIONAL_OR_KEYWORD and capacity > 2)):
                # keyword-only, or a 3rd+ positional — never one of the
                # two slots (config, model_overrides) we fill positionally
                shape["takes_candidate"] = True
        return shape

    @staticmethod
    def _fence(metrics: Any) -> None:
        """Per-step device fence: fetch the loss scalar
        (``block_until_ready`` is a no-op on tunneled platforms)."""
        if isinstance(metrics, dict) and "loss" in metrics:
            float(metrics["loss"])

    def _memory_breakdown(self) -> Dict[str, Any]:
        try:
            from ..telemetry.memory import get_memory_ledger

            led = get_memory_ledger()
            if not led.enabled:
                return {}
            out: Dict[str, Any] = {"pools_hbm": led.pool_bytes(space="hbm")}
            dev = led.device_stats()
            if dev:
                out["device"] = dev
            return out
        except Exception as e:
            logger.debug(f"tuning: memory breakdown unavailable ({e!r})")
            return {}

    def _calibrate(self, candidate: Dict[str, Any]) -> None:
        if self.memory_model is None:
            return
        try:
            from ..telemetry.memory import get_memory_ledger

            led = get_memory_ledger()
            if not led.enabled:
                return
            pools = led.pool_bytes(space="hbm", include_transient=True)
            measured = sum(pools.get(p, 0)
                           for p in ("params", "grads", "optimizer"))
            self.memory_model.calibrate(candidate, measured)
        except Exception as e:
            logger.debug(f"tuning: ledger calibration skipped ({e!r})")

    # -- the trial ---------------------------------------------------------

    def run(self, candidate: Dict[str, Any],
            timed_steps: int = 3) -> TrialResult:
        from ..telemetry.memory.oom import is_oom_error
        from ..telemetry.perf import get_compile_tracker

        timed_steps = max(int(timed_steps), 1)
        trk = get_compile_tracker()
        ev0, ms0 = trk.events_total, trk.time_ms_total
        engine = None
        try:
            engine, cfg = self._build(candidate)
            batch = self.batch_factory(cfg)
            if callable(getattr(engine, "trial_run", None)):
                summary = engine.trial_run(batch,
                                           warmup_steps=self.warmup_steps,
                                           timed_steps=timed_steps)
                # v is not None, NOT truthiness: hbm_headroom_frac=0.0
                # ("no headroom") is exactly the value analysis needs
                metrics = {k: float(v) for k, v in summary.items()
                           if k in ("tokens_per_sec", "samples_per_sec",
                                    "mfu", "step_time_p50_ms",
                                    "peak_hbm_bytes", "hbm_headroom_frac",
                                    "roofline_headroom")
                           and v is not None}
                source = str(summary.get("source", "telemetry"))
            else:  # legacy/fake engines: fenced wall-clock loop
                m = None
                for _ in range(self.warmup_steps):
                    m = engine.train_step(batch)
                if m is not None:
                    self._fence(m)
                t0 = time.perf_counter()
                for _ in range(timed_steps):
                    m = engine.train_step(batch)
                    self._fence(m)  # per-step fence: device time, not queue
                dt = (time.perf_counter() - t0) / timed_steps
                samples = float(getattr(engine, "train_batch_size", 0) or 1)
                # tokens_per_sec must exist on this path too — it is the
                # default score metric, and a search over wall-clock
                # engines would otherwise find "no feasible candidate";
                # rows×seq from the batch when it has array leaves, else
                # seq degenerates to 1 (tokens == samples)
                rows, seq = samples, 1.0
                try:
                    import jax

                    leaves = [l for l in jax.tree.leaves(batch)
                              if getattr(l, "ndim", 0) >= 1]
                    if leaves:
                        rows = float(leaves[0].shape[0])
                        if leaves[0].ndim >= 2:
                            seq = float(leaves[0].shape[1])
                except Exception as e:
                    debug_once("tuning/wallclock_batch_shape",
                               f"batch shape unreadable ({e!r}); tokens "
                               f"degrade to samples")
                metrics = {"samples_per_sec": samples / max(dt, 1e-9),
                           "tokens_per_sec": rows * seq / max(dt, 1e-9),
                           "step_time_p50_ms": dt * 1e3}
                source = "wall_clock"
            self._calibrate(candidate)
            result = TrialResult(candidate=dict(candidate), feasible=True,
                                 metrics=metrics, source=source,
                                 timed_steps=timed_steps,
                                 memory=self._memory_breakdown())
        except Exception as e:
            if is_oom_error(e):
                result = TrialResult(candidate=dict(candidate),
                                     feasible=False, oom=True,
                                     error=str(e),
                                     memory=self._memory_breakdown())
            else:
                logger.warning(f"tuning trial {candidate} failed: {e}")
                result = TrialResult(candidate=dict(candidate),
                                     feasible=False, error=str(e))
        finally:
            if engine is not None and self.teardown is not None:
                self.teardown(engine)
        result.compile_events = trk.events_total - ev0
        result.compile_s = (trk.time_ms_total - ms0) / 1e3
        return result


class SyntheticTrialRunner(TrialRunner):
    """Deterministic cost-model runner for tests and the CLI smoke.

    ``cost_model(candidate) -> {metric: value, ...}``; raise from it (or
    return ``{"oom": True}``) to simulate an infeasible candidate.  Every
    ``run`` is counted so tests can assert pruning really skipped work.
    """

    def __init__(self, cost_model: Callable[[Dict[str, Any]],
                                            Dict[str, float]],
                 memory_model: Optional[Any] = None):
        self.cost_model = cost_model
        self.memory_model = memory_model
        self.calls: List[Dict[str, Any]] = []

    def run(self, candidate: Dict[str, Any],
            timed_steps: int = 3) -> TrialResult:
        from ..telemetry.memory.oom import is_oom_error

        self.calls.append(dict(candidate))
        try:
            out = dict(self.cost_model(candidate))
        except Exception as e:
            if is_oom_error(e):
                return TrialResult(candidate=dict(candidate), feasible=False,
                                   oom=True, error=str(e),
                                   memory={"pools_hbm": {}})
            return TrialResult(candidate=dict(candidate), feasible=False,
                               error=str(e))
        if out.pop("oom", False):
            return TrialResult(candidate=dict(candidate), feasible=False,
                               oom=True, error="synthetic OOM",
                               memory={"pools_hbm": {}})
        measured = out.pop("measured_state_bytes", None)
        if measured and self.memory_model is not None:
            self.memory_model.calibrate(candidate, int(measured))
        return TrialResult(candidate=dict(candidate), feasible=True,
                           metrics={k: float(v) for k, v in out.items()},
                           source="synthetic", timed_steps=int(timed_steps))
