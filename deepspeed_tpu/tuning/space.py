"""Candidate space — the pluggable dimension registry.

A *dimension* is one tunable knob: a name, the values to try, and where
the knob lives — most are dotted DS-config keys (applied into the config
dict the engine factory receives), some are *model* knobs (``model.*``
prefixed: remat policy, attention impl — applied by the caller that owns
model construction, since the engine never rebuilds the user's model),
and donation/mesh knobs ride the same dotted convention under their
subsystem groups.

A *candidate* is a plain ``{dimension_name: value}`` dict; its store
form is the same dict (dotted keys ARE the override format the
best-known-config store persists and ``initialize()`` re-applies).
"""

from __future__ import annotations

import itertools
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

#: overrides under this prefix target the MODEL config (remat policy,
#: attention impl), not the DS config — ``initialize()`` cannot apply
#: them (it never rebuilds the caller's model); bench/search harnesses
#: that own model construction do.
MODEL_KEY_PREFIX = "model."


@dataclass
class Dimension:
    """One tunable knob.

    ``name`` is the dotted override key (``train_micro_batch_size_per_gpu``,
    ``zero_optimization.stage``, ``model.remat``).  ``values`` is the
    candidate list in search order.  ``feasible`` (optional) rejects a
    value given the partial candidate built so far — cheap structural
    constraints (gas must divide batch) belong here, memory constraints
    belong to the calibrated memory model."""

    name: str
    values: Sequence[Any]
    description: str = ""
    feasible: Optional[Callable[[Any, Dict[str, Any]], bool]] = None

    def __post_init__(self) -> None:
        if not self.values:
            raise ValueError(f"dimension {self.name!r}: empty value list")


@dataclass
class CandidateSpace:
    """Ordered registry of dimensions with candidate enumeration."""

    dimensions: List[Dimension] = field(default_factory=list)

    def register(self, dim: Dimension) -> "CandidateSpace":
        if any(d.name == dim.name for d in self.dimensions):
            raise ValueError(f"dimension {dim.name!r} already registered")
        self.dimensions.append(dim)
        return self

    def remove(self, name: str) -> "CandidateSpace":
        self.dimensions = [d for d in self.dimensions if d.name != name]
        return self

    def names(self) -> List[str]:
        return [d.name for d in self.dimensions]

    def __len__(self) -> int:
        n = 1
        for d in self.dimensions:
            n *= len(d.values)
        return n

    def candidates(self) -> Iterator[Dict[str, Any]]:
        """Enumerate the full cross product, dropping combos any
        dimension's ``feasible`` hook rejects."""
        names = [d.name for d in self.dimensions]
        for combo in itertools.product(*(d.values for d in self.dimensions)):
            cand = dict(zip(names, combo))
            ok = True
            for d in self.dimensions:
                if d.feasible is not None and not d.feasible(cand[d.name],
                                                             cand):
                    ok = False
                    break
            if ok:
                yield cand


def split_overrides(candidate: Dict[str, Any]
                    ) -> tuple[Dict[str, Any], Dict[str, Any]]:
    """Split a candidate into (ds-config overrides, model overrides) —
    the latter with the ``model.`` prefix stripped."""
    config = {k: v for k, v in candidate.items()
              if not k.startswith(MODEL_KEY_PREFIX)}
    model = {k[len(MODEL_KEY_PREFIX):]: v for k, v in candidate.items()
             if k.startswith(MODEL_KEY_PREFIX)}
    return config, model


def apply_overrides(base_config: Dict[str, Any],
                    overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Deep-copy ``base_config`` and set each dotted key (the same
    traversal contract as ``DS_AUTOTUNING_CONFIG_OVERRIDE``); ``model.*``
    keys are rejected — route them through :func:`split_overrides`."""
    cfg = json.loads(json.dumps(base_config))
    for dotted, value in overrides.items():
        if dotted.startswith(MODEL_KEY_PREFIX):
            raise ValueError(
                f"override {dotted!r} targets the model config — apply it "
                f"where the model is constructed (split_overrides)")
        node = cfg
        parts = dotted.split(".")
        for p in parts[:-1]:
            cur = node.get(p)
            if cur is not None and not isinstance(cur, dict):
                raise ValueError(
                    f"override key {dotted!r}: config node {p!r} holds the "
                    f"non-object value {cur!r} — cannot set a nested key "
                    f"under it")
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return cfg


def apply_calibration(store: Any = None,
                      device_kind: Optional[str] = None) -> float:
    """Ground the measured-once Pallas crossover constants in fleet
    profiler measurement (ISSUE 20).

    ROADMAP carries the debt explicitly: every PR-12 crossover threshold
    is a constant measured once on one host.  Once a ``telemetry
    profile`` capture has persisted a per-device-kind ``compute`` factor
    (measured/modeled ratio), the MoE dense/sparse dispatch crossover
    scales by ``1/factor`` — a device measured 2x slower than modeled on
    compute flips to the sparse path at half the T·E·C volume.  Returns
    the scale applied (1.0 when no calibration exists)."""
    from ..telemetry.profiler.calibration import get_calibration_store

    store = store or get_calibration_store()
    if device_kind is None:
        try:
            import jax

            d = jax.devices()[0]
            device_kind = (getattr(d, "device_kind", "")
                           or getattr(d, "platform", "") or "unknown")
        except Exception:
            device_kind = "unknown"
    try:
        factor = float(store.factor(device_kind, "compute"))
    except Exception:
        factor = 1.0
    scale = 1.0 / factor if factor > 0 else 1.0
    scale = min(max(scale, 0.25), 4.0)
    from ..ops.pallas.moe_dispatch import set_crossover_scale

    set_crossover_scale(scale)
    return scale


def default_space(max_micro_batch: int = 16,
                  include_offload: bool = False,
                  include_zero_stage: bool = True,
                  mesh_layouts: Optional[Sequence[str]] = None,
                  include_kernels: bool = True,
                  include_moe: bool = False,
                  moe_ep_degrees: Sequence[int] = (1, 2, 4),
                  ) -> CandidateSpace:
    """The stock search space: micro-batch × grad-accumulation × remat ×
    donation (× ZeRO stage, × offload, × mesh layout when asked) × the
    Pallas kernel plane (attention impl × flash block sizes × fused
    optimizer × collective overlap — every kernel is a searchable
    dimension, so the store picks winners per (model, mesh,
    device_kind) instead of a global default guessing).

    ``mesh_layouts`` entries are opaque layout names the trial harness
    interprets (an engine rebuild on a different mesh); omitted on
    single-chip searches where there is only one layout."""
    micro = [b for b in (1, 2, 4, 8, 16, 32) if b <= max_micro_batch]
    space = CandidateSpace()
    space.register(Dimension(
        "train_micro_batch_size_per_gpu", micro,
        description="per-chip micro batch (activation footprint vs MXU "
                    "utilization)"))
    space.register(Dimension(
        "gradient_accumulation_steps", [1, 2, 4],
        description="microbatch scan length at fixed global batch"))
    space.register(Dimension(
        "model.remat", [True, False],
        description="activation rematerialization (jax.checkpoint) — "
                    "recompute vs stash"))
    space.register(Dimension(
        "tuning.donate_state", [True],
        description="donate TrainState buffers into the step program "
                    "(off only for debugging aliasing)"))
    if include_zero_stage:
        space.register(Dimension(
            "zero_optimization.stage", [0, 1, 2, 3],
            description="ZeRO partitioning stage (reference tuning_space "
                        "dimension)"))
    if include_offload:
        space.register(Dimension(
            "zero_optimization.offload_optimizer.device", ["none", "cpu"],
            description="host-offloaded optimizer states (reference "
                        "offload dimension)"))
    if mesh_layouts:
        space.register(Dimension(
            "tuning.mesh_layout", list(mesh_layouts),
            description="mesh/sharding layout name the trial harness "
                        "realizes (dp/tp/sp split)"))
    if include_kernels:
        flash_on = lambda v, cand: (
            v == 0 or cand.get("model.attn_impl") == "flash")
        space.register(Dimension(
            "model.attn_impl", ["xla", "flash"],
            description="attention kernel: XLA einsum+softmax vs the "
                        "Pallas flash family (ops/pallas/"
                        "flash_attention.py dispatch ladder)"))
        space.register(Dimension(
            "model.flash_block_q", [0, 256, 512],
            description="flash q-block (0 = seq-length auto table)",
            feasible=flash_on))
        space.register(Dimension(
            "model.flash_block_k", [0, 256, 512],
            description="flash k-block (0 = seq-length auto table)",
            feasible=flash_on))
        space.register(Dimension(
            "kernels.fused_adam", [False, True],
            description="one-pass fused Pallas Adam over ZeRO shards vs "
                        "the optax chain (ops/pallas/fused_optimizer.py)"))
        space.register(Dimension(
            "kernels.overlap_collectives", [False, True],
            description="ZeRO-3 chunked-ring collective overlap "
                        "(comm/overlap.py) vs monolithic GSPMD "
                        "collectives",
            feasible=lambda v, cand: (not v) or cand.get(
                "zero_optimization.stage", 3) >= 3))
        space.register(Dimension(
            "kernels.overlap_chunks", [2, 4, 8],
            description="ring payloads per shard (finer pipelining vs "
                        "per-hop latency)",
            feasible=lambda v, cand: cand.get(
                "kernels.overlap_collectives", False) or v == 4))
    if include_moe:
        # the expert-parallel plane (ISSUE 19): ep degree × capacity
        # slack × dispatch rung.  ep rides the DS config (engine rebuilds
        # the mesh); capacity factor and dispatch impl are model knobs
        # (the MoE block is built with the model).
        space.register(Dimension(
            "moe.expert_parallel_size", list(moe_ep_degrees),
            description="expert mesh axis degree (experts sharded "
                        "ep-ways; ZeRO composes over (expert, data))"))
        space.register(Dimension(
            "model.capacity_factor", [1.0, 1.25, 2.0],
            description="expert capacity slack: FLOPs/memory per step vs "
                        "token drop rate under routing skew"))
        space.register(Dimension(
            "model.moe_dispatch_impl", ["auto", "dense", "sparse"],
            description="token dispatch rung: fused dense einsum vs "
                        "index-form gathers (ops/pallas/moe_dispatch.py; "
                        "'pallas' is picked by auto on unsharded TPU)"))
    return space
