"""Ledger-calibrated memory model — prune before you compile.

The analytic ``zero_memory_estimate`` (autotuning/autotuner.py — params
2N + grads 2N + fp32 master/Adam 12N, sharded per ZeRO stage) is a fine
*shape* for the state footprint but a silently wrong *scale* mis-prunes
candidates: it ignores activation residency, allocator rounding, XLA
scratch, and whatever else the real program holds.  This model keeps the
analytic shape and learns the scale from the PR-7 memory ledger: every
trial that actually runs reports its measured HBM state bytes, the
estimate-vs-measured ratio becomes the calibration factor (EWMA over
trials), and the drift is published as the
``tuning/memory_model_drift_frac`` gauge so a mis-modeling is a visible
number, not a mystery prune.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..autotuning.autotuner import zero_memory_estimate
from ..utils.logging import debug_once


class CalibratedMemoryModel:
    """Analytic ZeRO state estimate × a measured calibration scale.

    ``params_count``/``hbm_limit_bytes`` of 0 disable pruning entirely
    (the analytic model has nothing to say); calibration still records
    drift when measurements arrive."""

    def __init__(self, params_count: int = 0, hbm_limit_bytes: int = 0,
                 dp_size: int = 1, base_config: Optional[Dict[str, Any]] = None,
                 margin_frac: float = 0.05, ewma: float = 0.5):
        self.params_count = int(params_count)
        self.hbm_limit_bytes = int(hbm_limit_bytes)
        self.dp_size = max(int(dp_size), 1)
        self.base_config = dict(base_config or {})
        self.margin_frac = float(margin_frac)
        self.ewma = float(ewma)
        #: measured/estimated ratio learned from trials (1.0 = trust the
        #: analytic model as-is)
        self.scale = 1.0
        #: signed drift of the last calibration: (estimate - measured)/measured
        self.last_drift_frac: Optional[float] = None
        self.calibrations = 0

    # -- candidate knob extraction ----------------------------------------

    def _stage_and_offload(self, candidate: Dict[str, Any]) -> tuple[int, bool]:
        base_zero = self.base_config.get("zero_optimization", {}) or {}
        stage = int(candidate.get("zero_optimization.stage",
                                  base_zero.get("stage", 0)))
        base_off = (base_zero.get("offload_optimizer", {}) or {}).get(
            "device", "none")
        offload = str(candidate.get(
            "zero_optimization.offload_optimizer.device", base_off)) == "cpu"
        return stage, offload

    # -- estimate / prune / calibrate --------------------------------------

    def estimate(self, candidate: Dict[str, Any]) -> int:
        """Calibrated state-bytes estimate for a candidate (0 when the
        model is disabled)."""
        if not self.params_count:
            return 0
        stage, offload = self._stage_and_offload(candidate)
        analytic = zero_memory_estimate(self.params_count, stage,
                                        self.dp_size, offload)
        return int(analytic * self.scale)

    def prune_reason(self, candidate: Dict[str, Any]) -> Optional[str]:
        """Non-None → skip this candidate without compiling it: the
        calibrated state estimate alone exceeds the HBM budget (minus
        the safety margin kept for activations/scratch)."""
        if not (self.params_count and self.hbm_limit_bytes):
            return None
        est = self.estimate(candidate)
        budget = int(self.hbm_limit_bytes * (1.0 - self.margin_frac))
        if est > budget:
            return (f"calibrated state estimate {est / 2**30:.2f} GiB "
                    f"(scale {self.scale:.2f}) exceeds HBM budget "
                    f"{budget / 2**30:.2f} GiB")
        return None

    def calibrate(self, candidate: Dict[str, Any],
                  measured_state_bytes: int) -> Optional[float]:
        """Feed a trial's MEASURED state bytes (the memory ledger's
        hbm params+grads+optimizer pools) back into the model.  Returns
        the drift fraction recorded, or None when there was nothing to
        compare (model disabled / zero measurement)."""
        if not self.params_count or measured_state_bytes <= 0:
            return None
        stage, offload = self._stage_and_offload(candidate)
        analytic = zero_memory_estimate(self.params_count, stage,
                                        self.dp_size, offload)
        if analytic <= 0:
            return None
        ratio = measured_state_bytes / analytic
        # EWMA toward the measured ratio: one weird trial (a partially
        # registered ledger) must not swing every later prune decision
        self.scale = (self.ewma * ratio + (1.0 - self.ewma) * self.scale
                      if self.calibrations else ratio)
        self.calibrations += 1
        est = analytic * 1.0  # drift is of the UNcalibrated model — the
        # gauge answers "how wrong is the analytic formula here", which
        # stays meaningful after the scale has absorbed the error
        drift = (est - measured_state_bytes) / measured_state_bytes
        self.last_drift_frac = drift
        self._publish_drift(drift)
        return drift

    def _publish_drift(self, drift: float) -> None:
        try:
            from ..telemetry import get_telemetry

            tel = get_telemetry()
            if tel.enabled:
                tel.registry.gauge(
                    "tuning/memory_model_drift_frac",
                    "analytic-vs-measured state-bytes drift of the "
                    "autotuning memory model").set(round(drift, 4))
        except Exception as e:  # gauge publishing must never fail a tune
            debug_once("tuning/drift_gauge",
                       f"memory-model drift gauge unavailable ({e!r})")

    def snapshot(self) -> Dict[str, Any]:
        return {"params_count": self.params_count,
                "hbm_limit_bytes": self.hbm_limit_bytes,
                "dp_size": self.dp_size, "scale": round(self.scale, 4),
                "calibrations": self.calibrations,
                "last_drift_frac": (None if self.last_drift_frac is None
                                    else round(self.last_drift_frac, 4)),
                "margin_frac": self.margin_frac}


def hbm_limit_bytes() -> int:
    """Device HBM capacity via the memory ledger's device stats (0 when
    the platform reports none — CPU backends)."""
    from ..telemetry.memory import get_memory_ledger

    stats = get_memory_ledger().device_stats()
    return int(stats.get("bytes_limit", 0) or 0)
