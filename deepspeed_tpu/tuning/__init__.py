"""Telemetry-driven autotuning plane (ISSUE 9).

The reference DeepSpeed ships a whole autotuning subsystem
(``deepspeed/autotuning/`` — ``Autotuner`` + grid/random/model-based
tuners that profile candidate configs and emit a best-config JSON,
PAPER.md §2.5).  This package is its production rebuild on top of the
observability stack PRs 5/7 put in place — trials are scored from
*telemetry, not wall clock*, and a tuned config only becomes the stored
default by passing the perf sentinel:

* :mod:`.space` — the candidate space: a pluggable dimension registry
  (micro-batch × grad-accumulation × remat × donation × sharding, with
  offload / ZeRO-stage available as extra dimensions) and dotted-key
  candidate application.
* :mod:`.memory_model` — the ledger-calibrated memory model: the
  analytic ``zero_memory_estimate`` cross-checked against the PR-7
  memory ledger's *measured* per-pool bytes whenever a trial actually
  runs; drift is the ``tuning/memory_model_drift_frac`` gauge, and the
  calibrated estimate prunes infeasible candidates before they compile.
* :mod:`.trial` — trial runners: build a candidate engine, run a few
  steps in-process, score from device-fenced StepRecords / the compile
  tracker / the memory ledger; OOMs become *infeasible* results with
  their memory breakdown, never crashes.
* :mod:`.search` — grid + successive-halving strategies over the
  pruned candidate list.
* :mod:`.store` — the versioned best-known-config store keyed by
  (model fingerprint, mesh shape, device_kind, jax version), with
  provenance (artifact hash, scores, search budget).
* :mod:`.autoapply` — ``entry.initialize()`` consults the store and
  applies the stored config unless the user pinned the knob; what was
  applied lands in bench artifacts (``tuned_config_source``) and the
  debug-bundle context.
* :mod:`.promote` — sentinel-gated promotion: a candidate entry becomes
  the stored default only by passing ``telemetry perf check`` against
  the current baseline (exit-3 regression blocks it).
* :mod:`.cli` — ``python -m deepspeed_tpu.tuning
  {search,show,apply,promote,explain}``.
"""

from .memory_model import CalibratedMemoryModel
from .search import (GridStrategy, SearchEngine, SearchResult,
                     SuccessiveHalvingStrategy)
from .space import (MODEL_KEY_PREFIX, CandidateSpace, Dimension,
                    apply_overrides, default_space, split_overrides)
from .store import (BestConfigStore, current_device_kind, jax_version_key,
                    mesh_signature, model_fingerprint, package_store_path,
                    resolve_store_path, store_key)
from .trial import (EngineTrialRunner, SyntheticTrialRunner, TrialResult,
                    TrialRunner)
from .autoapply import (applied_info, maybe_apply_tuned_config,
                        reset_applied, tuned_config_source)
from .promote import promote_entry

__all__ = [
    "CandidateSpace", "Dimension", "default_space", "apply_overrides",
    "split_overrides", "MODEL_KEY_PREFIX",
    "CalibratedMemoryModel",
    "TrialResult", "TrialRunner", "EngineTrialRunner",
    "SyntheticTrialRunner",
    "SearchEngine", "SearchResult", "GridStrategy",
    "SuccessiveHalvingStrategy",
    "BestConfigStore", "store_key", "model_fingerprint", "mesh_signature",
    "current_device_kind", "jax_version_key", "resolve_store_path",
    "package_store_path",
    "maybe_apply_tuned_config", "applied_info", "tuned_config_source",
    "reset_applied",
    "promote_entry",
]
