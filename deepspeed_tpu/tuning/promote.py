"""Sentinel-gated promotion — the perf check IS the gate.

A search emits a ``candidate`` store entry; it becomes the stored
default (``promoted``, the status ``initialize()`` applies) only by
passing ``telemetry perf check`` against the current baseline: the
candidate's bench/run artifact is compared metric-by-metric with the
same tolerance machinery the CI sentinel uses, and any regression
beyond tolerance BLOCKS the promotion with the sentinel's exit code 3.
This closes the PR-5 loop: the same gate that stops a code regression
stops a bad tune from becoming the default.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..telemetry.perf import baseline as perfmod
from ..utils.logging import logger
from .store import BestConfigStore, artifact_sha1

#: exit codes (the telemetry CLI convention)
PROMOTE_OK = 0
PROMOTE_ERROR = 2
PROMOTE_BLOCKED = 3


def promote_entry(store: BestConfigStore, key: str, run_path: str,
                  baseline_path: str,
                  tolerances: Optional[Dict[str, float]] = None
                  ) -> Tuple[int, str]:
    """Gate ``key``'s candidate entry on ``run_path`` (its measured
    bench/run artifact) vs ``baseline_path``.  Returns (exit_code,
    report): 0 = promoted (store updated), 3 = regression blocked it,
    2 = structural error (missing entry/metrics/baseline)."""
    entry = store.get(key)
    if entry is None:
        return PROMOTE_ERROR, f"no store entry {key!r}"
    try:
        run = perfmod.load_run(run_path)
    except (OSError, ValueError) as e:
        return PROMOTE_ERROR, f"cannot read run artifact: {e}"
    metrics = perfmod.extract_perf(run)
    if not metrics:
        reason = perfmod.environment_failure_reason(run)
        if reason:
            return (PROMOTE_ERROR,
                    f"run artifact carries no data (environment failure: "
                    f"{reason}) — a no-data run cannot justify a promotion")
        return PROMOTE_ERROR, (
            f"{run_path}: no sentinel metrics "
            f"({', '.join(perfmod.PERF_METRICS)}) — not a bench artifact?")
    try:
        base = perfmod.load_baseline(baseline_path)
    except (OSError, ValueError) as e:
        return PROMOTE_ERROR, (f"cannot read baseline {baseline_path} "
                               f"({e}); run `telemetry perf baseline` first")
    result = perfmod.check_regression(metrics, base, tolerances=tolerances)
    report_lines: List[str] = [perfmod.format_check_report(result)]
    if not result["compared"]:
        return PROMOTE_ERROR, "\n".join(
            report_lines + ["run and baseline share no metrics — "
                            "cannot gate the promotion"])
    if result["regressions"]:
        report_lines.append(
            f"PROMOTION BLOCKED: {len(result['regressions'])} metric(s) "
            f"regressed beyond tolerance vs {baseline_path} — the tuned "
            f"config does not beat the baseline it would replace")
        return PROMOTE_BLOCKED, "\n".join(report_lines)
    try:
        sha = artifact_sha1(run_path)
    except OSError as e:
        logger.warning(f"tuning: artifact hash unavailable ({e})")
        sha = None
    summary = _one_line_summary(result)
    store.mark_promoted(key, check_report=summary, artifact_sha1=sha)
    report_lines.append(f"PROMOTED {key} (perf check clean: {summary})")
    return PROMOTE_OK, "\n".join(report_lines)


def _one_line_summary(result: Dict[str, Any]) -> str:
    imp = [f"{r['metric']} {r['baseline']:g}->{r['current']:g}"
           for r in result["improvements"]]
    parts = [f"compared={len(result['compared'])}"]
    if imp:
        parts.append("improved " + "; ".join(imp))
    return ", ".join(parts)
