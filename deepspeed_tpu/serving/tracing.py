"""Distributed request tracing — follow ONE request across the fleet
(ISSUE 15 tentpole).

A request entering the serving plane crosses real process and network
boundaries (front door → NetworkFrontend → prefill worker → P2P KV
transfer → decode worker); the aggregate trackers say *that* p99
regressed, never *which request* or *where its time went*.  This module
is the Dapper-style answer:

* **Context propagation** — the front door mints (or accepts via the
  ``X-DS-Trace`` header) a request trace id; it rides the worker
  JSON-line protocol (``submit``/``prefill``/``adopt_begin``) and the
  KV-transfer page messages, so every process touching the request tags
  its :class:`~.metrics.RequestRecord` with it.  Sampling is head-based
  and DETERMINISTIC on the id (:func:`~.metrics.head_sampled`), with an
  explicit ``sampled`` flag riding the RPCs once a request turns
  anomalous (a replay must be recorded on the worker it replays to,
  even at ``sample_rate=0``).
* **Cross-process shipment** — the process-global :class:`RequestLog`
  registers as a rollup *aux stream* (``telemetry/requests/<node>``,
  the PR-13 push path: store-down beats leave the batch buffered; the
  publication always holds the last window plus open-record snapshots,
  so a ``kill -9``'d worker's final push still shows its partial lane).
* **Assembly** — :func:`assemble_timeline` merges every node's records
  for one trace id into clock-aligned lanes (each publication carries
  its node's clocksync status; ``perf_counter + offset_s`` is the store
  clock), rendered as text (``python -m deepspeed_tpu.serving trace
  <id>``) or as Chrome-trace request lanes (``--out``, and folded into
  ``telemetry collect``'s ``cluster_trace.json``).

Also here: the front door's structured :class:`AccessLog` (one JSONL
line per request, size-capped rotation) — the flat index you grep for a
trace id before assembling its timeline.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import warn_once
from .metrics import RequestLog

#: the request trace-id header: accepted on ``POST /v1/generate``,
#: echoed on every reply (including 4xx/429) and in the SSE ``done``
#: event — an edge proxy can stamp it and correlate end to end
TRACE_HEADER = "X-DS-Trace"

#: store key prefix for per-node request-record publications (the
#: rollup aux stream)
REQUESTS_PREFIX = "telemetry/requests/"

_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._\-]{1,64}$")


def mint_trace_id() -> str:
    return os.urandom(8).hex()


def sanitize_trace_id(raw: Any) -> Optional[str]:
    """A client-supplied trace id, or None when absent/unusable (the
    caller mints one instead — a hostile header must not be able to
    smuggle newlines into logs or store keys)."""
    if not raw:
        return None
    s = str(raw).strip()
    return s if _TRACE_ID_RE.match(s) else None


# ---------------------------------------------------------------------------
# the process-global request log (registered as a rollup aux stream)
# ---------------------------------------------------------------------------

_request_log = RequestLog()


def get_request_log() -> RequestLog:
    return _request_log


def configure_request_log(**kw: Any) -> RequestLog:
    return _request_log.configure(**kw)


def configure_tracing_from_config(tcfg: Any) -> RequestLog:
    """Map the ``serving.tracing.*`` config group onto the process
    request log."""
    return _request_log.configure(
        enabled=bool(getattr(tcfg, "enabled", True)),
        sample_rate=float(getattr(tcfg, "sample_rate", 1.0)),
        maxlen=int(getattr(tcfg, "ring", 256)),
        anomaly_ttft_ms=float(getattr(tcfg, "anomaly_ttft_ms", 2000.0)),
        token_cap=int(getattr(tcfg, "token_timings", 512)))


def _register_aux_stream() -> None:
    from ..telemetry.rollup import register_aux_stream

    register_aux_stream("requests", _request_log)


# importing the serving plane wires its request stream into every
# subsequent push_node_telemetry beat (worker heartbeats, the front
# door's publisher, the elastic agent's tick) — no extra transport
_register_aux_stream()


# ---------------------------------------------------------------------------
# front-door structured access log (ISSUE 15 satellite)
# ---------------------------------------------------------------------------

class AccessLog:
    """One JSONL line per front-door request, size-cap rotated.

    Rotation keeps exactly one predecessor (``<path>.1`` — the same
    newest-K posture as flight-recorder bundle retention): when the
    live file would exceed ``max_bytes`` it is renamed aside and a
    fresh one starts, so the log can never eat the disk under a
    request flood."""

    def __init__(self, path: str, max_bytes: int = 8 << 20):
        self.path = str(path)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        try:
            self._size = os.path.getsize(self.path)
        except OSError:
            self._size = 0

    def write(self, **fields: Any) -> None:
        fields.setdefault("ts", round(time.time(), 3))
        line = json.dumps(fields, default=str) + "\n"
        data = line.encode()
        with self._lock:
            try:
                if self._size and self._size + len(data) > self.max_bytes:
                    os.replace(self.path, self.path + ".1")
                    self._size = 0
                with open(self.path, "a") as fh:
                    fh.write(line)
                self._size += len(data)
            except OSError as e:
                warn_once("serving/access-log",
                          f"access log write failed ({e!r}); "
                          f"requests keep serving")


# ---------------------------------------------------------------------------
# fetch + assembly (the read side)
# ---------------------------------------------------------------------------

def fetch_request_docs(client: Any) -> Dict[str, Dict[str, Any]]:
    """Every node's current request-record publication from the store:
    ``{node_id: {stream, clock, records: [...]}}``."""
    out: Dict[str, Dict[str, Any]] = {}
    for key in sorted(client.keys(REQUESTS_PREFIX)):
        doc = client.get(key)
        if isinstance(doc, dict) and isinstance(doc.get("records"), list):
            out[key[len(REQUESTS_PREFIX):]] = doc
    return out


def find_trace(docs: Dict[str, Dict[str, Any]], trace_id: str
               ) -> List[Dict[str, Any]]:
    """Matches for one trace id across every node's publication:
    ``[{node, aligned, offset_s, record}]``.  A prefix of the id
    (>= 6 chars) matches too — operators paste truncated ids — but an
    EXACT match always wins outright, and a prefix that resolves to
    more than one distinct id returns all of them so the caller can
    refuse to merge two requests into one timeline
    (:func:`distinct_trace_ids`)."""
    tid = str(trace_id)
    exact: List[Dict[str, Any]] = []
    prefix: List[Dict[str, Any]] = []
    for node, doc in sorted(docs.items()):
        clock = doc.get("clock") or {}
        aligned = bool(clock.get("synced")) \
            and isinstance(clock.get("offset_s"), (int, float))
        for rec in doc.get("records") or []:
            rid = str(rec.get("trace_id", ""))
            m = {"node": node, "aligned": aligned,
                 "offset_s": float(clock.get("offset_s") or 0.0),
                 "record": rec}
            if rid == tid:
                exact.append(m)
            elif len(tid) >= 6 and rid.startswith(tid):
                prefix.append(m)
    return exact if exact else prefix


def distinct_trace_ids(matches: List[Dict[str, Any]]) -> List[str]:
    return sorted({str(m["record"].get("trace_id", ""))
                   for m in matches})


def assemble_timeline(matches: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One clock-aligned timeline from every lane that touched the
    request.  Aligned lanes land on the shared store clock
    (``perf_ts + offset_s``) re-based to the earliest aligned instant;
    unaligned lanes are included flagged, re-based to their own start
    (internal order preserved) — same contract as the PR-13 merged
    trace."""
    if not matches:
        return {"lanes": [], "trace_id": None}
    tid = str(matches[0]["record"].get("trace_id"))
    aligned_starts = [
        float(m["record"].get("start_ts", 0.0)) + m["offset_s"]
        for m in matches if m["aligned"]]
    base_s = min(aligned_starts) if aligned_starts else 0.0
    lanes: List[Dict[str, Any]] = []
    for m in matches:
        rec = m["record"]
        off = m["offset_s"] if m["aligned"] else 0.0
        lane_base = base_s if m["aligned"] \
            else float(rec.get("start_ts", 0.0))

        def rel_ms(ts: Any) -> Optional[float]:
            if not isinstance(ts, (int, float)):
                return None
            return round((float(ts) + off - lane_base) * 1e3, 3)

        items: List[Dict[str, Any]] = []
        for ev in rec.get("events") or []:
            t = rel_ms(ev.get("ts"))
            if t is None:
                continue
            it = {"t_ms": t, "kind": "event", "name": ev.get("name")}
            it.update({k: v for k, v in ev.items()
                       if k not in ("ts", "name")})
            items.append(it)
        for ph in rec.get("phases") or []:
            t = rel_ms(ph.get("ts"))
            if t is None:
                continue
            it = {"t_ms": t, "kind": "phase", "name": ph.get("phase"),
                  "dur_ms": ph.get("dur_ms")}
            it.update({k: v for k, v in ph.items()
                       if k not in ("ts", "phase", "dur_ms")})
            items.append(it)
        items.sort(key=lambda it: it["t_ms"])
        start_ms = rel_ms(rec.get("start_ts"))
        end_ms = rel_ms(rec.get("end_ts"))
        lane = {
            "node": m["node"], "aligned": m["aligned"],
            "status": rec.get("status"),
            "done": bool(rec.get("done", True)),
            "klass": rec.get("klass"),
            "start_ms": start_ms, "end_ms": end_ms,
            "span_ms": (round(end_ms - start_ms, 3)
                        if None not in (start_ms, end_ms) else None),
            "tokens": rec.get("tokens"),
            "replays": rec.get("replays"),
            "preempts": rec.get("preempts"),
            "items": items,
            "record": rec,
        }
        lanes.append(lane)
    lanes.sort(key=lambda ln: (not ln["aligned"], ln["start_ms"] or 0.0,
                               ln["node"]))
    spans = [ln["end_ms"] for ln in lanes
             if ln["aligned"] and ln["end_ms"] is not None]
    return {"trace_id": tid, "lanes": lanes,
            "aligned_lanes": sum(1 for ln in lanes if ln["aligned"]),
            "wall_ms": round(max(spans), 3) if spans else None}


def render_timeline(tl: Dict[str, Any]) -> str:
    """Operator text view: one lane per (node, record), events/phases
    in clock-aligned order."""
    lines = [f"trace {tl.get('trace_id')}: {len(tl['lanes'])} lane(s), "
             f"{tl.get('aligned_lanes', 0)} clock-aligned"
             + (f", wall {tl['wall_ms']:.1f} ms"
                if tl.get("wall_ms") is not None else "")]
    for ln in tl["lanes"]:
        flags = []
        if not ln["aligned"]:
            flags.append("UNALIGNED")
        if not ln["done"]:
            flags.append("OPEN (partial — process died or in flight)")
        anomaly = (ln["record"] or {}).get("anomaly")
        if anomaly:
            flags.append(f"anomaly={anomaly}")
        head = (f"[{ln['node']}] {ln['klass']} status={ln['status']} "
                f"tokens={ln['tokens']} replays={ln['replays']}")
        if ln.get("span_ms") is not None:
            head += f" span={ln['span_ms']:.1f}ms"
        if flags:
            head += "  " + " ".join(flags)
        lines.append(head)
        rec = ln["record"] or {}
        if rec.get("queue_wait_ms") is not None:
            lines.append(f"    queue_wait {rec['queue_wait_ms']:.1f} ms "
                         f"(admission attempts "
                         f"{rec.get('admission_attempts', 0)})")
        for it in ln["items"]:
            extra = {k: v for k, v in it.items()
                     if k not in ("t_ms", "kind", "name", "dur_ms")}
            tail = (" ".join(f"{k}={v}" for k, v in extra.items())
                    if extra else "")
            if it["kind"] == "phase":
                lines.append(
                    f"    +{it['t_ms']:>10.1f} ms  {it['name']:<20} "
                    f"{float(it.get('dur_ms') or 0.0):>8.1f} ms  {tail}")
            else:
                lines.append(
                    f"    +{it['t_ms']:>10.1f} ms  {it['name']:<20} "
                    f"{'':>8}     {tail}")
        gaps = rec.get("gap_p99_ms")
        if gaps is not None:
            lines.append(f"    token gaps: p50 {rec.get('gap_p50_ms')} ms "
                         f"p99 {gaps} ms max {rec.get('gap_max_ms')} ms")
    return "\n".join(lines)


def request_trace_events(node: str, doc: Dict[str, Any], pid: int,
                         base_us: Optional[float] = None
                         ) -> "tuple[List[Dict[str, Any]], bool]":
    """One node's request publication as Chrome-trace events on lane
    ``pid`` — the shape ``cluster_trace.json`` and Perfetto load.
    ``base_us`` is the shared store-clock origin in microseconds (the
    PR-13 merged trace's ``store_clock_base_us``); aligned events are
    re-based onto it.  Returns ``(events, aligned)``."""
    clock = doc.get("clock") or {}
    aligned = bool(clock.get("synced")) \
        and isinstance(clock.get("offset_s"), (int, float))
    off_us = float(clock.get("offset_s") or 0.0) * 1e6
    events: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid,
        "args": {"name": f"{node} requests"
                 + ("" if aligned else " (unaligned)")}}]
    recs = [r for r in (doc.get("records") or []) if isinstance(r, dict)]
    lane_min = min((float(r.get("start_ts", 0.0)) for r in recs),
                   default=0.0) * 1e6

    def ts_us(ts: float) -> float:
        t = float(ts) * 1e6
        if aligned:
            return round(t + off_us - (base_us or 0.0), 1)
        return round(t - lane_min, 1)

    for rec in recs:
        tid8 = str(rec.get("trace_id", ""))[:8]
        start = rec.get("start_ts")
        end = rec.get("end_ts")
        if isinstance(start, (int, float)):
            dur = ((float(end) - float(start)) * 1e6
                   if isinstance(end, (int, float)) else 0.0)
            events.append({
                "ph": "X", "cat": "request", "pid": pid, "tid": 0,
                "name": f"request {tid8} ({rec.get('klass')})",
                "ts": ts_us(start), "dur": round(max(dur, 1.0), 1),
                "args": {"trace_id": rec.get("trace_id"),
                         "status": rec.get("status"),
                         "tokens": rec.get("tokens"),
                         "replays": rec.get("replays"),
                         "done": rec.get("done", True)}})
        for ph in rec.get("phases") or []:
            ts = ph.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            events.append({
                "ph": "X", "cat": "request", "pid": pid, "tid": 1,
                "name": f"{ph.get('phase')} [{tid8}]",
                "ts": ts_us(ts),
                "dur": round(max(float(ph.get("dur_ms") or 0.0)
                                 * 1e3, 1.0), 1),
                "args": {k: v for k, v in ph.items()
                         if k not in ("ts", "phase")}})
        for ev in rec.get("events") or []:
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            events.append({
                "ph": "i", "cat": "request", "pid": pid, "tid": 1,
                "s": "t", "name": f"{ev.get('name')} [{tid8}]",
                "ts": ts_us(ts),
                "args": {k: v for k, v in ev.items()
                         if k not in ("ts", "name")}})
    return events, aligned


def timeline_chrome_trace(docs: Dict[str, Dict[str, Any]],
                          trace_id: Optional[str] = None
                          ) -> Dict[str, Any]:
    """A standalone Chrome-trace document of request lanes (one pid per
    node), optionally filtered to one trace id — what ``serving trace
    --out`` writes for Perfetto."""
    filtered: Dict[str, Dict[str, Any]] = {}
    for node, doc in docs.items():
        recs = doc.get("records") or []
        if trace_id is not None:
            tid = str(trace_id)
            recs = [r for r in recs
                    if str(r.get("trace_id", "")) == tid
                    or (len(tid) >= 6
                        and str(r.get("trace_id", "")).startswith(tid))]
        if recs:
            filtered[node] = dict(doc, records=recs)
    base_candidates = []
    for doc in filtered.values():
        clock = doc.get("clock") or {}
        if clock.get("synced") and isinstance(clock.get("offset_s"),
                                              (int, float)):
            for r in doc["records"]:
                if isinstance(r.get("start_ts"), (int, float)):
                    base_candidates.append(
                        (float(r["start_ts"])
                         + float(clock["offset_s"])) * 1e6)
    base_us = min(base_candidates) if base_candidates else 0.0
    events: List[Dict[str, Any]] = []
    hosts: Dict[str, Any] = {}
    for pid, node in enumerate(sorted(filtered)):
        evs, aligned = request_trace_events(node, filtered[node], pid,
                                            base_us=base_us)
        events.extend(evs)
        hosts[node] = {"pid": pid, "aligned": aligned,
                       "records": len(filtered[node]["records"])}
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"source": "deepspeed_tpu.serving.tracing",
                         "trace_id": trace_id,
                         "store_clock_base_us": base_us,
                         "hosts": hosts}}
