"""Process-per-replica serving backend (ISSUE 14 tentpole b).

One :class:`ServingWorker` wraps ONE replica engine (real
``RaggedInferenceEngineV2`` or the host-only synthetic engine) in a
JSON-line TCP server — the same protocol family as the rendezvous store
and the tier-2 replica transport (``resilience/replica_server.py``),
which this is modeled on.  The network front door
(:mod:`.remote`/:mod:`.frontdoor`) drives a fleet of these as real
worker processes: ``kill -9`` one and the router drains it, requeues
its in-flight work onto survivors, and the client stream splices past
the delivered high-water mark.

Roles:

* ``mixed``   — accepts ``submit``/``poll``/``cancel`` (the replica's
  own :class:`~.frontend.ServingFrontend` pumps a single local replica)
  AND KV-page adoption, so a plain fleet needs no role split.
* ``prefill`` — runs ``prefill`` only: prompt in, first token out, KV
  pages parked (``unseat`` — slot freed, pages referenced) until
  ``kv_push`` streams them to a decode peer and ``release`` lets go.
  Completed prefills index the local trie, so a hot shared header is
  computed once per prefill replica, ever.
* ``decode``  — ``adopt_begin``/``kv_page_*``/``adopt_commit`` seat a
  remotely-prefilled request over the transferred pages (trie-shared
  pages skip the wire entirely), then ``poll`` streams its decode.

Protocol (one JSON object per line, ``op``-dispatched; every reply
carries ``ok``):

=================  =====================================================
``ping``           liveness + identity (id, role)
``stats``          load view: outstanding tokens, kv pages, prefix
                   stats, cache geometry (the router's placement inputs)
``match``          prefix-affinity score for a prompt
``submit``         queue a request (validation errors -> ``kind:
                   validation`` so the front door can map them to 4xx)
``poll``           tokens past a cursor + terminal status
``cancel``         abort (any phase — queued, running, prefill-parked,
                   mid-adoption)
``prefill``        run a prompt to its first token, park the KV
``kv_push``        stream parked pages to a decode endpoint (P2P)
``release``        drop a parked prefill's pages (cached-free tier
                   keeps the trie-indexed ones revivable)
``adopt_begin``    reserve pages+slot for a remote prefill (returns the
                   page indices the transfer must fill)
``kv_page_begin/chunk/commit``  chunked upload, sha256-gated PER PAGE
``adopt_commit``   seat the adopted request RUNNING
``adopt_abort``    give the reservation back
=================  =====================================================

Worker processes register in the rendezvous store like the tier-2
replica servers do (``serving/srv/<id>`` — endpoint, role, pid; index
metadata only), heartbeat ``rdzv/hb/<id>``, and ship their telemetry
registry through the PR-13 rollup (``push_node_telemetry``) so the
merged cluster view labels every serving counter per replica process.
"""

from __future__ import annotations

import os
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import log_dist, warn_once
from .frontend import ServingFrontend, ServingParams
from .kv_transfer import (DEFAULT_KV_CHUNK_BYTES, PageStager, inject_pages,
                          page_payload, push_pages)
from .router import Replica

#: store key prefix for worker registration (endpoint/role/pid — the
#: same "store carries metadata only" posture as ``resil/srv``)
SRV_PREFIX = "serving/srv/"


class _WorkerTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _WorkerHandler(socketserver.StreamRequestHandler):
    def handle(self):
        import json

        owner: "ServingWorker" = self.server.worker  # type: ignore
        for raw in self.rfile:
            try:
                req = json.loads(raw)
            except ValueError:
                break
            try:
                out = owner.handle_request(req)
            except Exception as e:  # a bad request must not kill the
                out = {"ok": False, "err": repr(e)}  # serving thread
            self.wfile.write((json.dumps(out) + "\n").encode())
            self.wfile.flush()


class ServingWorker:
    """One replica engine behind a JSON-line socket; see module doc."""

    #: abandoned-reservation expiry: a front door that dies between
    #: ``prefill``/``adopt_begin`` and ``release``/``adopt_commit``
    #: (the exact crash window the chaos tooling exercises) must not
    #: hold this worker's decode slots and KV pages forever — with 4
    #: slots, 4 orphaned adoptions would brick the worker.  Same
    #: failure class as the tier-2 replica server's staged-upload
    #: expiry (PR 11).  (Class attribute: a test seam.)
    _reservation_ttl_s: float = 600.0

    def __init__(self, engine: Any, worker_id: str, role: str = "mixed",
                 host: str = "", port: int = 0,
                 advertise_host: Optional[str] = None,
                 serving_params: Optional[ServingParams] = None,
                 kv_chunk_bytes: int = DEFAULT_KV_CHUNK_BYTES,
                 rpc_timeout_s: float = 30.0,
                 store_endpoint: Optional[str] = None,
                 telemetry_push_every_s: float = 1.0,
                 poll_drip: int = 0):
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"role: unknown worker role {role!r} "
                             f"(one of mixed, prefill, decode)")
        self.engine = engine
        self.id = str(worker_id)
        self.role = role
        self.params = serving_params or ServingParams()
        self.kv_chunk_bytes = int(kv_chunk_bytes)
        self.rpc_timeout_s = float(rpc_timeout_s)
        #: flow control: a poll returns at most this many new tokens
        #: (0 = unbounded).  Chaos tests set it to keep long streams
        #: genuinely in flight while they kill -9 the worker.
        self.poll_drip = int(poll_drip)
        #: rid -> {"handle", "buffer", "done"} (submit + adopted)
        self._handles: Dict[str, Dict[str, Any]] = {}
        #: rid -> {"req", "prompt", "prefill_ms"} (parked prefills)
        self._prefills: Dict[str, Dict[str, Any]] = {}
        #: rid -> {"handle", "need", "stager", "first_token"}
        self._adopts: Dict[str, Dict[str, Any]] = {}
        self._prefills_served = 0
        self._lock = threading.Lock()
        #: serializes the prefill role's direct engine drive (put ->
        #: step* -> unseat must be atomic: a second prefill stepping
        #: the engine could decode an un-parked request past its
        #: budget and release the pages mid-extract)
        self._engine_lock = threading.Lock()
        self.frontend: Optional[ServingFrontend] = None
        if role in ("mixed", "decode"):
            self.frontend = ServingFrontend([Replica(engine, 0)],
                                            params=self.params)
            self.frontend.start()
        self._srv = _WorkerTCPServer((host or "", int(port)),
                                     _WorkerHandler)
        self._srv.worker = self  # type: ignore[attr-defined]
        self.port = int(self._srv.server_address[1])
        self.host = (advertise_host or os.environ.get("DS_ELASTIC_HOST")
                     or "127.0.0.1")
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name=f"ds-serving-worker-{self.id}")
        self._thread.start()
        self._store = None
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if store_endpoint:
            self._register(store_endpoint, telemetry_push_every_s)
        log_dist(f"serving worker {self.id} ({role}) at {self.endpoint}")

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    # -- store registration + telemetry push --------------------------------

    def _register(self, store_endpoint: str, push_every_s: float) -> None:
        from ..elasticity.rendezvous import RendezvousClient

        self._store = RendezvousClient(store_endpoint)
        self._store.set(SRV_PREFIX + self.id,
                        {"endpoint": self.endpoint, "role": self.role,
                         "pid": os.getpid()}, journal=True)
        self._store.hb(f"rdzv/hb/{self.id}")
        # fleet profiler plane (ISSUE 20): the beat loop polls the
        # store's capture-command channel; a duration-mode capture runs
        # right on the beat thread (the profiler traces the whole
        # process, so decode bursts on the serving threads land in the
        # window) and its measured device time folds into the open
        # request lifecycle records
        self._profiler_plane = None
        try:
            from ..telemetry.profiler import configure_profiler_plane

            self._profiler_plane = configure_profiler_plane(
                node_id=self.id)
            self._profiler_plane.add_fold_hook(self._fold_capture)
            self._profiler_plane.register_bundle_context()
        except Exception as e:
            warn_once("serving/worker-profiler",
                      f"profiler plane unavailable ({e!r})")
        self._hb_thread = threading.Thread(
            target=self._beat_loop, args=(push_every_s,), daemon=True,
            name=f"ds-serving-worker-hb-{self.id}")
        self._hb_thread.start()

    def _beat_loop(self, push_every_s: float) -> None:
        """The heartbeat/publish thread: store heartbeat, clock sync
        (what clock-aligns this worker's request-trace lane), registry
        + request-record push, and the live-load gauges ``telemetry top
        --serving`` renders."""
        last_tokens = 0
        last_mono = time.monotonic()
        while not self._hb_stop.wait(push_every_s):
            try:
                self._store.hb(f"rdzv/hb/{self.id}")
                from ..telemetry import (get_telemetry, maybe_sync_clock,
                                         push_node_telemetry)

                maybe_sync_clock(self._store, node_id=self.id)
                tel = get_telemetry()
                if tel.enabled:
                    st = self.stats()
                    tel.set_gauge("serving/worker_active",
                                  float(st.get("active", 0)),
                                  help="requests active on this worker")
                    tel.set_gauge("serving/worker_queued",
                                  float(st.get("queued", 0)),
                                  help="requests queued on this worker")
                    tel.set_gauge(
                        "serving/worker_outstanding_tokens",
                        float(st.get("outstanding_tokens", 0)),
                        help="admitted-but-unfinished token budget")
                    toks = int(st.get("tokens_delivered", 0))
                    now = time.monotonic()
                    dt = max(now - last_mono, 1e-6)
                    tel.set_gauge(
                        "serving/worker_tok_s",
                        max(0.0, (toks - last_tokens) / dt),
                        help="tokens/s delivered over the last "
                             "heartbeat interval")
                    last_tokens, last_mono = toks, now
                push_node_telemetry(self._store, self.id)
                if self._profiler_plane is not None:
                    self._profiler_plane.poll(self._store)
            except Exception as e:  # store down: degraded, retry
                warn_once("serving/worker-hb",
                          f"worker heartbeat degraded ({e!r})")

    def _fold_capture(self, doc: Dict[str, Any]) -> None:
        """Profiler fold hook: a finished capture's measured device time
        lands as a ``profiler_device`` phase on every request that was
        open during the burst — the PR-15 lifecycle record then shows
        the decode burst's DEVICE milliseconds next to its host phases."""
        from .tracing import get_request_log

        census = doc.get("census") or {}
        dev_ms = float(census.get("device_total_us", 0.0)) / 1e3
        for rec in get_request_log().open_records():
            rec.phase("profiler_device", dur_ms=dev_ms,
                      req=int(doc.get("req", 0)),
                      device_kind=str(doc.get("device_kind", "")),
                      window_ms=round(
                          float(doc.get("window_s", 0.0)) * 1e3, 3))

    def shutdown(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        if self.frontend is not None:
            self.frontend.close()
        self._srv.shutdown()
        self._srv.server_close()
        if self._store is not None:
            try:
                self._store.close()
            except Exception as e:
                warn_once("serving/worker-store-close",
                          f"store close failed ({e!r})")

    # -- protocol ------------------------------------------------------------

    def handle_request(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "v": "serving-replica", "id": self.id,
                    "role": self.role}
        if op == "stats":
            return {"ok": True, "v": self.stats()}
        if op == "match":
            return {"ok": True, "v": self._match(list(req["prompt"]))}
        if op == "submit":
            return self._op_submit(req)
        if op == "poll":
            return self._op_poll(req)
        if op == "cancel":
            return self._op_cancel(req)
        if op == "prefill":
            return self._op_prefill(req)
        if op == "kv_push":
            return self._op_kv_push(req)
        if op == "release":
            return self._op_release(req)
        if op == "adopt_begin":
            return self._op_adopt_begin(req)
        if op in ("kv_page_begin", "kv_page_chunk", "kv_page_commit"):
            return self._op_kv_page(op, req)
        if op == "adopt_commit":
            return self._op_adopt_commit(req)
        if op == "adopt_abort":
            return self._op_adopt_abort(req)
        return {"ok": False, "err": f"bad op {op!r}"}

    def stats(self) -> Dict[str, Any]:
        sched = self.engine.scheduler
        cc = self.engine.cache_config
        out: Dict[str, Any] = {
            "id": self.id, "role": self.role,
            "block_size": int(cc.block_size),
            "num_blocks": int(cc.num_blocks),
            "max_seq_len": int(cc.max_seq_len),
            "kv_pages_free": int(sched.allocator.num_free),
        }
        alloc = sched.allocator
        if hasattr(alloc, "num_cached"):
            out["kv_pages_cached"] = int(alloc.num_cached)
        if hasattr(sched, "prefix"):
            out["prefix"] = sched.prefix.stats()
            out["preemptions"] = int(sched.preemptions)
        if self.frontend is not None:
            with self.frontend._lock:
                reps = self.frontend.router.replicas
                out["outstanding_tokens"] = sum(r.outstanding_tokens()
                                                for r in reps)
                out["active"] = sum(len(r.active) for r in reps)
                out["queued"] = sum(
                    len(q) for q in self.frontend._queues.values())
                out["tokens_delivered"] = sum(
                    self.frontend.metrics.tokens.values())
        else:
            with self._lock:
                out["outstanding_tokens"] = sum(
                    len(p["prompt"]) for p in self._prefills.values())
                out["active"] = len(self._prefills)
                out["queued"] = 0
                out["tokens_delivered"] = self._prefills_served
        return out

    def _match(self, prompt: List[int]) -> int:
        if self.frontend is not None:
            return self.frontend.match_tokens(prompt)
        with self._engine_lock:
            sched = self.engine.scheduler
            if hasattr(sched, "match_tokens"):
                return int(sched.match_tokens(prompt))
            return 0

    # -- submit / poll / cancel ---------------------------------------------

    @staticmethod
    def _trace_of(req: Dict[str, Any]) -> "tuple":
        """The propagated trace context of one protocol request:
        ``(trace_id, sampled)`` — ``sampled`` stays None (local
        head-based decision) when the sender didn't carry a verdict."""
        from .tracing import sanitize_trace_id

        trace = sanitize_trace_id(req.get("trace"))
        sampled = req.get("sampled")
        return trace, (bool(sampled) if sampled is not None else None)

    def _op_submit(self, req: Dict[str, Any]) -> Dict[str, Any]:
        if self.frontend is None:
            return {"ok": False, "kind": "role",
                    "err": f"worker {self.id} is prefill-only"}
        rid = str(req["rid"])
        trace, sampled = self._trace_of(req)
        try:
            h = self.frontend.submit(list(req["prompt"]),
                                     int(req.get("max_new_tokens", 64)),
                                     str(req.get("klass", "interactive")),
                                     trace_id=trace, sampled=sampled)
        except ValueError as e:
            return {"ok": False, "kind": "validation", "err": str(e)}
        with self._lock:
            self._handles[rid] = {"handle": h, "buffer": [], "done": False}
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "serving/worker_requests_total",
            help="requests accepted by this replica worker process")
        return {"ok": True}

    def _op_poll(self, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = str(req["rid"])
        cursor = max(0, int(req.get("cursor", 0)))
        with self._lock:
            ent = self._handles.get(rid)
            if ent is None:
                return {"ok": False, "kind": "unknown_rid",
                        "err": f"no request {rid} on worker {self.id}"}
            toks, done = ent["handle"].drain()
            ent["buffer"].extend(toks)
            if done:
                ent["done"] = True
            h = ent["handle"]
            status = h.status if ent["done"] else \
                ("running" if h.status in ("running", "adopting", "done")
                 else h.status)
            new = ent["buffer"][cursor:]
            if self.poll_drip > 0:
                new = new[:self.poll_drip]
            fully_delivered = cursor + len(new) >= len(ent["buffer"])
            out = {"ok": True, "tokens": new, "status": status,
                   "done": ent["done"] and fully_delivered}
            if out["done"]:
                if h.error is not None:
                    out["error"] = str(h.error)
                # the terminal reply is the entry's last use — evict,
                # or a long-lived worker leaks one handle + token
                # buffer per request served.  (If this reply is lost
                # on the wire, the router re-queues and replays — the
                # splice keeps that correct, just not free.)
                self._handles.pop(rid, None)
            return out

    def _op_cancel(self, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = str(req["rid"])
        with self._lock:
            ent = self._handles.pop(rid, None)
            pre = self._prefills.pop(rid, None)
            self._adopts.pop(rid, None)
        if ent is not None and self.frontend is not None:
            self.frontend.cancel(ent["handle"])
        if pre is not None:
            with self._engine_lock:
                self.engine.scheduler.cancel(pre["req"])
            rec = pre.get("rec")
            if rec is not None:
                from .tracing import get_request_log

                rec.finish("cancelled")
                get_request_log().commit(rec)
        return {"ok": True}

    # -- prefill side (disaggregation) ----------------------------------------

    def _expire_reservations(self) -> None:
        """Give back slots+pages whose front door vanished mid-pipeline
        (see ``_reservation_ttl_s``).  Run at the reservation-pressure
        points (``prefill``/``adopt_begin``), like the replica server's
        staged-upload sweep."""
        now = time.time()
        with self._lock:
            stale_pre = [rid for rid, e in self._prefills.items()
                         if now - e["ts"] > self._reservation_ttl_s]
            stale_ad = [rid for rid, e in self._adopts.items()
                        if now - e["ts"] > self._reservation_ttl_s]
            pres = [self._prefills.pop(rid) for rid in stale_pre]
            ads = [self._adopts.pop(rid) for rid in stale_ad]
            for rid in stale_ad:
                self._handles.pop(rid, None)
        for ent in pres:
            with self._engine_lock:
                self.engine.scheduler.cancel(ent["req"])
            rec = ent.get("rec")
            if rec is not None:
                from .tracing import get_request_log

                rec.finish("expired")  # anomalous: always ringed
                get_request_log().commit(rec)
        for ad in ads:
            self.frontend.adopt_abort(ad["handle"])
        if stale_pre or stale_ad:
            warn_once("serving/worker-expire",
                      f"worker {self.id}: expired "
                      f"{len(stale_pre)} parked prefill(s) and "
                      f"{len(stale_ad)} orphaned adoption(s) past "
                      f"{self._reservation_ttl_s:.0f}s")

    def _op_prefill(self, req: Dict[str, Any]) -> Dict[str, Any]:
        if self.frontend is not None:
            # a mixed/decode worker's pump thread owns the engine; a
            # concurrent direct drive here would corrupt the planner
            return {"ok": False, "kind": "role",
                    "err": f"worker {self.id} ({self.role}) does not "
                           f"run disaggregated prefills"}
        self._expire_reservations()
        rid = str(req["rid"])
        prompt = list(req["prompt"])
        trace, sampled = self._trace_of(req)
        from .tracing import get_request_log, mint_trace_id

        rec = get_request_log().start(
            trace or mint_trace_id(), rid,
            str(req.get("klass", "interactive")), len(prompt),
            int(req.get("max_new_tokens", 0)), sampled=sampled)
        t0 = time.perf_counter()
        with self._engine_lock:
            try:
                # budget 2: covers every prompt page + the first
                # sampled token; the decode side holds the REAL budget
                r = self.engine.put(prompt, 2)
            except ValueError as e:
                rec.finish("failed", error=e)
                get_request_log().commit(rec)
                return {"ok": False, "kind": "validation", "err": str(e)}
            guard = 0
            while not r.generated and r.state.value != "done":
                self.engine.step(temperature=self.params.temperature,
                                 eos_token_id=None)
                guard += 1
                if guard > 100_000:
                    self.engine.scheduler.cancel(r)
                    rec.finish("failed",
                               error=RuntimeError("prefill stalled"))
                    get_request_log().commit(rec)
                    return {"ok": False,
                            "err": "prefill made no progress"}
            first = int(r.generated[0])
            # park: slot freed, pages stay referenced for kv_push
            self.engine.scheduler.unseat(r)
        ms = (time.perf_counter() - t0) * 1e3
        rec.phase("prefill", start_ts=t0, worker=self.id)
        rec.event("parked")
        with self._lock:
            self._prefills[rid] = {"req": r, "prompt": prompt,
                                   "prefill_ms": ms, "ts": time.time(),
                                   "rec": rec}
            self._prefills_served += 1
        n_pages = self.engine.scheduler.prompt_pages(len(prompt))
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "serving/worker_prefills_total",
            help="disaggregated prefills run by this worker")
        return {"ok": True, "first_token": first, "n_pages": n_pages,
                "prefill_ms": round(ms, 3)}

    def _op_kv_push(self, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = str(req["rid"])
        to = str(req["to"])
        pages = [int(p) for p in req.get("pages", [])]
        with self._lock:
            ent = self._prefills.get(rid)
        if ent is None:
            return {"ok": False, "kind": "unknown_rid",
                    "err": f"no parked prefill {rid}"}
        t0 = time.perf_counter()
        with self._engine_lock:
            payloads = {i: page_payload(self.engine, ent["prompt"],
                                        ent["req"].blocks, i)
                        for i in pages}
        from .remote import jsonline_rpc
        from .tracing import sanitize_trace_id

        chunk = int(req.get("chunk_bytes", self.kv_chunk_bytes))
        out = push_pages(
            lambda reqs: jsonline_rpc(to, reqs,
                                      timeout=self.rpc_timeout_s),
            rid, payloads, chunk_bytes=chunk,
            trace_id=sanitize_trace_id(req.get("trace")))
        out["transfer_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        out["ok"] = True
        rec = ent.get("rec")
        if rec is not None:
            # one phase per kv_push call = one page batch on the wire
            rec.phase("transfer_push", start_ts=t0, to=to,
                      pages=out.get("pages"), bytes=out.get("bytes"))
        return out

    def _op_release(self, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = str(req["rid"])
        with self._lock:
            ent = self._prefills.pop(rid, None)
        if ent is None:
            return {"ok": False, "kind": "unknown_rid",
                    "err": f"no parked prefill {rid}"}
        with self._engine_lock:
            # releases through refcounts: trie-indexed prompt pages
            # land in the cached-free tier -> the next prefill of the
            # same header revives them instead of recomputing
            self.engine.scheduler.cancel(ent["req"])
        rec = ent.get("rec")
        if rec is not None:
            from .tracing import get_request_log

            rec.event("released")
            rec.finish("done")
            get_request_log().commit(rec)
        return {"ok": True}

    # -- decode side (adoption) ----------------------------------------------

    def _op_adopt_begin(self, req: Dict[str, Any]) -> Dict[str, Any]:
        if self.frontend is None:
            return {"ok": False, "kind": "role",
                    "err": f"worker {self.id} is prefill-only"}
        self._expire_reservations()
        rid = str(req["rid"])
        trace, sampled = self._trace_of(req)
        try:
            h, need = self.frontend.adopt_begin(
                list(req["prompt"]), int(req["max_new_tokens"]),
                str(req.get("klass", "interactive")),
                trace_id=trace, sampled=sampled)
        except ValueError as e:
            return {"ok": False, "kind": "validation", "err": str(e)}
        if h is None:
            return {"ok": False, "kind": "capacity",
                    "err": "no free slot/pages for adoption"}
        with self._lock:
            self._adopts[rid] = {"handle": h, "need": list(need),
                                 "stager": PageStager(),
                                 "first_token": int(req["first_token"]),
                                 "ts": time.time()}
            self._handles[rid] = {"handle": h, "buffer": [], "done": False}
        return {"ok": True, "need": list(need)}

    def _op_kv_page(self, op: str, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = str(req["rid"])
        page = int(req["page"])
        with self._lock:
            ad = self._adopts.get(rid)
            if ad is None:
                return {"ok": False, "kind": "unknown_rid",
                        "err": f"no adoption in progress for {rid}"}
            stager: PageStager = ad["stager"]
            try:
                if op == "kv_page_begin":
                    stager.begin(page, req)
                elif op == "kv_page_chunk":
                    stager.chunk(page, int(req["i"]), str(req["v"]))
                else:
                    nbytes = stager.commit(page)
                    from ..telemetry import get_telemetry

                    tel = get_telemetry()
                    tel.inc_counter(
                        "serving/kv_transfer_received_total",
                        help="KV pages received and checksum-verified")
                    tel.inc_counter(
                        "serving/kv_transfer_received_bytes_total",
                        v=nbytes,
                        help="raw KV bytes received over the transfer")
            except ValueError as e:
                if op == "kv_page_commit":
                    from ..telemetry import get_telemetry

                    get_telemetry().inc_counter(
                        "serving/kv_transfer_rejects_total",
                        help="KV pages rejected at the checksum gate")
                return {"ok": False, "kind": "checksum"
                        if op == "kv_page_commit" else "protocol",
                        "err": str(e)}
        return {"ok": True}

    def _op_adopt_commit(self, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = str(req["rid"])
        with self._lock:
            # pop = an atomic claim (a concurrent duplicate commit for
            # the same rid must see unknown_rid, never double-commit)
            ad = self._adopts.pop(rid, None)
            if ad is None:
                return {"ok": False, "kind": "unknown_rid",
                        "err": f"no adoption in progress for {rid}"}
            missing = [p for p in ad["need"]
                       if p not in ad["stager"].ready]
            if missing:
                self._adopts[rid] = ad  # un-claim: sender may retry
                return {"ok": False, "kind": "incomplete",
                        "err": f"pages {missing} not received/verified"}
        h = ad["handle"]
        skipped = (self.engine.scheduler.prompt_pages(len(h.prompt))
                   - len(ad["need"]))
        if h.record is not None:
            h.record.event(
                "kv_received", pages=len(ad["stager"].ready),
                bytes=sum(len(p.get("raw", b""))
                          for p in ad["stager"].ready.values()),
                skipped_pages=skipped)
        try:
            self.frontend.adopt_commit(
                h, ad["first_token"],
                inject_fn=lambda: inject_pages(self.engine,
                                               h.request.blocks,
                                               ad["stager"].ready))
        except Exception as e:
            # a failed commit (bad payload dtype/shape, dead replica)
            # must give the slot+pages back — the claim above already
            # removed the entry, so the expiry sweep could never see it
            with self._lock:
                self._handles.pop(rid, None)
            self.frontend.adopt_abort(h, error=e)
            return {"ok": False, "kind": "commit", "err": repr(e)}
        if skipped > 0:
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                "serving/kv_transfer_skipped_pages_total", v=skipped,
                help="prompt pages served from the local prefix trie "
                     "instead of the wire (cluster-wide KV tier)")
        return {"ok": True, "skipped_pages": skipped}

    def _op_adopt_abort(self, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = str(req["rid"])
        with self._lock:
            ad = self._adopts.pop(rid, None)
            self._handles.pop(rid, None)
        if ad is not None:
            self.frontend.adopt_abort(ad["handle"])
        return {"ok": True}
