"""ServingScheduler — the v2 ragged planner with prefix sharing,
refcounted pages, and preemptible decode slots.

Same planner surface as :class:`RaggedScheduler` (the engine drives it
through ``plan_step``/``chunk_done``/``decode_burst_done`` unchanged);
the deltas are exactly the serving-plane primitives:

* **Reservation** (`_reserve`): the prompt is matched against the prefix
  trie first; matched whole blocks are *acquired* (refcount++) instead
  of allocated, and the request's ``prefilled`` cursor starts past them
  — prefill recomputes nothing the pool already holds.  The reuse
  boundary is capped (a) strictly before the last prompt token (the
  final token must run so the first sampled token exists) and (b) so
  every remaining chunk start stays on a lattice where the engine's
  page-table ``dynamic_slice`` cannot clamp (see the engine's
  max_seq_len/prefill_chunk guard).
* **Release** (`_release`): refcount decrements; pages reaching zero
  that the trie still indexes enter the allocator's cached tier (LRU
  reclaimed) instead of the free list.
* **Indexing**: a request's full prompt pages are inserted into the trie
  the moment its prefill completes (``chunk_done``) — concurrent
  requests in the same batch can already share them.
* **Preemption** (`preempt`/`resume`): a RUNNING request can be bumped
  out of its decode slot; its pages stay referenced, its host state
  (generated tokens, prefill cursor) is untouched, so ``resume`` is just
  re-seating it in a free slot — decode continues from the same KV.
"""

from __future__ import annotations

from collections import deque
from typing import List, Optional

from ..inference.v2.kv_cache import KVCacheConfig
from ..inference.v2.scheduler import (RaggedScheduler, Request,
                                      RequestState)
from .prefix_cache import PrefixCache, RefcountedBlockAllocator


class ServingScheduler(RaggedScheduler):
    def __init__(self, cache_config: KVCacheConfig,
                 max_batch_slots: int = 8, prefill_chunk: int = 128,
                 prefill_batch: int = 1, prefix_sharing: bool = True,
                 max_cached_blocks: int = 0):
        self._max_cached_blocks = int(max_cached_blocks)
        super().__init__(cache_config, max_batch_slots, prefill_chunk,
                         prefill_batch)
        self.allocator: RefcountedBlockAllocator
        self.prefix = PrefixCache(self.allocator, cache_config.block_size,
                                  enabled=prefix_sharing)
        self.preemptions = 0

    def _make_allocator(self, num_blocks: int) -> RefcountedBlockAllocator:
        return RefcountedBlockAllocator(
            num_blocks, max_cached=self._max_cached_blocks)

    # -- prefix-shared reservation ----------------------------------------

    def _reuse_cap(self, prompt_len: int, matched_tokens: int) -> int:
        """Largest safe reuse boundary (tokens): block-aligned, at most
        ``matched_tokens``, strictly before the last prompt token, and
        placed so every later chunk start ``cap + k*chunk`` keeps
        ``start + chunk <= max_seq_len`` (the engine's dynamic_slice
        would silently clamp past that, retargeting KV writes onto the
        sequence's earlier pages)."""
        bs = self.cache.block_size
        cap = min(matched_tokens, ((prompt_len - 1) // bs) * bs)
        max_seq = self.cache.max_seq_len
        while cap > 0:
            last_start = cap + ((prompt_len - cap - 1) // self.chunk) \
                * self.chunk
            if last_start + self.chunk <= max_seq:
                break
            cap -= bs
        return max(cap, 0)

    def _shared_plan(self, prompt: List[int], max_new_tokens: int
                     ) -> tuple:
        """The ONE trie-match + reuse-cap + capacity accounting, shared
        by ``_reserve``, ``can_admit`` and ``adopt_reserve`` so their
        admission arithmetic can never diverge.  Read-only.  Returns
        ``(shared_blocks, fresh_needed, reused_tokens, available)`` —
        ``available`` already excludes the cached pages this very
        request would revive (fresh allocations may reclaim cached
        pages, but not the ones being re-acquired)."""
        bs = self.cache.block_size
        matched = self.prefix.match(prompt)
        reused = self._reuse_cap(len(prompt), len(matched) * bs)
        shared = matched[:reused // bs]
        need = -(-(len(prompt) + max_new_tokens) // bs)
        fresh = need - len(shared)
        cached_shared = sum(1 for b in shared
                            if self.allocator.is_cached(b))
        avail = (self.allocator.num_free
                 + self.allocator.num_cached - cached_shared)
        return shared, fresh, reused, avail

    def _reserve(self, req: Request) -> bool:
        shared, fresh, reused, avail = self._shared_plan(
            req.prompt, req.max_new_tokens)
        if fresh > avail:
            return False
        # the reservation is committing — only now is the mid-block
        # divergence a real CoW.  A page-blocked head retries _reserve
        # every plan_step; counting before the capacity check inflated
        # cow_events once per pump round.
        self.prefix.count_mid_block_divergence(req.prompt)
        self.prefix.acquire(shared)
        req.blocks = shared + self.allocator.allocate(fresh)
        req.prefilled = reused
        self.prefix.record_lookup(len(req.prompt), reused)
        return True

    def can_admit(self, prompt: List[int], max_new_tokens: int,
                  reserve_pages: int = 0,
                  ignore_slots: bool = False) -> bool:
        """Advisory capacity check for front-end admission control:
        would ``_reserve`` + a free slot succeed right now, leaving at
        least ``reserve_pages`` available afterwards?  Read-only.
        ``ignore_slots`` answers the pages-only question — the
        front-end uses it to tell slot-blocked (preemption helps) from
        page-blocked (it cannot: preempted KV stays resident)."""
        if not ignore_slots and self._free_slot() < 0:
            return False
        _, fresh, _, avail = self._shared_plan(prompt, max_new_tokens)
        return fresh + max(reserve_pages, 0) <= avail

    def match_tokens(self, prompt: List[int]) -> int:
        """Prefix-affinity signal for the router: how many tokens of
        this prompt the local trie already holds (post-cap)."""
        matched = self.prefix.match(prompt)
        return self._reuse_cap(len(prompt), len(matched)
                               * self.cache.block_size)

    # -- release through refcounts ----------------------------------------

    def _release(self, req: Request) -> None:
        self.allocator.release(req.blocks, cache_fn=self.prefix.is_indexed)

    def admit_now(self, req: Request) -> bool:
        """Synchronously seat a just-added request, bypassing the FIFO
        ``waiting`` deque.  The front-end checks capacity (`can_admit`),
        preempts if needed, then calls this — deferring to the next
        ``plan_step``'s FIFO `_admit` would let a lower-class resume
        steal the very slot the preemption freed."""
        if req not in self.waiting:
            raise ValueError(f"admit_now: uid {req.uid} is not waiting")
        slot = self._free_slot()
        if slot < 0 or not self._reserve(req):
            return False  # stays in waiting; _admit will retry in order
        self.waiting.remove(req)
        req.state = RequestState.PREFILL
        req.slot = slot
        self.slots[slot] = req
        self.prefilling.append(req)
        return True

    # -- class-aware SplitFuse interleave ----------------------------------

    def plan_step(self) -> tuple:
        """Prefill chunks are planned in priority order (stable within a
        class): an interactive prompt admitted behind N background
        prefills jumps the chunk lattice, which is what bounds its TTFT
        by a chunk, not by the whole background backlog."""
        if len(self.prefilling) > 1:
            self.prefilling = deque(
                sorted(self.prefilling, key=lambda r: r.priority))
        return super().plan_step()

    # -- trie indexing at prefill completion -------------------------------

    def chunk_done(self, chunk, first_token, eos_token_id=None) -> None:
        req = chunk.request
        super().chunk_done(chunk, first_token, eos_token_id)
        if chunk.is_last:
            # the full prompt's KV is now in the pool (the device call
            # returned before chunk_done runs) — index every full prompt
            # page; already-indexed chunks keep their shared page.  A
            # request finishing inside this very call (max_new=1/EOS)
            # has released its pages already — skip, nothing to index.
            if req.state is not RequestState.DONE:
                self.prefix.insert(req.prompt, req.blocks)

    # -- preemptible decode slots ------------------------------------------

    def unseat(self, req: Request) -> None:
        """:meth:`preempt` minus the SLO counters — the disaggregation
        plane's "hold the pages, free the slot" primitive: a prefill
        replica parks a just-prefilled request here while its KV pages
        stream out to a decode replica, then :meth:`cancel`\\ s it."""
        if req.state is RequestState.PREFILL:
            self.prefilling.remove(req)
        elif req.state is not RequestState.RUNNING:
            raise ValueError(
                f"can only preempt RUNNING/PREFILL requests, uid "
                f"{req.uid} is {req.state.value}")
        self.slots[req.slot] = None
        req.slot = -1
        req.state = RequestState.WAITING

    def preempt(self, req: Request) -> None:
        """Bump a RUNNING or PREFILL request out of its slot.  Pages
        stay referenced (all KV written so far is intact), generated
        tokens and the prefill cursor stay accepted; the caller
        re-queues the request and later calls :meth:`resume`, which
        continues decode — or the chunk lattice — exactly where it
        stopped."""
        self.unseat(req)
        self.preemptions += 1
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "serving/preemptions",
            help="decode slots preempted for a higher latency class")

    def preempt_release(self, req: Request) -> int:
        """HBM-pressure preemption (ROADMAP 3e): bump the request AND
        release its KV pages back through the refcounts — trie-indexed
        prompt pages land in the cached-free LRU tier (immediately
        reclaimable, revivable), everything else returns to the free
        list.  The request object is RETIRED (state DONE): the caller
        re-queues its *handle* for a fresh admission, whose ``_reserve``
        re-matches the prefix trie and recomputes only what the cached
        tier no longer holds.  Returns the number of pages released."""
        if req.state is RequestState.PREFILL:
            self.prefilling.remove(req)
        elif req.state is not RequestState.RUNNING:
            raise ValueError(
                f"can only preempt RUNNING/PREFILL requests, uid "
                f"{req.uid} is {req.state.value}")
        released = len(req.blocks)
        self._release(req)
        req.blocks = []
        if req.slot >= 0:
            self.slots[req.slot] = None
            req.slot = -1
        req.state = RequestState.DONE
        self.preemptions += 1
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        tel.inc_counter(
            "serving/preemptions",
            help="decode slots preempted for a higher latency class")
        tel.inc_counter(
            "serving/preempt_pages_released_total", v=released,
            help="KV pages released by HBM-pressure preemptions "
                 "(cached-free tier keeps trie-indexed prompt pages "
                 "revivable)")
        return released

    def resume(self, req: Request) -> bool:
        """Re-seat a preempted request in a free slot; decode (or the
        remaining prefill chunks) continue from the retained KV.  False
        if no slot is free."""
        if req.state is not RequestState.WAITING or not req.blocks:
            raise ValueError(
                f"resume expects a preempted request (WAITING with pages "
                f"reserved), uid {req.uid} is {req.state.value}")
        slot = self._free_slot()
        if slot < 0:
            return False
        req.slot = slot
        self.slots[slot] = req
        if req.prefilled < len(req.prompt):
            req.state = RequestState.PREFILL
            self.prefilling.append(req)
        else:
            req.state = RequestState.RUNNING
        return True

    # -- disaggregated prefill/decode adoption -----------------------------

    def prompt_pages(self, prompt_len: int) -> int:
        """Pages holding prompt KV (positions ``0..prompt_len-1``) —
        the page set a disaggregated transfer must cover.  The final
        page may be partial: decode's first write lands in it too, so
        it ships whole."""
        return -(-prompt_len // self.cache.block_size)

    def adopt_reserve(self, prompt: List[int], max_new_tokens: int
                      ) -> Optional[tuple]:
        """Decode-side phase 1 of KV-page adoption: reserve pages + a
        decode slot for a request whose prefill ran ELSEWHERE.  The
        prompt is matched against the local prefix trie first — shared
        pages already hold the right KV and are NOT re-transferred,
        which is what makes the paged prefix cache a cluster-wide tier.
        Returns ``(request, need)`` where ``need`` lists the
        prompt-page indices the transfer must fill, or ``None`` when no
        slot/pages are available (the caller re-queues).  The request
        parks WAITING in its slot (inert to the planner) until
        :meth:`adopt_commit` seats it RUNNING."""
        self.validate(prompt, max_new_tokens)
        slot = self._free_slot()
        if slot < 0:
            return None
        shared, fresh, reused, avail = self._shared_plan(prompt,
                                                         max_new_tokens)
        if fresh > avail:
            return None
        self.prefix.acquire(shared)
        req = Request(uid=self._uid, prompt=list(prompt),
                      max_new_tokens=int(max_new_tokens))
        self._uid += 1
        req.blocks = shared + self.allocator.allocate(fresh)
        req.prefilled = len(prompt)
        req.slot = slot
        self.slots[slot] = req
        self.prefix.record_lookup(len(prompt), reused)
        need = list(range(len(shared), self.prompt_pages(len(prompt))))
        return req, need

    def adopt_commit(self, req: Request, first_token: int,
                     eos_token_id: Optional[int] = None) -> None:
        """Phase 2: the transferred pages are in the pool — seat the
        request RUNNING with the prefill replica's sampled first token
        and index its prompt pages into the local trie (the next
        same-prefix adoption transfers nothing)."""
        if req.state is not RequestState.WAITING or req.slot < 0:
            raise ValueError(
                f"adopt_commit expects a reserved adoption (WAITING in "
                f"a slot), uid {req.uid} is {req.state.value}")
        req.state = RequestState.RUNNING
        req.generated.append(int(first_token))
        self._maybe_finish(req, int(first_token), eos_token_id)
        if req.state is not RequestState.DONE:
            self.prefix.insert(req.prompt, req.blocks)

    def adopt_abort(self, req: Request) -> None:
        """Transfer failed: give the reservation back (pages through
        refcounts, slot freed) — the caller re-routes the request."""
        if req.blocks:
            self._release(req)
            req.blocks = []
        if req.slot >= 0:
            self.slots[req.slot] = None
            req.slot = -1
        req.state = RequestState.DONE

    # -- introspection -----------------------------------------------------

    def telemetry_gauges(self) -> dict:
        # extends the base occupancy gauges, so the pool/prefix numbers
        # publish through the existing plan_step path automatically
        g = super().telemetry_gauges()
        g["serving/kv_pages_cached"] = float(self.allocator.num_cached)
        g["serving/kv_pages_free"] = float(self.allocator.num_free)
        g["serving/prefix_hit_rate"] = self.prefix.hit_rate
        return g
