"""Serving CLI — bench / serve / worker / trace.

* ``python -m deepspeed_tpu.serving bench [--dry-run] [--network]`` —
  the deterministic multi-tenant workload.  ``--dry-run`` drives
  synthetic replicas on a fake clock (CI smoke); real mode compiles a
  tiny model; ``--network`` spawns a real front door + 2 replica worker
  PROCESSES and drives sustained mixed-class QPS over actual HTTP/SSE,
  emitting the gated ``serving_net_*`` metrics.
* ``python -m deepspeed_tpu.serving serve`` — run the HTTP/SSE front
  door.  ``--dry-run`` boots synthetic in-process replicas, answers its
  own health probe, and shuts down cleanly (the run_suite smoke);
  ``--workers N`` launches a worker-process fleet behind it;
  ``--store`` discovers externally-launched workers from the
  rendezvous store.
* ``python -m deepspeed_tpu.serving worker`` — run ONE replica worker
  process (the launcher and chaos tests spawn these; ``kill -9`` one
  and the front door's router drains it).
* ``python -m deepspeed_tpu.serving trace <request-id>`` — assemble ONE
  request's cross-process timeline from every node's request-record
  publication in the rendezvous store (ISSUE 15): front door, router,
  prefill/decode workers, each a clock-aligned lane showing queue wait,
  admission, preempt/replay, transfer batches, and token timing.  Exit
  0 with the timeline, 3 when the id is unknown; ``--out`` writes the
  lanes as a Chrome-trace JSON for Perfetto.

The emitted bench JSON lines carry the gated serving metrics
(``serving_p99_ttft_ms``, ``prefix_hit_rate``, ``serving_net_*``) in
the exact shape ``telemetry perf check`` reads.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional

import numpy as np


def run_workload(frontend: Any, clock, n_interactive: int = 12,
                 n_background: int = 6, header_len: int = 128,
                 interactive_new: int = 16, background_new: int = 96,
                 warm_rounds: int = 4, seed: int = 0) -> Dict[str, Any]:
    """Drive a shared-header, mixed-class workload to completion and
    report the serving metrics.  Background requests saturate the decode
    slots first; interactive requests then arrive one at a time and are
    each driven to completion (so their TTFT reflects contention, not
    batching of the probe stream itself)."""
    from .metrics import ServingMetrics

    rng = np.random.RandomState(seed)
    header = rng.randint(2, 29000, size=header_len).tolist()

    def prompt(tail: int) -> list:
        return header + rng.randint(2, 29000, size=tail).tolist()

    def hit_counts():
        hits = looks = 0
        for r in frontend.router.replicas:
            p = getattr(r.scheduler, "prefix", None)
            if p is not None:
                hits += p.hit_tokens
                looks += p.lookup_tokens
        return hits, looks

    # this workload's own window: fresh latency trackers, and the prefix
    # hit rate as a delta (a warm-up pass must not pollute the p99 tail
    # with compile time, nor dilute the hit rate)
    frontend.metrics = ServingMetrics()
    hits0, looks0 = hit_counts()
    t0 = clock()
    background = [frontend.submit(prompt(16), max_new_tokens=background_new,
                                  klass="background")
                  for _ in range(n_background)]
    for _ in range(warm_rounds):
        frontend.pump()
    interactive = []
    for _ in range(n_interactive):
        h = frontend.submit(prompt(8), max_new_tokens=interactive_new,
                            klass="interactive")
        interactive.append(h)
        for _ in range(100_000):
            frontend.pump()
            if h.status != "running" and h.status != "queued":
                break
        else:
            raise RuntimeError("interactive request never completed")
    frontend.run_until_idle()
    elapsed = max(clock() - t0, 1e-9)

    m = frontend.metrics
    done = [h for h in interactive + background if h.status == "done"]
    out = {
        "serving_p99_ttft_ms": round(m.ttft["interactive"].percentile(99),
                                     3),
        "serving_p50_ttft_ms": round(m.ttft["interactive"].percentile(50),
                                     3),
        "background_p99_ttft_ms": round(
            m.ttft["background"].percentile(99), 3),
        "prefix_hit_rate": round(
            (hit_counts()[0] - hits0)
            / max(hit_counts()[1] - looks0, 1), 4),
        "tok_s_interactive": round(m.tokens["interactive"] / elapsed, 1),
        "tok_s_background": round(m.tokens["background"] / elapsed, 1),
        "preemptions": m.counters["preemptions"],
        "requests_completed": len(done),
        "requests_submitted": m.counters["submitted"],
        "elapsed_s": round(elapsed, 4),
    }
    return out


def _dry_run_frontend(replicas: int, slots: int = 4):
    from . import (FakeClock, Replica, ServingFrontend, ServingParams,
                   SyntheticEngine)
    from ..inference.v2 import KVCacheConfig

    clock = FakeClock()
    cache = KVCacheConfig(num_blocks=256, block_size=16, max_seq_len=512)
    reps = [Replica(SyntheticEngine(cache, max_batch_slots=slots,
                                    prefill_chunk=64, prefill_batch=2,
                                    decode_burst=4, clock=clock), i)
            for i in range(replicas)]
    fe = ServingFrontend(reps, params=ServingParams(
        interactive_reserve_frac=0.1), clock=clock)
    return fe, clock


def _real_frontend(replicas: int):
    import time

    import jax.numpy as jnp

    from . import ServingParams, build_serving_frontend
    from ..inference.v2 import KVCacheConfig
    from ..models import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(num_layers=2, max_seq_len=256,
                           dtype=jnp.float32)
    fe = build_serving_frontend(
        LlamaModel(cfg), replicas=replicas,
        cache_config=KVCacheConfig(num_blocks=128, block_size=16,
                                   max_seq_len=256),
        max_batch_slots=4, prefill_chunk=32, prefill_batch=2,
        decode_burst=4,
        serving_params=ServingParams(interactive_reserve_frac=0.1))
    return fe, time.monotonic


def sse_events(resp) -> "Any":
    """Parse a ``text/event-stream`` HTTP response into ``(event,
    data_dict)`` pairs; comment heartbeats are skipped.  Yields until
    the close-delimited body ends."""
    event, data = None, []
    while True:
        line = resp.readline()
        if not line:
            return
        line = line.decode().rstrip("\n").rstrip("\r")
        if not line:
            if event is not None:
                yield event, json.loads("".join(data) or "{}")
            event, data = None, []
            continue
        if line.startswith(":"):
            continue  # heartbeat comment
        if line.startswith("event:"):
            event = line[len("event:"):].strip()
        elif line.startswith("data:"):
            data.append(line[len("data:"):].strip())


def http_generate_stream(host: str, port: int, prompt: list,
                         max_new_tokens: int, klass: str,
                         timeout: float = 60.0,
                         trace: Optional[str] = None) -> Dict[str, Any]:
    """One streamed request through the front door; returns the tokens,
    client-measured TTFT, and the server's ``done`` summary.  ``trace``
    rides the ``X-DS-Trace`` header (ISSUE 15)."""
    import http.client
    import time as _time

    headers = {"Content-Type": "application/json", "X-DS-Class": klass}
    if trace:
        headers["X-DS-Trace"] = str(trace)
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        t0 = _time.monotonic()
        conn.request(
            "POST", "/v1/generate",
            body=json.dumps({"prompt": prompt,
                             "max_new_tokens": max_new_tokens,
                             "stream": True}),
            headers=headers)
        resp = conn.getresponse()
        if resp.status != 200:
            return {"status_code": resp.status,
                    "error": resp.read().decode()[:200], "tokens": []}
        tokens, ttft_ms, done = [], None, {}
        for event, data in sse_events(resp):
            if event == "token":
                if ttft_ms is None:
                    ttft_ms = (_time.monotonic() - t0) * 1e3
                tokens.append(int(data["token"]))
            elif event in ("done", "error"):
                done = data
                break
        return {"status_code": 200, "tokens": tokens,
                "ttft_ms": ttft_ms, "done": done}
    finally:
        conn.close()


def run_network_workload(host: str, port: int, duration_s: float = 3.0,
                         tenants: int = 4, concurrency: int = 6,
                         header_len: int = 96, interactive_new: int = 12,
                         background_new: int = 48,
                         seed: int = 0) -> Dict[str, Any]:
    """Sustained mixed-class QPS against a live front door: ``tenants``
    shared prompt headers (cross-request prefix hits), ``concurrency``
    client threads submitting back-to-back over real HTTP/SSE for
    ``duration_s``.  Returns the gated ``serving_net_*`` metrics."""
    import http.client
    import threading
    import time as _time

    rng = np.random.RandomState(seed)
    headers = [rng.randint(2, 29000, size=header_len).tolist()
               for _ in range(tenants)]
    results: list = []
    errors: list = []
    lock = threading.Lock()
    stop = _time.monotonic() + duration_s

    def client(idx: int) -> None:
        r = np.random.RandomState(seed + 1000 + idx)
        i = 0
        while _time.monotonic() < stop:
            klass = "interactive" if (i % 3) else "background"
            new = interactive_new if klass == "interactive" \
                else background_new
            prompt = (headers[(idx + i) % tenants]
                      + r.randint(2, 29000, size=4).tolist())
            try:
                out = http_generate_stream(host, port, prompt, new, klass)
            except OSError as e:
                with lock:
                    errors.append(repr(e))
                break
            with lock:
                if out["status_code"] == 200 and out["tokens"]:
                    results.append((klass, out["ttft_ms"],
                                    len(out["tokens"])))
                elif out["status_code"] != 429:
                    errors.append(str(out.get("error"))[:120])
            i += 1

    t0 = _time.monotonic()
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 60.0)
    elapsed = max(_time.monotonic() - t0, 1e-9)

    inter = sorted(ms for k, ms, _ in results
                   if k == "interactive" and ms is not None)

    def pct(p: float) -> float:
        if not inter:
            return 0.0
        return inter[min(len(inter) - 1,
                         int(round(p / 100.0 * (len(inter) - 1))))]

    hit_rate = 0.0
    try:
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("GET", "/v1/metrics")
        m = json.loads(conn.getresponse().read())
        hit_rate = float(m.get("prefix_hit_rate", 0.0))
        conn.close()
    except (OSError, ValueError):
        pass
    return {
        "serving_net_p99_ttft_ms": round(pct(99), 3),
        "serving_net_p50_ttft_ms": round(pct(50), 3),
        "serving_net_qps_sustained": round(len(results) / elapsed, 2),
        "serving_net_prefix_hit_rate": round(hit_rate, 4),
        "requests_completed": len(results),
        "tokens_streamed": sum(n for _, _, n in results),
        "elapsed_s": round(elapsed, 3),
        "errors": errors[:5],
    }


def _network_bench(args: argparse.Namespace) -> int:
    """bench --network: a real front door + 2 worker processes."""
    from ..launcher.serving_fleet import (launch_worker_fleet,
                                          shutdown_fleet)
    from . import (FrontDoor, FrontDoorParams, NetworkFrontend,
                   NetworkParams, ReplicaEndpoint)

    fleet = launch_worker_fleet(args.replicas)
    door = None
    try:
        eps = [ReplicaEndpoint(w.id, w.endpoint, role=w.role)
               for w in fleet]
        fe = NetworkFrontend(eps, net=NetworkParams())
        door = FrontDoor(fe, params=FrontDoorParams())
        door.start()
        out = run_network_workload(door.host, door.port,
                                   duration_s=args.duration,
                                   seed=args.seed)
        out["replicas"] = len(fleet)
        out["network"] = True
        print(json.dumps(out))
        return 0 if out["requests_completed"] > 0 else 3
    finally:
        if door is not None:
            door.shutdown()
        shutdown_fleet(fleet)


def _replay_bench(args: argparse.Namespace) -> int:
    """bench --replay: re-issue a recorded access log.  Against
    ``--endpoint`` when given (a door someone else runs — the README
    walkthrough), else against an ephemeral fleet + door (CI smoke)."""
    from .replay import (read_access_log, replay_report,
                         replayable_records, run_replay)

    import threading

    records = replayable_records(read_access_log(args.replay))
    if not records:
        print(json.dumps({"ok": False,
                          "error": f"no replayable records in "
                                   f"{args.replay}"}))
        return 3
    fleet, door = [], None
    tick_stop = threading.Event()
    ticker = None
    try:
        if args.endpoint:
            host, _, port = args.endpoint.rpartition(":")
            host, port = host or "127.0.0.1", int(port)
        else:
            from ..launcher.serving_fleet import launch_worker_fleet
            from ..runtime.config import ServingSLOConfig
            from . import (FrontDoor, FrontDoorParams, NetworkFrontend,
                           NetworkParams, ReplicaEndpoint)

            from ..telemetry import get_telemetry

            # the burn-rate figure reads this process's registry (the
            # pump publishes per-class TTFT gauges into it)
            get_telemetry().configure(enabled=True, jsonl=False,
                                      prometheus=False)
            fleet = launch_worker_fleet(args.replicas)
            eps = [ReplicaEndpoint(w.id, w.endpoint, role=w.role)
                   for w in fleet]
            fe = NetworkFrontend(eps, net=NetworkParams())
            door = FrontDoor(fe, params=FrontDoorParams(),
                             slo_cfg=ServingSLOConfig())
            door.start()
            host, port = door.host, door.port
            # no store -> no publisher beat; tick the SLO monitor
            # ourselves so the replay report carries the sentinel
            # burn-rate figure

            def _tick() -> None:
                while not tick_stop.wait(0.25):
                    door.slo_tick()

            ticker = threading.Thread(target=_tick, daemon=True,
                                      name="ds-replay-slo-tick")
            ticker.start()
        out = run_replay(host, port, records, speed=args.speed,
                         max_requests=args.max_requests)
        report = replay_report(out, speed=args.speed)
        report["source"] = args.replay
        if fleet:
            report["replicas"] = len(fleet)
        if door is not None and door.slo is not None:
            door.slo_tick(force=True)
            lat = [st["burn_slow"]
                   for st in door.slo.snapshot()["objectives"]
                   if st["kind"] == "latency"
                   and st["burn_slow"] is not None]
            if lat:
                report["serving_slo_burn_rate_p99"] = round(max(lat), 4)
        print(json.dumps(report))
        return 0 if report["replayed"] > 0 \
            and not report["aborted"] else 3
    finally:
        tick_stop.set()
        if ticker is not None:
            ticker.join(timeout=5.0)
        if door is not None:
            door.shutdown()
        if fleet:
            from ..launcher.serving_fleet import shutdown_fleet

            shutdown_fleet(fleet)


def bench_command(args: argparse.Namespace) -> int:
    if getattr(args, "replay", None):
        return _replay_bench(args)
    if getattr(args, "network", False):
        return _network_bench(args)
    if args.dry_run:
        fe, clock = _dry_run_frontend(args.replicas)
        header_len, inter_new, bg_new = 128, 16, 96
    else:
        fe, clock = _real_frontend(args.replicas)
        # sized for a tiny model within its 256-token max_seq_len
        header_len, inter_new, bg_new = 64, 8, 24
    out = run_workload(fe, clock, n_interactive=args.interactive,
                       n_background=args.background,
                       header_len=header_len, interactive_new=inter_new,
                       background_new=bg_new, seed=args.seed)
    out["dry_run"] = bool(args.dry_run)
    out["replicas"] = args.replicas
    print(json.dumps(out))
    return 0


def _build_worker_engine(args: argparse.Namespace):
    from ..inference.v2 import KVCacheConfig

    cache = KVCacheConfig(num_blocks=args.blocks,
                          block_size=args.block_size,
                          max_seq_len=args.max_seq_len)
    if args.engine == "synthetic":
        from . import SyntheticEngine

        return SyntheticEngine(cache, max_batch_slots=args.slots,
                               prefill_chunk=args.block_size * 4,
                               prefill_batch=2, decode_burst=4,
                               step_delay_s=args.step_delay_ms / 1e3)
    # tiny real model on whatever backend JAX has (CPU works)
    import jax.numpy as jnp

    from ..inference.v2 import build_engine_v2
    from ..models import LlamaConfig, LlamaModel
    from .scheduler import ServingScheduler

    cfg = LlamaConfig.tiny(num_layers=2,
                           max_seq_len=args.max_seq_len,
                           dtype=jnp.float32)
    return build_engine_v2(
        LlamaModel(cfg), cache_config=cache,
        max_batch_slots=args.slots,
        prefill_chunk=args.block_size * 2, prefill_batch=2,
        decode_burst=4, scheduler_factory=ServingScheduler)


def worker_command(args: argparse.Namespace) -> int:
    import signal
    import threading

    from ..telemetry import get_telemetry
    from . import ServingWorker

    # the worker ships its registry through the PR-13 rollup — the
    # merged cluster view labels serving counters per replica process
    get_telemetry().configure(enabled=True, jsonl=False,
                              prometheus=False)
    if args.trace_sample_rate is not None \
            or args.trace_ring is not None \
            or args.trace_anomaly_ttft_ms is not None:
        from .tracing import configure_request_log

        configure_request_log(sample_rate=args.trace_sample_rate,
                              maxlen=args.trace_ring,
                              anomaly_ttft_ms=args.trace_anomaly_ttft_ms)
    engine = _build_worker_engine(args)
    w = ServingWorker(engine, args.id, role=args.role, port=args.port,
                      store_endpoint=args.store,
                      kv_chunk_bytes=args.kv_chunk_bytes,
                      poll_drip=args.drip,
                      telemetry_push_every_s=args.push_every)
    # one parseable readiness line, flushed — launchers wait on it
    print(f"DS_SERVING_WORKER id={w.id} role={w.role} "
          f"endpoint={w.endpoint}", flush=True)
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    w.shutdown()
    return 0


def _load_network_config(spec: Optional[str]):
    """``--ds-config``: a DeepSpeed config path or inline JSON whose
    ``serving.network`` group seeds the serve defaults (explicit CLI
    flags win).  The ``serving.tracing`` group, when present, is
    applied to the process request log as a side input; the
    ``serving.slo`` and ``serving.autoscaler`` groups ride back on the
    returned network config (``_slo_cfg`` / ``_autoscaler_cfg``
    attributes) for the door/policy-loop construction sites."""
    if not spec:
        return None
    import os

    from ..runtime.config import (ServingAutoscalerConfig,
                                  ServingNetworkConfig, ServingSLOConfig,
                                  ServingTracingConfig)

    if os.path.exists(spec):
        with open(spec) as fh:
            doc = json.load(fh)
    else:
        doc = json.loads(spec)
    tgroup = (doc.get("serving") or {}).get("tracing")
    if isinstance(tgroup, dict):
        from .tracing import configure_tracing_from_config

        configure_tracing_from_config(ServingTracingConfig(**tgroup))
    group = (doc.get("serving") or {}).get("network") or {}
    ncfg = ServingNetworkConfig(**group)
    sgroup = (doc.get("serving") or {}).get("slo")
    object.__setattr__(ncfg, "_slo_cfg",
                       ServingSLOConfig(**sgroup)
                       if isinstance(sgroup, dict) else None)
    agroup = (doc.get("serving") or {}).get("autoscaler")
    object.__setattr__(ncfg, "_autoscaler_cfg",
                       ServingAutoscalerConfig(**agroup)
                       if isinstance(agroup, dict) else None)
    return ncfg


def serve_command(args: argparse.Namespace) -> int:
    import http.client
    import signal
    import threading

    from . import (FrontDoor, FrontDoorParams, NetworkFrontend,
                   NetworkParams, ReplicaEndpoint, discover_endpoints,
                   door_params_from_config, net_params_from_config)

    ncfg = _load_network_config(args.ds_config)
    door_params = (door_params_from_config(ncfg) if ncfg is not None
                   else FrontDoorParams())
    if args.queue_token_budget is not None:
        door_params.queue_token_budget = args.queue_token_budget
    if args.retry_after is not None:
        door_params.retry_after_s = args.retry_after
    if args.access_log is not None:
        door_params.access_log = args.access_log
    net = net_params_from_config(ncfg) if ncfg is not None \
        else NetworkParams()
    if args.disaggregate:
        net.disaggregate = True
    if args.kv_chunk_bytes is not None:
        net.kv_chunk_bytes = args.kv_chunk_bytes
    host = args.host if args.host is not None else \
        (ncfg.host if ncfg is not None else "127.0.0.1")
    port = args.port if args.port is not None else \
        (ncfg.port if ncfg is not None else 0)
    store = args.store if args.store is not None else \
        (ncfg.store_endpoint if ncfg is not None else None)
    workers = args.workers if args.workers is not None else \
        (ncfg.workers if ncfg is not None and ncfg.enabled else 0)
    prefill_workers = args.prefill_workers \
        if args.prefill_workers is not None \
        else (ncfg.prefill_workers if ncfg is not None else 1)

    fleet = []
    if args.dry_run:
        fe, _ = _dry_run_frontend(args.replicas)
        # a fake-clock front-end never advances wall TTFT — fine for
        # the boot/probe/shutdown smoke this mode exists for
    elif workers > 0 or store:
        from ..launcher.serving_fleet import launch_worker_fleet

        eps = []
        if workers > 0:
            prefill = prefill_workers if net.disaggregate else 0
            # the serving.tracing config applied to THIS process must
            # reach the workers it spawns, or their trace lanes run
            # with default sampling/retention silently
            from .tracing import get_request_log

            rlog = get_request_log()
            trace_args = [
                "--trace-sample-rate", str(rlog.sample_rate),
                "--trace-ring", str(rlog.maxlen),
                "--trace-anomaly-ttft-ms", str(rlog.anomaly_ttft_ms)]
            fleet = launch_worker_fleet(workers, prefill=prefill,
                                        store=store,
                                        engine=args.engine,
                                        extra_args=trace_args)
            eps = [ReplicaEndpoint(w.id, w.endpoint, role=w.role)
                   for w in fleet]
        elif store:
            from ..elasticity.rendezvous import RendezvousClient

            eps = discover_endpoints(RendezvousClient(store))
        if not eps:
            print(json.dumps({"ok": False,
                              "error": "no worker endpoints found"}))
            return 2
        fe = NetworkFrontend(eps, net=net)
    else:
        fe, _ = _real_frontend(args.replicas)
    if store:
        # the door is a trace lane too: publish its registry + request
        # records over the rollup so `serving trace` sees the edge
        from ..telemetry import get_telemetry

        get_telemetry().configure(enabled=True, jsonl=False,
                                  prometheus=False)
    slo_cfg = getattr(ncfg, "_slo_cfg", None) if ncfg is not None \
        else None
    if slo_cfg is None and getattr(args, "slo", False):
        from ..runtime.config import ServingSLOConfig

        slo_cfg = ServingSLOConfig()
    door = FrontDoor(fe, host=host, port=port, params=door_params,
                     store_endpoint=store, slo_cfg=slo_cfg)
    door.start()
    autoscaler = None
    as_cfg = getattr(ncfg, "_autoscaler_cfg", None) if ncfg is not None \
        else None
    if getattr(args, "autoscale", False) and as_cfg is None:
        from ..runtime.config import ServingAutoscalerConfig

        as_cfg = ServingAutoscalerConfig(enabled=True)
    if as_cfg is not None and as_cfg.enabled:
        if fleet:
            from ..telemetry import get_telemetry
            from ..telemetry.flight_recorder import get_flight_recorder
            from .autoscaler import Autoscaler

            autoscaler = Autoscaler(
                fe, fleet, as_cfg, engine=args.engine,
                store_endpoint=store,
                max_outstanding_tokens=fe.params.max_outstanding_tokens,
                registry=get_telemetry().registry,
                recorder=get_flight_recorder())
            autoscaler.start()
        else:
            print("warning: --autoscale needs a launched worker fleet "
                  "(--workers N); ignoring", file=sys.stderr)
    try:
        if args.dry_run:
            # boot -> probe -> clean shutdown, one parseable JSON line
            conn = http.client.HTTPConnection(door.host, door.port,
                                              timeout=10)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            health = json.loads(resp.read())
            conn.close()
            print(json.dumps({"ok": resp.status == 200,
                              "endpoint": door.endpoint,
                              "healthz": health}))
            return 0 if resp.status == 200 else 3
        print(f"DS_SERVING_FRONTDOOR endpoint={door.endpoint}",
              flush=True)
        stop = threading.Event()

        def _term(signum, frame):
            stop.set()

        signal.signal(signal.SIGTERM, _term)
        signal.signal(signal.SIGINT, _term)
        stop.wait()
        return 0
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        door.shutdown()
        if fleet:
            from ..launcher.serving_fleet import shutdown_fleet

            shutdown_fleet(fleet)


def trace_command(args: argparse.Namespace) -> int:
    """Assemble one request's cross-process timeline (ISSUE 15)."""
    import os
    import sys as _sys

    from ..elasticity.rendezvous import RendezvousClient
    from .tracing import (assemble_timeline, distinct_trace_ids,
                          fetch_request_docs, find_trace,
                          render_timeline, timeline_chrome_trace)

    if not args.endpoint:
        print("error: trace needs --endpoint host:port "
              "(or $DS_RDZV_ENDPOINT)", file=_sys.stderr)
        return 2
    client = RendezvousClient(args.endpoint, retries=1, backoff_s=0.05)
    try:
        docs = fetch_request_docs(client)
    except (ConnectionError, OSError) as e:
        print(f"error: store unreachable at {args.endpoint}: {e}",
              file=_sys.stderr)
        return 2
    finally:
        try:
            client.close()
        except (OSError, ConnectionError):
            pass  # read-only CLI teardown; nothing to leak
    matches = find_trace(docs, args.trace_id)
    if not matches:
        nodes = ", ".join(sorted(docs)) or "none publishing"
        print(f"no records for trace {args.trace_id!r} "
              f"(nodes consulted: {nodes}) — the request was not "
              f"sampled, fell off the retention window, or the id is "
              f"wrong", file=_sys.stderr)
        return 3
    ids = distinct_trace_ids(matches)
    if len(ids) > 1:
        # a short prefix resolving to several requests must never
        # merge them into one fabricated timeline
        print(f"prefix {args.trace_id!r} is ambiguous — "
              f"{len(ids)} distinct trace ids match: "
              + ", ".join(ids[:8])
              + (" …" if len(ids) > 8 else ""), file=_sys.stderr)
        return 2
    resolved = ids[0]
    tl = assemble_timeline(matches)
    if args.json:
        print(json.dumps(tl, default=str, indent=2))
    else:
        print(render_timeline(tl))
    if args.out:
        doc = timeline_chrome_trace(docs, trace_id=resolved)
        out = os.path.abspath(args.out)
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as fh:
            json.dump(doc, fh)
        print(f"chrome trace written: {out}", file=_sys.stderr)
    return 0


def slo_command(args: argparse.Namespace) -> int:
    """Render the fleet's SLO burn-rate state from the telemetry
    rollup in the rendezvous store.  Exit 2 when the store is
    unreachable, 3 when no door is publishing SLO gauges yet."""
    import sys as _sys

    from ..elasticity.rendezvous import RendezvousClient
    from ..telemetry.rollup import collect_rollup
    from .slo import render_slo_table, slo_rows_from_rollup

    if not args.endpoint:
        print("error: slo needs --endpoint host:port "
              "(or $DS_RDZV_ENDPOINT)", file=_sys.stderr)
        return 2
    client = RendezvousClient(args.endpoint, retries=1, backoff_s=0.05)
    try:
        peers = sorted(k.rsplit("/", 1)[1]
                       for k in client.keys("telemetry/metrics/"))
        rollup = collect_rollup(client, peers)
    except (ConnectionError, OSError) as e:
        print(f"error: store unreachable at {args.endpoint}: {e}",
              file=_sys.stderr)
        return 2
    finally:
        try:
            client.close()
        except (OSError, ConnectionError):
            pass  # read-only CLI teardown; nothing to leak
    rows = slo_rows_from_rollup(rollup)
    if not rows:
        nodes = ", ".join(peers) or "none publishing"
        print(f"no SLO gauges in the rollup (nodes consulted: {nodes})"
              f" — is a door running with serving.slo enabled?",
              file=_sys.stderr)
        return 3
    if args.json:
        print(json.dumps(rows, indent=2))
    else:
        print(render_slo_table(rows))
    return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.serving",
        description="serving-plane operator commands")
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bench", help="mixed-class serving benchmark")
    b.add_argument("--dry-run", action="store_true",
                   help="synthetic replicas on a fake clock (no device)")
    b.add_argument("--network", action="store_true",
                   help="real front door + worker processes over HTTP")
    b.add_argument("--replicas", type=int, default=2)
    b.add_argument("--interactive", type=int, default=12)
    b.add_argument("--background", type=int, default=6)
    b.add_argument("--duration", type=float, default=3.0,
                   help="--network: sustained-load window (s)")
    b.add_argument("--seed", type=int, default=0)
    b.add_argument("--replay", default=None, metavar="ACCESS_LOG",
                   help="re-issue a recorded JSONL access log as load, "
                        "preserving inter-arrival timing, classes, "
                        "sizes, and trace ids; reports achieved vs "
                        "recorded")
    b.add_argument("--speed", type=float, default=1.0,
                   help="--replay: time-compression factor (2.0 = "
                        "twice as fast as recorded)")
    b.add_argument("--endpoint", default=None,
                   help="--replay: drive an already-running front "
                        "door (host:port) instead of an ephemeral "
                        "fleet")
    b.add_argument("--max-requests", type=int, default=0,
                   help="--replay: stop after this many records "
                        "(0 = all)")

    s = sub.add_parser("serve", help="run the HTTP/SSE front door")
    s.add_argument("--dry-run", action="store_true",
                   help="boot synthetic replicas, answer a health "
                        "probe, shut down cleanly (CI smoke)")
    s.add_argument("--ds-config", default=None,
                   help="DeepSpeed config (path or inline JSON) whose "
                        "serving.network group seeds the defaults "
                        "below; explicit flags win")
    s.add_argument("--host", default=None)
    s.add_argument("--port", type=int, default=None)
    s.add_argument("--replicas", type=int, default=2,
                   help="in-process replicas (no --workers/--store)")
    s.add_argument("--workers", type=int, default=None,
                   help="spawn this many replica worker PROCESSES")
    s.add_argument("--prefill-workers", type=int, default=None,
                   help="of the worker fleet, run this many as "
                        "dedicated prefill replicas (--disaggregate)")
    s.add_argument("--disaggregate", action="store_true",
                   help="prefill/decode disaggregation over the "
                        "KV-page transport")
    s.add_argument("--engine", choices=("synthetic", "tiny-llama"),
                   default="synthetic")
    s.add_argument("--store", default=None,
                   help="rendezvous store endpoint (worker discovery "
                        "+ registration)")
    s.add_argument("--queue-token-budget", type=int, default=None)
    s.add_argument("--retry-after", type=float, default=None)
    s.add_argument("--kv-chunk-bytes", type=int, default=None)
    s.add_argument("--access-log", default=None,
                   help="structured JSONL access log path "
                        "(one line per request, size-cap rotated)")
    s.add_argument("--slo", action="store_true",
                   help="evaluate default SLO burn-rate monitors in "
                        "the door (serving.slo config group overrides)")
    s.add_argument("--autoscale", action="store_true",
                   help="run the autoscaler policy loop over the "
                        "launched worker fleet (needs --workers N; "
                        "serving.autoscaler config group overrides)")

    w = sub.add_parser("worker", help="run ONE replica worker process")
    w.add_argument("--id", required=True)
    w.add_argument("--role", choices=("mixed", "prefill", "decode"),
                   default="mixed")
    w.add_argument("--engine", choices=("synthetic", "tiny-llama"),
                   default="synthetic")
    w.add_argument("--port", type=int, default=0)
    w.add_argument("--store", default=None)
    w.add_argument("--slots", type=int, default=4)
    w.add_argument("--blocks", type=int, default=256)
    w.add_argument("--block-size", type=int, default=16)
    w.add_argument("--max-seq-len", type=int, default=512)
    w.add_argument("--kv-chunk-bytes", type=int, default=64 * 1024)
    w.add_argument("--drip", type=int, default=0,
                   help="flow control: tokens per poll reply (0 = all; "
                        "chaos tests keep streams in flight with 1)")
    w.add_argument("--trace-sample-rate", type=float, default=None,
                   help="request-trace head sample rate (anomalies are "
                        "always recorded)")
    w.add_argument("--trace-ring", type=int, default=None,
                   help="request-trace retention window (records)")
    w.add_argument("--trace-anomaly-ttft-ms", type=float, default=None,
                   help="TTFT (ms) past which a request is force-"
                        "sampled as anomalous")
    w.add_argument("--push-every", type=float, default=1.0,
                   help="telemetry/request-record publish cadence (s)")
    w.add_argument("--step-delay-ms", type=float, default=0.0,
                   help="synthetic engine: wall-clock sleep per step "
                        "(paces decode for chaos tests)")

    import os as _os

    t = sub.add_parser("trace", help="assemble one request's cross-"
                                     "process timeline (exit 3 when "
                                     "the id is unknown)")
    t.add_argument("trace_id", help="the X-DS-Trace id (a unique "
                                    "prefix >= 6 chars works)")
    t.add_argument("--endpoint",
                   default=_os.environ.get("DS_RDZV_ENDPOINT"),
                   help="rendezvous store host:port "
                        "(default: $DS_RDZV_ENDPOINT)")
    t.add_argument("--json", action="store_true",
                   help="emit the assembled timeline as JSON")
    t.add_argument("--out", default=None,
                   help="also write the request lanes as a Chrome-"
                        "trace JSON (open in Perfetto)")

    sl = sub.add_parser("slo", help="fleet SLO burn-rate state from "
                                    "the telemetry rollup (exit 3 when "
                                    "no door publishes SLO gauges)")
    sl.add_argument("--endpoint",
                    default=_os.environ.get("DS_RDZV_ENDPOINT"),
                    help="rendezvous store host:port "
                         "(default: $DS_RDZV_ENDPOINT)")
    sl.add_argument("--json", action="store_true",
                    help="emit the SLO rows as JSON")

    args = p.parse_args(argv)
    if args.cmd == "bench":
        return bench_command(args)
    if args.cmd == "serve":
        return serve_command(args)
    if args.cmd == "worker":
        return worker_command(args)
    if args.cmd == "trace":
        return trace_command(args)
    if args.cmd == "slo":
        return slo_command(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
