"""Serving CLI — ``python -m deepspeed_tpu.serving bench [--dry-run]``.

One deterministic multi-tenant workload, two execution modes:

* ``--dry-run`` — synthetic replicas on a fake clock: zero device work,
  finishes in milliseconds, numbers deterministic.  This is the CI
  smoke (run_suite.sh) and the quickest way to see the serving metrics
  end to end.
* real mode — a tiny real model through ``build_serving_frontend`` on
  whatever backend JAX has (CPU works): the same workload against the
  actual compiled engine.  ``bench.py``'s serving variant reuses
  :func:`run_workload` against a production-sized model.

The emitted JSON line carries the gated serving metrics
(``serving_p99_ttft_ms``, ``prefix_hit_rate``, ``tok_s_interactive``)
in the exact shape ``telemetry perf check`` reads.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional

import numpy as np


def run_workload(frontend: Any, clock, n_interactive: int = 12,
                 n_background: int = 6, header_len: int = 128,
                 interactive_new: int = 16, background_new: int = 96,
                 warm_rounds: int = 4, seed: int = 0) -> Dict[str, Any]:
    """Drive a shared-header, mixed-class workload to completion and
    report the serving metrics.  Background requests saturate the decode
    slots first; interactive requests then arrive one at a time and are
    each driven to completion (so their TTFT reflects contention, not
    batching of the probe stream itself)."""
    from .metrics import ServingMetrics

    rng = np.random.RandomState(seed)
    header = rng.randint(2, 29000, size=header_len).tolist()

    def prompt(tail: int) -> list:
        return header + rng.randint(2, 29000, size=tail).tolist()

    def hit_counts():
        hits = looks = 0
        for r in frontend.router.replicas:
            p = getattr(r.scheduler, "prefix", None)
            if p is not None:
                hits += p.hit_tokens
                looks += p.lookup_tokens
        return hits, looks

    # this workload's own window: fresh latency trackers, and the prefix
    # hit rate as a delta (a warm-up pass must not pollute the p99 tail
    # with compile time, nor dilute the hit rate)
    frontend.metrics = ServingMetrics()
    hits0, looks0 = hit_counts()
    t0 = clock()
    background = [frontend.submit(prompt(16), max_new_tokens=background_new,
                                  klass="background")
                  for _ in range(n_background)]
    for _ in range(warm_rounds):
        frontend.pump()
    interactive = []
    for _ in range(n_interactive):
        h = frontend.submit(prompt(8), max_new_tokens=interactive_new,
                            klass="interactive")
        interactive.append(h)
        for _ in range(100_000):
            frontend.pump()
            if h.status != "running" and h.status != "queued":
                break
        else:
            raise RuntimeError("interactive request never completed")
    frontend.run_until_idle()
    elapsed = max(clock() - t0, 1e-9)

    m = frontend.metrics
    done = [h for h in interactive + background if h.status == "done"]
    out = {
        "serving_p99_ttft_ms": round(m.ttft["interactive"].percentile(99),
                                     3),
        "serving_p50_ttft_ms": round(m.ttft["interactive"].percentile(50),
                                     3),
        "background_p99_ttft_ms": round(
            m.ttft["background"].percentile(99), 3),
        "prefix_hit_rate": round(
            (hit_counts()[0] - hits0)
            / max(hit_counts()[1] - looks0, 1), 4),
        "tok_s_interactive": round(m.tokens["interactive"] / elapsed, 1),
        "tok_s_background": round(m.tokens["background"] / elapsed, 1),
        "preemptions": m.counters["preemptions"],
        "requests_completed": len(done),
        "requests_submitted": m.counters["submitted"],
        "elapsed_s": round(elapsed, 4),
    }
    return out


def _dry_run_frontend(replicas: int, slots: int = 4):
    from . import (FakeClock, Replica, ServingFrontend, ServingParams,
                   SyntheticEngine)
    from ..inference.v2 import KVCacheConfig

    clock = FakeClock()
    cache = KVCacheConfig(num_blocks=256, block_size=16, max_seq_len=512)
    reps = [Replica(SyntheticEngine(cache, max_batch_slots=slots,
                                    prefill_chunk=64, prefill_batch=2,
                                    decode_burst=4, clock=clock), i)
            for i in range(replicas)]
    fe = ServingFrontend(reps, params=ServingParams(
        interactive_reserve_frac=0.1), clock=clock)
    return fe, clock


def _real_frontend(replicas: int):
    import time

    import jax.numpy as jnp

    from . import ServingParams, build_serving_frontend
    from ..inference.v2 import KVCacheConfig
    from ..models import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(num_layers=2, max_seq_len=256,
                           dtype=jnp.float32)
    fe = build_serving_frontend(
        LlamaModel(cfg), replicas=replicas,
        cache_config=KVCacheConfig(num_blocks=128, block_size=16,
                                   max_seq_len=256),
        max_batch_slots=4, prefill_chunk=32, prefill_batch=2,
        decode_burst=4,
        serving_params=ServingParams(interactive_reserve_frac=0.1))
    return fe, time.monotonic


def bench_command(args: argparse.Namespace) -> int:
    if args.dry_run:
        fe, clock = _dry_run_frontend(args.replicas)
        header_len, inter_new, bg_new = 128, 16, 96
    else:
        fe, clock = _real_frontend(args.replicas)
        # sized for a tiny model within its 256-token max_seq_len
        header_len, inter_new, bg_new = 64, 8, 24
    out = run_workload(fe, clock, n_interactive=args.interactive,
                       n_background=args.background,
                       header_len=header_len, interactive_new=inter_new,
                       background_new=bg_new, seed=args.seed)
    out["dry_run"] = bool(args.dry_run)
    out["replicas"] = args.replicas
    print(json.dumps(out))
    return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.serving",
        description="serving-plane operator commands")
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bench", help="mixed-class serving benchmark")
    b.add_argument("--dry-run", action="store_true",
                   help="synthetic replicas on a fake clock (no device)")
    b.add_argument("--replicas", type=int, default=2)
    b.add_argument("--interactive", type=int, default=12)
    b.add_argument("--background", type=int, default=6)
    b.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.cmd == "bench":
        return bench_command(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
