"""Synthetic serving engine — the real scheduler, a fake device.

Everything the serving plane *decides* (admission, prefix sharing,
preemption, routing, draining) is host-side logic over the
:class:`ServingScheduler`; only token *values* need a device.  The
synthetic engine drives the REAL scheduler through the REAL planner
surface (``plan_step`` / ``chunk_done`` / ``decode_burst_done``,
including the SplitFuse burst-length rule) but invents tokens with a
deterministic hash of (prompt, position) — so:

* serving tests and the ``bench --dry-run`` CLI smoke run in
  milliseconds with zero compilation and no accelerator;
* a request re-executed after a replica death regenerates the *same*
  token sequence, which is exactly the property the front-end's
  seamless re-queue relies on (greedy decode has it on real hardware);
* an injectable :class:`FakeClock` advances by a configurable cost per
  prefill chunk / decode step, making TTFT distributions deterministic
  for the SLO acceptance tests.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..inference.v2.kv_cache import KVCacheConfig
from ..inference.v2.scheduler import Request
from .scheduler import ServingScheduler


class FakeClock:
    """Injectable monotonic clock: ``clock()`` reads, ``advance`` moves."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


def synthetic_token(prompt: List[int], index: int) -> int:
    """Deterministic token for generation position ``index`` of a
    request with this prompt — stable across re-execution."""
    seed = 0
    for t in prompt[:64]:
        seed = (seed * 1000003 + int(t)) % (1 << 31)
    return (seed * 31 + index * 2654435761) % 29000 + 2


def synthetic_expert(prompt: List[int], index: int,
                     num_experts: int) -> int:
    """Deterministic expert id for a generated token — the synthetic
    twin of the real gate's argmax routing.  Prompt-dependent (like a
    real router: different inputs excite different experts) so a
    replica serving a skewed prompt mix develops genuinely skewed
    expert load."""
    return (synthetic_token(prompt, index) * 40503) % num_experts


class SyntheticEngine:
    """Drop-in replica engine: real ServingScheduler, no device."""

    def __init__(self, cache_config: Optional[KVCacheConfig] = None,
                 max_batch_slots: int = 8, prefill_chunk: int = 64,
                 prefill_batch: int = 2, decode_burst: int = 4,
                 prefix_sharing: bool = True,
                 clock: Optional[FakeClock] = None,
                 prefill_cost_s: float = 0.004,
                 decode_cost_s: float = 0.002,
                 step_delay_s: float = 0.0,
                 num_experts: int = 0):
        self.cache_config = cache_config or KVCacheConfig(
            num_blocks=256, block_size=16, max_seq_len=1024)
        self.scheduler = ServingScheduler(
            self.cache_config, max_batch_slots=max_batch_slots,
            prefill_chunk=prefill_chunk, prefill_batch=prefill_batch,
            prefix_sharing=prefix_sharing)
        self.decode_burst = max(1, int(decode_burst))
        self.pool = None  # no device pool
        self._clock = clock
        self.prefill_cost_s = float(prefill_cost_s)
        self.decode_cost_s = float(decode_cost_s)
        #: REAL wall-clock sleep per step: paces worker-process decode
        #: so chaos tests can kill -9 a replica genuinely mid-stream
        self.step_delay_s = float(step_delay_s)
        self.steps = 0
        #: synthetic MoE routing (ISSUE 19): when > 0 every decoded token
        #: is attributed to a deterministic expert, mirroring the real
        #: engine's per-expert load telemetry — the router placement
        #: tests exercise hot-expert avoidance without a device
        self.num_experts = int(num_experts)
        self.expert_counts = np.zeros(max(self.num_experts, 1), np.int64)

    # -- the engine surface the front-end drives ---------------------------

    def put(self, prompt: List[int], max_new_tokens: int = 32) -> Request:
        return self.scheduler.add_request(prompt, max_new_tokens)

    def step(self, temperature: float = 0.0,
             eos_token_id: Optional[int] = None) -> int:
        """One planner step, mirroring the real engine's control flow
        (burst 1 while prefill work interleaves, else decode_burst)."""
        del temperature  # synthetic tokens are class-less
        if self.step_delay_s > 0:
            import time

            time.sleep(self.step_delay_s)
        chunks, decode = self.scheduler.plan_step()
        n = 0
        cost = 0.0
        for ch in chunks:
            first = (synthetic_token(ch.request.prompt, 0)
                     if ch.is_last else None)
            self.scheduler.chunk_done(ch, first, eos_token_id)
            n += ch.n_valid
            cost += self.prefill_cost_s
        if decode:
            burst = 1 if (chunks or self.scheduler.prefilling) \
                else self.decode_burst
            toks = np.zeros((burst, self.scheduler.max_slots), np.int64)
            for req in decode:
                base = len(req.generated)
                for t in range(burst):
                    toks[t, req.slot] = synthetic_token(req.prompt,
                                                        base + t)
                    if self.num_experts > 0:
                        self.expert_counts[synthetic_expert(
                            req.prompt, base + t, self.num_experts)] += 1
            n += self.scheduler.decode_burst_done(decode, toks,
                                                  eos_token_id)
            cost += self.decode_cost_s * burst
        if self._clock is not None and cost:
            self._clock.advance(cost)
        self.steps += 1
        return n

    # -- MoE load surface (mirrors RaggedInferenceEngineV2) -----------------

    def moe_expert_load(self) -> Optional[np.ndarray]:
        """Per-expert token-load fractions (sum 1) or ``None`` before any
        routed token / without synthetic experts."""
        if self.num_experts <= 0:
            return None
        total = self.expert_counts.sum()
        if total <= 0:
            return None
        return self.expert_counts / float(total)

    def moe_load_imbalance(self) -> float:
        """max/mean expert load — 1.0 is a balanced router, 0.0 means no
        MoE data (same contract as the real v2 engine)."""
        load = self.moe_expert_load()
        if load is None:
            return 0.0
        return float(load.max() / max(load.mean(), 1e-12))
