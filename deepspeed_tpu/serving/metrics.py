"""Serving observability — per-latency-class TTFT / per-token latency
and per-request lifecycle records (ISSUE 15 tentpole b).

The serving plane's SLOs are *distributional* (p50/p99 time-to-first-
token per class), which the telemetry registry's fixed-bucket histograms
approximate too coarsely to gate on.  :class:`LatencyTracker` keeps a
bounded sample window and computes exact percentiles over it — and,
since a p99 with no identity is a dead end at 3am, each sample may carry
an *exemplar* reference (the request's trace id) so the slowest request
in the window is traceable, not anonymous.  :class:`ServingMetrics` owns
one TTFT and one TPOT (time-per-output-token) tracker per class plus the
serving counters, publishes gauges through the existing
:class:`MetricsRegistry`, and renders the ``serving`` section of debug
bundles.

:class:`RequestRecord` is the per-request sibling of the training
plane's StepRecord: one request's whole lifecycle — queue wait,
admission attempts, preempt/resume, replica placement and replays,
prefill/transfer/decode phases, token timings — stamped on
``time.perf_counter()`` so the PR-13 clocksync offset lands every event
on the shared store clock.  :class:`RequestLog` is the bounded ring the
records commit into: head-based sampled (``serving.tracing.
sample_rate`` — deterministic on the trace id, so every process that
touches a request makes the SAME decision) with always-on sampling for
anomalous requests (replayed, preempted, failed, expired, or TTFT over
the threshold), shipped cross-process over the PR-13 rollup transport.

All ServingMetrics methods are called with the front-end's lock held
(single writer); RequestRecord/RequestLog carry their own lock (door
handler threads, worker protocol threads, and the pump all touch them).
Reads used by tests/CLI take point-in-time copies.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: latency classes in strict priority order — admission drains them
#: left-to-right, preemption moves rightmost work out of the way
CLASSES = ("interactive", "batch", "background")


class LatencyTracker:
    """Bounded sample window with exact percentiles (ms).  Samples may
    carry an exemplar ref (a request trace id) so the window's tail is
    traceable."""

    def __init__(self, max_samples: int = 512):
        self._samples: deque = deque(maxlen=int(max_samples))
        self._refs: deque = deque(maxlen=int(max_samples))

    def observe(self, ms: float, ref: Optional[str] = None) -> None:
        self._samples.append(float(ms))
        self._refs.append(ref)

    @property
    def count(self) -> int:
        return len(self._samples)

    def percentile(self, p: float) -> float:
        """Exact percentile over the window (nearest-rank); 0.0 empty."""
        if not self._samples:
            return 0.0
        ordered = sorted(self._samples)
        rank = min(len(ordered) - 1,
                   max(0, int(round(p / 100.0 * (len(ordered) - 1)))))
        return ordered[rank]

    def exemplar(self) -> Optional["tuple"]:
        """``(ms, ref)`` of the slowest ref-carrying sample in the
        window — the request id behind the p99, not just its number."""
        best = None
        for ms, ref in zip(self._samples, self._refs):
            if ref is not None and (best is None or ms > best[0]):
                best = (ms, ref)
        return best

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "count": float(self.count),
            "p50_ms": round(self.percentile(50), 3),
            "p99_ms": round(self.percentile(99), 3)}
        ex = self.exemplar()
        if ex is not None:
            # the id a `serving trace <id>` can assemble — surfaced
            # right next to the percentile it explains
            out["p99_exemplar"] = ex[1]
            out["p99_exemplar_ms"] = round(ex[0], 3)
        return out


#: why an admission attempt bounced (ISSUE 16 satellite): no free
#: decode slot / not enough KV pages / per-replica outstanding-token
#: budget / HBM-headroom floor deferral.  One increment per blocked
#: pump round, not per unique request — it is a pressure rate.
ADMISSION_REJECT_REASONS = ("slots", "pages", "token_budget", "headroom")


def count_admission_reject(metrics: "ServingMetrics", reason: str) -> None:
    """One admission rejection, attributed: the local counter shows in
    ``/v1/metrics``; the telemetry counter rides the rollup so the
    cluster view can tell "add workers" (slots/tokens) from "add HBM"
    (pages/headroom)."""
    metrics.inc(f"admission_rejected_{reason}")
    from ..telemetry import get_telemetry

    tel = get_telemetry()
    if tel.enabled:
        tel.inc_counter(f"serving/admission_rejected_{reason}_total",
                        help="admission attempts bounced, by blocking "
                             "resource")


class ServingMetrics:
    """The serving plane's numbers: per-class latency + global counters."""

    def __init__(self, window: int = 512):
        self.ttft = {c: LatencyTracker(window) for c in CLASSES}
        self.tpot = {c: LatencyTracker(window) for c in CLASSES}
        self.tokens = {c: 0 for c in CLASSES}
        self.completed = {c: 0 for c in CLASSES}
        #: disaggregated-mode TTFT attribution: where the first token's
        #: latency went (prefill replica / KV-page transfer / decode
        #: replica's first burst)
        self.disagg = {k: LatencyTracker(window)
                       for k in ("prefill_ms", "transfer_ms", "decode_ms")}
        self.counters: Dict[str, int] = {
            "submitted": 0, "cancelled": 0, "failed": 0,
            "preemptions": 0, "preempt_pages_released": 0,
            "requeued_replica_death": 0,
            "admission_deferred_headroom": 0,
            "disagg_requests": 0,
        }
        # seeded so a zero shows in /v1/metrics before the first
        # rejection — an operator diffing reasons must see the absence
        for r in ADMISSION_REJECT_REASONS:
            self.counters[f"admission_rejected_{r}"] = 0

    def inc(self, name: str, v: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + v

    def record_ttft(self, klass: str, ms: float,
                    ref: Optional[str] = None) -> None:
        self.ttft[klass].observe(ms, ref=ref)

    def record_disagg(self, breakdown: Dict[str, float],
                      count: bool = True) -> None:
        """One disaggregated request's TTFT attribution (ms per
        stage); missing stages are skipped.  ``count=False`` records a
        late-arriving stage (decode_ms lands with the first decoded
        token) without double-counting the request."""
        if count:
            self.counters["disagg_requests"] += 1
        for k, tracker in self.disagg.items():
            v = breakdown.get(k)
            if v is not None:
                tracker.observe(float(v))
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        if tel.enabled:
            for k, tracker in self.disagg.items():
                if tracker.count:
                    tel.set_gauge(
                        f"serving/disagg_ttft_{k.replace('_ms', '')}_p50_ms",
                        tracker.percentile(50),
                        help="disaggregated TTFT attribution p50 by stage")

    def record_completion(self, klass: str, n_tokens: int,
                          gen_time_s: float) -> None:
        self.completed[klass] += 1
        self.tokens[klass] += int(n_tokens)
        if n_tokens > 1 and gen_time_s > 0:
            self.tpot[klass].observe(gen_time_s * 1e3 / (n_tokens - 1))

    # -- export ------------------------------------------------------------

    def publish(self, queue_depths: Dict[str, int],
                prefix_hit_rate: float,
                moe_imbalance: Optional[Dict[int, float]] = None) -> None:
        """Push the current numbers as gauges/counters through the
        telemetry hub (no-op when telemetry is off).  ``moe_imbalance``
        maps replica id → hot-expert imbalance (max/mean expert load) so
        the autoscaler and dashboards see which replica is routing
        skewed."""
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        if not tel.enabled:
            return
        if moe_imbalance:
            for rid, imb in sorted(moe_imbalance.items()):
                tel.set_gauge(f"serving/replica{rid}_moe_imbalance",
                              float(imb),
                              help="max/mean expert load of the replica's "
                                   "recent decodes (1.0 = balanced)")
            tel.set_gauge("serving/moe_imbalance_max",
                          max(float(v) for v in moe_imbalance.values()),
                          help="worst hot-expert imbalance across "
                               "replicas — the fleet's routing-skew "
                               "signal")
        for c in CLASSES:
            tel.set_gauge(f"serving/{c}_ttft_p50_ms",
                          self.ttft[c].percentile(50),
                          help="time-to-first-token p50 by class")
            tel.set_gauge(f"serving/{c}_ttft_p99_ms",
                          self.ttft[c].percentile(99),
                          help="time-to-first-token p99 by class")
            tel.set_gauge(f"serving/{c}_tpot_p50_ms",
                          self.tpot[c].percentile(50),
                          help="per-output-token latency p50 by class")
            tel.set_gauge(f"serving/{c}_queue_depth",
                          float(queue_depths.get(c, 0)),
                          help="requests queued (not yet admitted)")
        tel.set_gauge("serving/prefix_hit_rate", prefix_hit_rate,
                      help="fraction of prompt tokens served from shared "
                           "prefix pages")

    def snapshot(self) -> Dict[str, Any]:
        classes: Dict[str, Any] = {}
        for c in CLASSES:
            classes[c] = {"ttft": self.ttft[c].summary(),
                          "tpot": self.tpot[c].summary(),
                          "tokens": self.tokens[c],
                          "completed": self.completed[c]}
        out = {"classes": classes, "counters": dict(self.counters)}
        if self.counters.get("disagg_requests"):
            out["disagg_ttft"] = {k: t.summary()
                                  for k, t in self.disagg.items()}
        return out


# ---------------------------------------------------------------------------
# per-request lifecycle records (ISSUE 15 tentpole b)
# ---------------------------------------------------------------------------

#: bound on non-token events kept per record (a pathological admission
#: storm must not grow one record without bound)
MAX_RECORD_EVENTS = 128


def head_sampled(trace_id: str, sample_rate: float) -> bool:
    """Deterministic head-based sampling decision: every process that
    hashes the same trace id reaches the same verdict, so a sampled
    request is sampled on EVERY lane it crosses (and an unsampled one
    costs nothing anywhere) without a flag having to ride each hop."""
    rate = float(sample_rate)
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = int(hashlib.sha1(str(trace_id).encode()).hexdigest()[:8], 16)
    return (h / float(0xFFFFFFFF)) < rate


class RequestRecord:
    """One request's lifecycle on ONE process — the serving sibling of
    the training StepRecord.  Event/phase timestamps are raw
    ``time.perf_counter()`` seconds: the node's clocksync offset
    (shipped alongside, see ``serving/tracing.py``) lands them on the
    shared store clock, which is what lets N processes' records merge
    into one aligned timeline."""

    def __init__(self, trace_id: str, uid: Any, klass: str,
                 prompt_tokens: int, max_new_tokens: int,
                 sampled: bool, lock: Optional[threading.Lock] = None,
                 token_cap: int = 512):
        self.trace_id = str(trace_id)
        self.uid = uid
        self.klass = str(klass)
        self.prompt_tokens = int(prompt_tokens)
        self.max_new_tokens = int(max_new_tokens)
        self.sampled = bool(sampled)
        self.start_ts = time.perf_counter()
        self.end_ts: Optional[float] = None
        self.status = "open"
        self.events: List[Dict[str, Any]] = []
        self.phases: List[Dict[str, Any]] = []
        #: perf-counter stamps of the first ``token_cap`` delivered
        #: tokens (enough for gap percentiles without unbounded growth)
        self.token_ts: List[float] = []
        self._token_cap = int(token_cap)
        self.tokens = 0
        self.replays = 0
        self.preempts = 0
        self.admission_attempts = 0
        self.replicas: List[Any] = []
        self.admitted_ts: Optional[float] = None
        self.ttft_ms: Optional[float] = None
        self.breakdown: Optional[Dict[str, float]] = None
        self.error: Optional[str] = None
        self.anomaly: Optional[str] = None
        self.events_dropped = 0
        self._lock = lock or threading.Lock()

    # -- producers (any thread) --------------------------------------------

    def event(self, name: str, **extra: Any) -> None:
        ev = {"name": str(name), "ts": time.perf_counter()}
        ev.update(extra)
        with self._lock:
            if name == "replayed":
                self.replays += 1
            elif name == "preempted":
                self.preempts += 1
            elif name == "admitted":
                self.admitted_ts = ev["ts"]
                if "replica" in extra:
                    self.replicas.append(extra["replica"])
            if len(self.events) >= MAX_RECORD_EVENTS:
                self.events_dropped += 1
                return
            self.events.append(ev)

    def phase(self, name: str, start_ts: Optional[float] = None,
              end_ts: Optional[float] = None,
              dur_ms: Optional[float] = None, **extra: Any) -> None:
        """One timed phase (prefill / transfer batch / decode burst).
        Either ``start_ts``/``end_ts`` (perf-counter) or an externally
        measured ``dur_ms`` anchored at ``end_ts`` (default: now)."""
        end = float(end_ts) if end_ts is not None else time.perf_counter()
        if dur_ms is None:
            start = float(start_ts) if start_ts is not None else end
            dur_ms = (end - start) * 1e3
        else:
            start = end - float(dur_ms) / 1e3
        ph = {"phase": str(name), "ts": start,
              "dur_ms": round(float(dur_ms), 3)}
        ph.update(extra)
        with self._lock:
            if len(self.phases) >= MAX_RECORD_EVENTS:
                self.events_dropped += 1
                return
            self.phases.append(ph)

    def note_blocked_admission(self) -> None:
        with self._lock:
            self.admission_attempts += 1

    def token(self) -> None:
        now = time.perf_counter()
        with self._lock:
            self.tokens += 1
            if len(self.token_ts) < self._token_cap:
                self.token_ts.append(now)

    def finish(self, status: str, ttft_ms: Optional[float] = None,
               error: Optional[BaseException] = None,
               breakdown: Optional[Dict[str, float]] = None) -> None:
        with self._lock:
            self.end_ts = time.perf_counter()
            self.status = str(status)
            if ttft_ms is not None:
                self.ttft_ms = float(ttft_ms)
            if error is not None:
                self.error = repr(error)
            if breakdown:
                self.breakdown = dict(breakdown)

    def propagate_sampled(self) -> bool:
        """The sampling verdict a downstream hop should honor: the
        head-based decision, forced on once the request turned
        anomalous (a replayed request must be recorded on the worker it
        replays to, even at sample_rate=0)."""
        with self._lock:
            return bool(self.sampled or self.replays or self.preempts)

    # -- read side -----------------------------------------------------------

    def token_timing_summary(self) -> Dict[str, float]:
        with self._lock:
            ts = list(self.token_ts)
        if len(ts) < 2:
            return {}
        gaps = sorted((b - a) * 1e3 for a, b in zip(ts, ts[1:]))

        def pct(p: float) -> float:
            return gaps[min(len(gaps) - 1,
                            int(round(p / 100.0 * (len(gaps) - 1))))]

        return {"gap_p50_ms": round(pct(50), 3),
                "gap_p99_ms": round(pct(99), 3),
                "gap_max_ms": round(gaps[-1], 3)}

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "trace_id": self.trace_id, "uid": self.uid,
                "klass": self.klass,
                "prompt_tokens": self.prompt_tokens,
                "max_new_tokens": self.max_new_tokens,
                "sampled": self.sampled, "status": self.status,
                "start_ts": self.start_ts, "end_ts": self.end_ts,
                "tokens": self.tokens, "replays": self.replays,
                "preempts": self.preempts,
                "admission_attempts": self.admission_attempts,
                "replicas": list(self.replicas),
                "events": [dict(e) for e in self.events],
                "phases": [dict(p) for p in self.phases],
            }
            if self.admitted_ts is not None:
                out["queue_wait_ms"] = round(
                    (self.admitted_ts - self.start_ts) * 1e3, 3)
            for k in ("ttft_ms", "breakdown", "error", "anomaly"):
                v = getattr(self, k)
                if v is not None:
                    out[k] = v
            if self.events_dropped:
                out["events_dropped"] = self.events_dropped
        out.update(self.token_timing_summary())
        return out


class RequestLog:
    """Bounded ring of committed :class:`RequestRecord` documents plus
    the registry of still-open ones — the process-local half of the
    request-tracing plane.

    Commit policy: a finished record lands in the ring when it was
    head-sampled OR turned anomalous (replayed / preempted / failed /
    expired / TTFT over ``anomaly_ttft_ms``) — so at ``sample_rate=0``
    the ring still holds exactly the requests worth asking about.  The
    ring doubles as the retention window the PR-13 rollup transport
    ships (``pending()``/``mark_pushed()``): the store key always holds
    the last ``maxlen`` records plus a snapshot of open sampled ones, so
    a ``kill -9``'d process's final publication still shows its partial
    lanes."""

    def __init__(self, maxlen: int = 256, sample_rate: float = 1.0,
                 anomaly_ttft_ms: float = 2000.0, enabled: bool = True,
                 token_cap: int = 512):
        self.enabled = bool(enabled)
        self.maxlen = int(maxlen)
        self.sample_rate = float(sample_rate)
        self.anomaly_ttft_ms = float(anomaly_ttft_ms)
        self.token_cap = int(token_cap)
        self._ring: deque = deque(maxlen=self.maxlen)
        self._open: Dict[int, RequestRecord] = {}
        self._rid = 0
        self._seq = 0
        self._pushed_seq = -1
        self.dropped = 0
        self.stream_id = f"{os.getpid()}-{time.time_ns()}"
        self._lock = threading.Lock()

    def configure(self, enabled: Optional[bool] = None,
                  sample_rate: Optional[float] = None,
                  maxlen: Optional[int] = None,
                  anomaly_ttft_ms: Optional[float] = None,
                  token_cap: Optional[int] = None) -> "RequestLog":
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if sample_rate is not None:
                self.sample_rate = float(sample_rate)
            if anomaly_ttft_ms is not None:
                self.anomaly_ttft_ms = float(anomaly_ttft_ms)
            if token_cap is not None:
                self.token_cap = int(token_cap)
            if maxlen is not None and int(maxlen) != self.maxlen:
                self.maxlen = int(maxlen)
                self._ring = deque(self._ring, maxlen=self.maxlen)
        return self

    # -- producer surface ----------------------------------------------------

    def start(self, trace_id: str, uid: Any, klass: str,
              prompt_tokens: int, max_new_tokens: int,
              sampled: Optional[bool] = None) -> RequestRecord:
        """Open a record.  ``sampled=None`` takes the deterministic
        head-based decision; an explicit flag (propagated over an RPC by
        an upstream hop that already KNOWS the request is anomalous)
        wins."""
        if sampled is None:
            sampled = head_sampled(trace_id, self.sample_rate)
        rec = RequestRecord(trace_id, uid, klass, prompt_tokens,
                            max_new_tokens, sampled,
                            token_cap=self.token_cap)
        with self._lock:
            self._rid += 1
            rec._open_id = self._rid
            if self.enabled:
                self._open[self._rid] = rec
        return rec

    def anomaly_of(self, rec: RequestRecord) -> Optional[str]:
        if rec.replays:
            return "replayed"
        if rec.preempts:
            return "preempted"
        if rec.status in ("failed", "expired"):
            return rec.status
        if rec.ttft_ms is not None \
                and rec.ttft_ms > self.anomaly_ttft_ms > 0:
            return "slow_ttft"
        return None

    def commit(self, rec: RequestRecord) -> bool:
        """Close a record: ring it when sampled or anomalous.  Always
        drops it from the open registry."""
        anomaly = self.anomaly_of(rec)
        rec.anomaly = anomaly
        with self._lock:
            self._open.pop(getattr(rec, "_open_id", -1), None)
            if not self.enabled or not (rec.sampled or anomaly):
                return False
            self._seq += 1
            doc = rec.to_dict()
            doc["seq"] = self._seq
            doc["done"] = True
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1  # oldest record falls off the window
            self._ring.append(doc)
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        tel.inc_counter("serving/trace_records_total",
                        help="request records committed to the trace ring")
        if anomaly:
            tel.inc_counter(
                "serving/trace_anomaly_records_total",
                help="request records force-sampled as anomalous "
                     "(replayed/preempted/failed/slow-TTFT)")
        return True

    # -- transport surface (the rollup aux-stream protocol) ------------------

    def pending(self) -> Optional[List[Dict[str, Any]]]:
        """The publication batch: the whole committed window plus a
        snapshot of open sampled records (``done: false`` — a process
        killed mid-request leaves its partial lane behind).  ``None``
        when nothing moved since the last successful push."""
        with self._lock:
            if not self.enabled:
                return None
            open_recs = [r for r in self._open.values()
                         if r.sampled or r.replays or r.preempts]
            if self._seq == self._pushed_seq and not open_recs:
                return None
            out = [dict(d) for d in self._ring]
        for r in open_recs:
            d = r.to_dict()
            d["seq"] = 0  # never acked: re-shipped until committed
            d["done"] = False
            out.append(d)
        return out

    def mark_pushed(self, batch: List[Dict[str, Any]]) -> None:
        with self._lock:
            self._pushed_seq = max(
                [self._pushed_seq]
                + [int(d.get("seq", 0)) for d in batch if d.get("done")])

    # -- read side -----------------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(d) for d in self._ring]

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def open_records(self) -> List["RequestRecord"]:
        """The live (uncommitted) records — the profiler fold hook
        stamps a captured decode-burst's device time onto these."""
        with self._lock:
            return list(self._open.values())

    def find(self, trace_id: str) -> List[Dict[str, Any]]:
        """Committed + open records for one trace id (exact match)."""
        tid = str(trace_id)
        with self._lock:
            hits = [dict(d) for d in self._ring
                    if d.get("trace_id") == tid]
            open_recs = [r for r in self._open.values()
                         if r.trace_id == tid]
        for r in open_recs:
            d = r.to_dict()
            d["done"] = False
            hits.append(d)
        return hits

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._open.clear()
            self._seq = 0
            self._pushed_seq = -1
            self.dropped = 0
            self.stream_id = f"{os.getpid()}-{time.time_ns()}"
