"""Serving observability — per-latency-class TTFT / per-token latency.

The serving plane's SLOs are *distributional* (p50/p99 time-to-first-
token per class), which the telemetry registry's fixed-bucket histograms
approximate too coarsely to gate on.  :class:`LatencyTracker` keeps a
bounded sample window and computes exact percentiles over it;
:class:`ServingMetrics` owns one TTFT and one TPOT (time-per-output-
token) tracker per class plus the serving counters, publishes gauges
through the existing :class:`MetricsRegistry`, and renders the
``serving`` section of debug bundles.

All methods are called with the front-end's lock held (single writer);
reads used by tests/CLI take point-in-time copies.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict

#: latency classes in strict priority order — admission drains them
#: left-to-right, preemption moves rightmost work out of the way
CLASSES = ("interactive", "batch", "background")


class LatencyTracker:
    """Bounded sample window with exact percentiles (ms)."""

    def __init__(self, max_samples: int = 512):
        self._samples: deque = deque(maxlen=int(max_samples))

    def observe(self, ms: float) -> None:
        self._samples.append(float(ms))

    @property
    def count(self) -> int:
        return len(self._samples)

    def percentile(self, p: float) -> float:
        """Exact percentile over the window (nearest-rank); 0.0 empty."""
        if not self._samples:
            return 0.0
        ordered = sorted(self._samples)
        rank = min(len(ordered) - 1,
                   max(0, int(round(p / 100.0 * (len(ordered) - 1)))))
        return ordered[rank]

    def summary(self) -> Dict[str, float]:
        return {"count": float(self.count),
                "p50_ms": round(self.percentile(50), 3),
                "p99_ms": round(self.percentile(99), 3)}


class ServingMetrics:
    """The serving plane's numbers: per-class latency + global counters."""

    def __init__(self, window: int = 512):
        self.ttft = {c: LatencyTracker(window) for c in CLASSES}
        self.tpot = {c: LatencyTracker(window) for c in CLASSES}
        self.tokens = {c: 0 for c in CLASSES}
        self.completed = {c: 0 for c in CLASSES}
        #: disaggregated-mode TTFT attribution: where the first token's
        #: latency went (prefill replica / KV-page transfer / decode
        #: replica's first burst)
        self.disagg = {k: LatencyTracker(window)
                       for k in ("prefill_ms", "transfer_ms", "decode_ms")}
        self.counters: Dict[str, int] = {
            "submitted": 0, "cancelled": 0, "failed": 0,
            "preemptions": 0, "preempt_pages_released": 0,
            "requeued_replica_death": 0,
            "admission_deferred_headroom": 0,
            "disagg_requests": 0,
        }

    def inc(self, name: str, v: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + v

    def record_ttft(self, klass: str, ms: float) -> None:
        self.ttft[klass].observe(ms)

    def record_disagg(self, breakdown: Dict[str, float],
                      count: bool = True) -> None:
        """One disaggregated request's TTFT attribution (ms per
        stage); missing stages are skipped.  ``count=False`` records a
        late-arriving stage (decode_ms lands with the first decoded
        token) without double-counting the request."""
        if count:
            self.counters["disagg_requests"] += 1
        for k, tracker in self.disagg.items():
            v = breakdown.get(k)
            if v is not None:
                tracker.observe(float(v))
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        if tel.enabled:
            for k, tracker in self.disagg.items():
                if tracker.count:
                    tel.set_gauge(
                        f"serving/disagg_ttft_{k.replace('_ms', '')}_p50_ms",
                        tracker.percentile(50),
                        help="disaggregated TTFT attribution p50 by stage")

    def record_completion(self, klass: str, n_tokens: int,
                          gen_time_s: float) -> None:
        self.completed[klass] += 1
        self.tokens[klass] += int(n_tokens)
        if n_tokens > 1 and gen_time_s > 0:
            self.tpot[klass].observe(gen_time_s * 1e3 / (n_tokens - 1))

    # -- export ------------------------------------------------------------

    def publish(self, queue_depths: Dict[str, int],
                prefix_hit_rate: float) -> None:
        """Push the current numbers as gauges/counters through the
        telemetry hub (no-op when telemetry is off)."""
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        if not tel.enabled:
            return
        for c in CLASSES:
            tel.set_gauge(f"serving/{c}_ttft_p50_ms",
                          self.ttft[c].percentile(50),
                          help="time-to-first-token p50 by class")
            tel.set_gauge(f"serving/{c}_ttft_p99_ms",
                          self.ttft[c].percentile(99),
                          help="time-to-first-token p99 by class")
            tel.set_gauge(f"serving/{c}_tpot_p50_ms",
                          self.tpot[c].percentile(50),
                          help="per-output-token latency p50 by class")
            tel.set_gauge(f"serving/{c}_queue_depth",
                          float(queue_depths.get(c, 0)),
                          help="requests queued (not yet admitted)")
        tel.set_gauge("serving/prefix_hit_rate", prefix_hit_rate,
                      help="fraction of prompt tokens served from shared "
                           "prefix pages")

    def snapshot(self) -> Dict[str, Any]:
        classes: Dict[str, Any] = {}
        for c in CLASSES:
            classes[c] = {"ttft": self.ttft[c].summary(),
                          "tpot": self.tpot[c].summary(),
                          "tokens": self.tokens[c],
                          "completed": self.completed[c]}
        out = {"classes": classes, "counters": dict(self.counters)}
        if self.counters.get("disagg_requests"):
            out["disagg_ttft"] = {k: t.summary()
                                  for k, t in self.disagg.items()}
        return out
