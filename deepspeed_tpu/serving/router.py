"""Multi-replica routing — load-aware, prefix-affine, health-fed.

N engine replicas (each a :class:`RaggedInferenceEngineV2` with its own
KV pool and :class:`ServingScheduler`, or a host-only
:class:`~.synthetic.SyntheticEngine` in tests/dry-runs) sit behind one
router.  Placement policy, in order:

1. **Health** — only healthy replicas are candidates.  A replica is
   unhealthy when (a) an operator / the front-end marked it dead, (b)
   its injected probe says so, or (c) the process-global
   device-unresponsive latch is set (the PR-7 bounded liveness probe
   tripped: the accelerator tunnel is gone, every in-process replica is
   gone with it).  The front-end additionally subscribes to the hang
   watchdog's trip edge.  A dead replica *drains*: the front-end
   re-queues its in-flight work onto healthy replicas instead of
   blackholing it.
2. **Prefix affinity** — prefer the replica whose prefix trie already
   holds the longest indexed prefix of this prompt (at least
   ``affinity_min_tokens`` worth, so one hot block doesn't pin
   everything to one replica).
3. **Least outstanding tokens** — among equals, the replica with the
   smallest admitted-but-unfinished token count (remaining prompt +
   remaining generation budget summed over its active requests).

Per-replica KV memory is attributed in the PR-7 memory ledger under
distinct ``kv_cache`` sub-keys (``serving/replica<i>/kv_pool`` from the
engine, ``serving/replica<i>/prefix_cache`` maintained here), so ``mem
top`` names serving memory and the ``memory_pressure`` health rule sees
prefix-cache growth.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from ..telemetry.memory import get_memory_ledger
from ..telemetry.memory.ledger import device_unresponsive


class Replica:
    """One engine behind the router + its serving bookkeeping."""

    def __init__(self, engine: Any, replica_id: int,
                 probe: Optional[Callable[[], bool]] = None):
        self.engine = engine
        self.id = int(replica_id)
        self.scheduler = engine.scheduler
        #: handles admitted to this replica and not yet finished
        self.active: List[Any] = []
        self._probe = probe
        self._dead_reason: Optional[str] = None
        #: per-pump-round probe memo — one pump calls healthy() from
        #: half a dozen placement/drain/guard sites; an expensive probe
        #: (device RPC) must run once per round, not once per site
        self._probe_round = 0
        self._probe_seen = -1
        self._probe_ok = True
        #: bytes of ONE pool page across layers/K/V — for prefix-cache
        #: ledger attribution; 0 when the engine has no device pool
        pool = getattr(engine, "pool", None)
        if pool is not None:
            total = int(pool["k"].nbytes) + int(pool["v"].nbytes)
            self.block_nbytes = total // engine.cache_config.num_blocks
        else:
            self.block_nbytes = 0

    # -- health ------------------------------------------------------------

    def new_round(self, gen: int) -> None:
        """Invalidate the probe memo (the front-end, once per pump)."""
        self._probe_round = gen

    def healthy(self) -> bool:
        if self._dead_reason is not None:
            return False
        # the latch is a process-global flag read — always checked fresh
        latch = device_unresponsive()
        if latch is not None:
            self._dead_reason = f"device unresponsive: {latch}"
            return False
        if self._probe is not None:
            if self._probe_seen != self._probe_round:
                self._probe_seen = self._probe_round
                self._probe_ok = self._run_probe()
            if not self._probe_ok:
                return False
        return True

    def _run_probe(self) -> bool:
        try:
            ok = bool(self._probe())
        except Exception as e:
            self._dead_reason = f"health probe raised: {e!r}"
            return False
        if not ok:
            self._dead_reason = "health probe reported dead"
        return ok

    def mark_dead(self, reason: str) -> None:
        self._dead_reason = str(reason)

    @property
    def dead_reason(self) -> Optional[str]:
        return self._dead_reason

    # -- load --------------------------------------------------------------

    def outstanding_tokens(self) -> int:
        total = 0
        for h in self.active:
            req = h.request
            if req is None:
                continue
            total += max(len(req.prompt) - req.prefilled, 0) \
                + req.remaining_budget
        return total

    def moe_load_imbalance(self) -> float:
        """Hot-expert signal from the engine: max/mean expert load of
        its recent decodes (1.0 = balanced router, 0.0 = no MoE data).
        Both the v2 engine and :class:`~.synthetic.SyntheticEngine`
        expose the same method; other engines read as 0.0."""
        fn = getattr(self.engine, "moe_load_imbalance", None)
        if fn is None:
            return 0.0
        try:
            return float(fn())
        except Exception:
            return 0.0

    def update_ledger(self) -> None:
        """Refresh this replica's prefix-cache attribution.  Marked
        ``transient``: cached pages live INSIDE the already-registered
        KV pool allocation, so counting them in the steady-state drift
        cross-check would double-count HBM — but ``mem top`` still shows
        reclaimable prefix memory per replica."""
        led = get_memory_ledger()
        if not led.enabled or self.block_nbytes <= 0:
            return
        alloc = getattr(self.scheduler, "allocator", None)
        cached = getattr(alloc, "num_cached", 0)
        led.register(
            "kv_cache", f"serving/replica{self.id}/prefix_cache",
            cached * self.block_nbytes, transient=True,
            tag=f"prefix-shared cached pages ({cached}) — reclaimable "
                f"subset of the replica's KV pool")

    def snapshot(self) -> dict:
        sched = self.scheduler
        out = {"id": self.id,
               "healthy": self._dead_reason is None,
               "active_requests": len(self.active),
               "outstanding_tokens": self.outstanding_tokens()}
        imb = self.moe_load_imbalance()
        if imb > 0.0:
            out["moe_load_imbalance"] = imb
            load = getattr(self.engine, "moe_expert_load", None)
            if load is None:
                stats = getattr(self.engine, "last_moe_stats", None) or {}
                out["moe_expert_load"] = stats.get("load")
            else:
                arr = load()
                out["moe_expert_load"] = (None if arr is None
                                          else list(map(float, arr)))
        if self._dead_reason:
            out["dead_reason"] = self._dead_reason
        if hasattr(sched, "prefix"):
            out["prefix"] = sched.prefix.stats()
            out["kv_pages_free"] = sched.allocator.num_free
            out["kv_pages_cached"] = sched.allocator.num_cached
            out["preemptions"] = sched.preemptions
        return out


class ReplicaRouter:
    """Least-outstanding-tokens with prefix affinity over healthy
    replicas."""

    def __init__(self, replicas: List[Replica],
                 affinity_min_tokens: int = 16,
                 moe_imbalance_weight: float = 0.25):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.affinity_min_tokens = int(affinity_min_tokens)
        #: hot-expert penalty: a replica whose recent decodes route
        #: max/mean = 2x (one expert doing double work — its MoE FLOPs
        #: are bottlenecked on the hot expert's capacity) scores like it
        #: carries ``1 + weight`` times its outstanding tokens.  0
        #: disables MoE-aware placement.
        self.moe_imbalance_weight = float(moe_imbalance_weight)

    def healthy(self) -> List[Replica]:
        return [r for r in self.replicas if r.healthy()]

    def route_candidates(self, prompt: List[int]) -> List[Replica]:
        """Healthy replicas in placement order (best first): max prefix
        affinity, then least *effective* load — outstanding tokens
        inflated by the replica's hot-expert imbalance (a skewed router
        bottlenecks on its hottest expert, so equal token counts are not
        equal work on a MoE replica) — then stable id."""
        def score(r: Replica):
            affinity = 0
            if hasattr(r.scheduler, "match_tokens"):
                m = r.scheduler.match_tokens(prompt)
                if m >= self.affinity_min_tokens:
                    affinity = m
            load = float(r.outstanding_tokens())
            imb = r.moe_load_imbalance() if self.moe_imbalance_weight else 0.0
            if imb > 1.0:
                load = (load + 1.0) * (
                    1.0 + self.moe_imbalance_weight * (imb - 1.0))
            return (-affinity, load, r.id)

        return sorted(self.healthy(), key=score)

    def route(self, prompt: List[int]) -> Optional[Replica]:
        """Pick the replica for a fresh request; ``None`` when no
        replica is healthy."""
        candidates = self.route_candidates(prompt)
        return candidates[0] if candidates else None

    def snapshot(self) -> dict:
        return {"replicas": [r.snapshot() for r in self.replicas]}
