"""Network replica routing — live endpoints instead of in-process
replicas (ISSUE 14 tentpole b).

:class:`NetworkFrontend` is the PR-8 front-end's control loop rewired
to real worker processes: the same latency-class queues, strict-
priority admission, prefix-affinity placement and drain-and-requeue —
but every replica is a :class:`ReplicaEndpoint` (a JSON-line socket to
a :class:`~.worker.ServingWorker` process), health is a live ``ping``
with a bounded timeout, and a replica *death* is a real dead socket
(``kill -9`` included): in-flight handles re-queue onto survivors and
delivery splices past the streamed high-water mark (exact under greedy
decode — both the synthetic engine and temperature-0 real engines
regenerate the identical sequence).

Disaggregated mode: with prefill-role endpoints present, admission runs
the prefill → KV-page-stream → decode pipeline instead of a plain
submit — the first token is delivered the moment prefill returns (TTFT
excludes the transfer), pages stream prefill→decode peer-to-peer, and
the handle's ``ttft_breakdown`` attributes the tail
(prefill/transfer/decode) for ``telemetry top`` and the SSE ``done``
event.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import log_dist, warn_once
from .frontend import NoHealthyReplicaError, ServingHandle, ServingParams
from .metrics import CLASSES, ServingMetrics
from .worker import SRV_PREFIX


def jsonline_rpc(endpoint: str, requests: List[Dict[str, Any]],
                 timeout: float = 30.0) -> List[Dict[str, Any]]:
    """Send ``requests`` over ONE connection to a JSON-line server
    (worker or tier-2 replica protocol); returns the replies in order.
    No retries — a dead peer raises ``ConnectionError``/``OSError`` for
    the caller's drain/fallthrough logic."""
    host, _, port = endpoint.rpartition(":")
    out: List[Dict[str, Any]] = []
    with socket.create_connection((host or "127.0.0.1", int(port)),
                                  timeout=timeout) as s:
        f = s.makefile("rwb")
        try:
            for req in requests:
                f.write((json.dumps(req) + "\n").encode())
                f.flush()
                line = f.readline()
                if not line:
                    raise ConnectionError(
                        f"worker {endpoint} closed the connection")
                out.append(json.loads(line))
        finally:
            f.close()
    return out


@dataclasses.dataclass
class NetworkParams:
    """Network-plane knobs (the ``serving.network.*`` config group maps
    onto this; tests construct it directly)."""

    rpc_timeout_s: float = 30.0
    #: health-probe timeout — a worker that cannot answer ``ping``
    #: within this is dead for the round
    probe_timeout_s: float = 2.0
    #: ping cadence: probes cost a fresh TCP connection per endpoint,
    #: and an idle pump loops ~200x/s — probe at most this often
    #: (transport failures on submit/poll mark an endpoint dead
    #: instantly regardless)
    probe_every_s: float = 1.0
    #: pump-thread idle sleep / run_until_idle backoff
    poll_interval_s: float = 0.005
    #: (the 429 token-budget backpressure knobs live in
    #: FrontDoorParams — the HTTP layer owns shedding)
    kv_chunk_bytes: int = 64 * 1024
    #: run the prefill->transfer->decode pipeline when prefill-role
    #: endpoints are present
    disaggregate: bool = False


class ReplicaEndpoint:
    """One worker process behind the network router."""

    def __init__(self, eid: str, endpoint: str, role: str = "mixed",
                 probe_timeout_s: float = 2.0,
                 rpc_timeout_s: float = 30.0):
        self.id = str(eid)
        self.endpoint = str(endpoint)
        self.role = str(role)
        self.probe_timeout_s = float(probe_timeout_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self._dead_reason: Optional[str] = None
        self._probe_round = 0
        self._probe_seen = -1
        self._probe_ok = True

    def rpc(self, requests: List[Dict[str, Any]],
            timeout: Optional[float] = None) -> List[Dict[str, Any]]:
        """One protocol exchange; a transport failure marks the
        endpoint dead (sticky) and re-raises for the caller's drain."""
        try:
            return jsonline_rpc(self.endpoint, requests,
                                timeout=timeout or self.rpc_timeout_s)
        except (ConnectionError, OSError) as e:
            self.mark_dead(f"rpc failed: {e!r}")
            raise

    def new_round(self, gen: int) -> None:
        self._probe_round = gen

    def healthy(self) -> bool:
        if self._dead_reason is not None:
            return False
        if self._probe_seen != self._probe_round:
            self._probe_seen = self._probe_round
            try:
                r = jsonline_rpc(self.endpoint, [{"op": "ping"}],
                                 timeout=self.probe_timeout_s)[0]
                self._probe_ok = bool(r.get("ok"))
                if not self._probe_ok:
                    self._dead_reason = f"ping refused: {r.get('err')}"
            except (ConnectionError, OSError) as e:
                self._probe_ok = False
                self._dead_reason = f"ping failed: {e!r}"
        return self._probe_ok

    def mark_dead(self, reason: str) -> None:
        self._dead_reason = str(reason)

    @property
    def dead_reason(self) -> Optional[str]:
        return self._dead_reason

    def stats(self) -> Dict[str, Any]:
        return self.rpc([{"op": "stats"}])[0].get("v", {})

    def snapshot(self) -> Dict[str, Any]:
        out = {"id": self.id, "endpoint": self.endpoint, "role": self.role,
               "healthy": self._dead_reason is None}
        if self._dead_reason:
            out["dead_reason"] = self._dead_reason
        return out


def discover_endpoints(client: Any,
                       probe_timeout_s: float = 2.0,
                       rpc_timeout_s: float = 30.0
                       ) -> List[ReplicaEndpoint]:
    """Worker endpoints from the rendezvous store's ``serving/srv/*``
    registrations (workers self-register at boot, like the tier-2
    replica servers)."""
    eps: List[ReplicaEndpoint] = []
    for key in sorted(client.keys(SRV_PREFIX)):
        v = client.get(key)
        if not isinstance(v, dict) or "endpoint" not in v:
            continue
        eps.append(ReplicaEndpoint(
            key[len(SRV_PREFIX):], v["endpoint"],
            role=v.get("role", "mixed"), probe_timeout_s=probe_timeout_s,
            rpc_timeout_s=rpc_timeout_s))
    return eps


class NetworkFrontend:
    """submit/stream/cancel over a fleet of worker processes.  The
    surface mirrors :class:`~.frontend.ServingFrontend` (the HTTP front
    door drives either interchangeably)."""

    def __init__(self, endpoints: List[ReplicaEndpoint],
                 params: Optional[ServingParams] = None,
                 net: Optional[NetworkParams] = None,
                 clock=time.monotonic):
        if not endpoints:
            raise ValueError("network front-end needs at least one "
                             "worker endpoint")
        self.endpoints = list(endpoints)
        self.params = params or ServingParams()
        self.net = net or NetworkParams()
        # the front-end owns its endpoints' transport knobs: every
        # construction site (serve CLI, bench, discovery) builds bare
        # ReplicaEndpoints, so the configured serving.network timeouts
        # must land HERE or they are dead config
        for e in self.endpoints:
            e.probe_timeout_s = self.net.probe_timeout_s
            e.rpc_timeout_s = self.net.rpc_timeout_s
        self.clock = clock
        self.metrics = ServingMetrics()
        self._queues: Dict[str, List[ServingHandle]] = {
            c: [] for c in CLASSES}
        #: endpoint id -> in-flight handles placed there
        self._active: Dict[str, List[ServingHandle]] = {}
        self._uid = 0
        self._round = 0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._drained: set = set()
        #: (block_size, num_blocks, max_seq_len) learned from the first
        #: reachable worker — local request validation without an RPC
        #: per submit
        self._geometry: Optional[Dict[str, int]] = None
        #: (round, rate) memo — the hit rate costs one stats RPC per
        #: endpoint, far too much for every pump round
        self._hit_rate_memo = (-1, 0.0)
        self._hit_rate_every = 50
        #: probe generation + cadence stamp (see net.probe_every_s)
        self._probe_gen = 0
        self._last_probe_mono = 0.0

    # -- fleet views ---------------------------------------------------------

    def _serving_endpoints(self) -> List[ReplicaEndpoint]:
        """Endpoints that accept whole requests (prefill-only ones
        serve the disaggregation pipeline, never plain submits)."""
        return [e for e in self.endpoints
                if e.role != "prefill" and e.healthy()]

    def _prefill_endpoints(self) -> List[ReplicaEndpoint]:
        return [e for e in self.endpoints
                if e.role == "prefill" and e.healthy()]

    def healthy_count(self) -> int:
        return sum(1 for e in self.endpoints if e.dead_reason is None)

    def add_endpoint(self, ep: ReplicaEndpoint) -> None:
        """Adopt a new worker endpoint live (autoscaler scale-up /
        replacement).  The id must be FRESH: the drain ledger
        (``_drained``) is keyed by endpoint id, so reusing a dead
        worker's id would silently skip the new worker's future drain."""
        with self._lock:
            if any(e.id == ep.id for e in self.endpoints):
                raise ValueError(
                    f"endpoint id {ep.id!r} already known (dead ids "
                    f"stay in the drain ledger — spawn replacements "
                    f"under fresh ids)")
            # the front-end owns transport knobs (see __init__)
            ep.probe_timeout_s = self.net.probe_timeout_s
            ep.rpc_timeout_s = self.net.rpc_timeout_s
            self.endpoints.append(ep)
        log_dist(f"serving: endpoint {ep.id} ({ep.role}) at "
                 f"{ep.endpoint} joined the fleet")

    def remove_endpoint(self, eid: str,
                        reason: str = "scale_down") -> bool:
        """Kill-safe scale-down: mark the endpoint dead so the pump's
        existing drain path re-queues its in-flight requests splice-
        exact — the SAME path a crashed worker takes, so scale-down
        cannot lose tokens a crash wouldn't.  Stopping the worker
        process is the caller's job (after this returns, nothing new
        lands on it)."""
        with self._lock:
            ep = self._endpoint_by_id(str(eid))
            if ep is None:
                return False
            if ep.dead_reason is None:
                ep.mark_dead(str(reason))
        return True

    def _geom(self) -> Optional[Dict[str, int]]:
        if self._geometry is None:
            for ep in self.endpoints:
                if ep.dead_reason is not None:
                    continue
                try:
                    s = ep.stats()
                except (ConnectionError, OSError):
                    continue
                if "block_size" in s:
                    self._geometry = {
                        "block_size": int(s["block_size"]),
                        "num_blocks": int(s["num_blocks"]),
                        "max_seq_len": int(s["max_seq_len"])}
                    break
        return self._geometry

    # -- request surface ------------------------------------------------------

    def validate(self, prompt: List[int], max_new_tokens: int) -> None:
        """Front-door validation without a worker round-trip: the
        structural checks always, the pool-geometry checks once a
        worker has told us its cache shape."""
        if not isinstance(prompt, (list, tuple)) or not prompt:
            raise ValueError("prompt: must be a non-empty token list")
        if not all(isinstance(t, int) and not isinstance(t, bool)
                   for t in prompt):
            raise ValueError("prompt: every token must be an integer")
        if int(max_new_tokens) <= 0:
            raise ValueError(
                f"max_new_tokens: must be >= 1, got {max_new_tokens}")
        g = self._geom()
        if g is not None:
            total = len(prompt) + int(max_new_tokens)
            if total > g["max_seq_len"]:
                raise ValueError(
                    f"request of {total} tokens exceeds max_seq_len "
                    f"{g['max_seq_len']}")
            need = -(-total // g["block_size"])
            if need > g["num_blocks"] - 1:
                raise ValueError(
                    f"request needs {need} pages but the pool only has "
                    f"{g['num_blocks'] - 1}")

    def queued_tokens(self, klass: str) -> int:
        with self._lock:
            return sum(len(h.prompt) + h.max_new_tokens
                       for h in self._queues.get(klass, ()))

    def submit(self, prompt: List[int], max_new_tokens: int = 64,
               klass: str = "interactive",
               trace_id: Optional[str] = None,
               sampled: Optional[bool] = None) -> ServingHandle:
        if klass not in CLASSES:
            raise ValueError(f"klass: unknown latency class {klass!r} "
                             f"(one of {', '.join(CLASSES)})")
        self.validate(prompt, max_new_tokens)
        with self._lock:
            if not any(e.dead_reason is None for e in self.endpoints
                       if e.role != "prefill"):
                raise NoHealthyReplicaError(
                    "submit rejected: no live serving worker "
                    + "; ".join(f"{e.id}: {e.dead_reason}"
                                for e in self.endpoints))
            h = ServingHandle(self._uid, list(prompt), int(max_new_tokens),
                              klass, self.clock(), self,
                              self.params.stream_buffer)
            self._uid += 1
            from .tracing import get_request_log, mint_trace_id

            h.trace_id = trace_id or mint_trace_id()
            h.record = get_request_log().start(
                h.trace_id, h.uid, klass, len(prompt),
                int(max_new_tokens), sampled=sampled)
            h.record.event("submitted")
            self._queues[klass].append(h)
            self.metrics.inc("submitted")
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                f"serving/{klass}_submitted",
                help="requests submitted per latency class")
            return h

    def cancel(self, handle: ServingHandle) -> None:
        with self._lock:
            if handle.status == "queued":
                try:
                    self._queues[handle.klass].remove(handle)
                except ValueError:
                    pass
                self.metrics.inc("cancelled")
                handle._finish("cancelled")
            elif handle.status == "admitting":
                # mid-pipeline: the admitting pump finalizes the
                # cancel (it may still have to tear down a remote
                # seat) — consumers get their _DONE from there
                handle._cancel_requested = True
                return
            elif handle.status == "running":
                ep = self._endpoint_by_id(handle.replica_id)
                if ep is not None:
                    try:
                        ep.rpc([{"op": "cancel",
                                 "rid": getattr(handle, "rid", "")}])
                    except (ConnectionError, OSError) as e:
                        warn_once("serving/net-cancel",
                                  f"remote cancel failed ({e!r})")
                    lst = self._active.get(ep.id)
                    if lst is not None and handle in lst:
                        lst.remove(handle)
                self.metrics.inc("cancelled")
                handle._finish("cancelled")

    # -- the pump -------------------------------------------------------------

    def pump(self) -> int:
        """One network serving round: probe, drain dead endpoints,
        admit (colocated or disaggregated), poll token streams.
        Returns tokens delivered — 0 means idle.

        Lock discipline: the health probes, the token polls, and the
        disaggregated admission pipeline (whose KV-page transfer can
        take seconds) run OUTSIDE ``self._lock``, so one stalled peer
        cannot block ``submit``/``cancel``/``queued_tokens`` (every
        front-door request) behind the pump.  Plain-mode admission RPCs
        still run under the lock: a worker ``submit`` is host-side
        bookkeeping, answered in microseconds, and the first transport
        failure marks the endpoint dead."""
        with self._lock:
            self._round += 1
        self._maybe_probe()
        claim = None
        with self._lock:
            self._drain_dead()
            # healthy() below is memoized for this round — no I/O here
            if not self._serving_endpoints():
                if any(self._queues.values()):
                    self._fail_pending_no_replica()
                return 0
            if self.net.disaggregate and self._prefill_endpoints():
                claim = self._claim_head()
            else:
                self._admit_all()
        if claim is not None:
            self._admit_claimed(claim)
        n = self._poll_all()
        with self._lock:
            self._drain_dead()  # a poll may have found a dead socket
            last_round, rate = self._hit_rate_memo
            if self._round - last_round >= self._hit_rate_every:
                rate = self._aggregate_hit_rate()
                self._hit_rate_memo = (self._round, rate)
            self.metrics.publish(
                {c: len(q) for c, q in self._queues.items()}, rate)
        return n

    def _maybe_probe(self) -> None:
        """Cadence-gated fleet ping (a fresh TCP connection per
        endpoint — see ``net.probe_every_s``); runs WITHOUT the main
        lock.  Only the pump/run_until_idle driver calls this."""
        now = time.monotonic()
        if now - self._last_probe_mono < self.net.probe_every_s:
            return
        self._last_probe_mono = now
        self._probe_gen += 1
        for ep in self.endpoints:
            ep.new_round(self._probe_gen)
            ep.healthy()

    def run_until_idle(self, max_rounds: int = 100_000) -> None:
        for _ in range(max_rounds):
            # probe (not just the sticky flag): pending work with the
            # whole fleet dead must raise promptly, like the
            # in-process front-end — consumers unblock first.  Between
            # probe cadences, transport failures inside pump() mark
            # endpoints dead and the next iteration raises.
            self._maybe_probe()
            with self._lock:
                pending = (any(self._queues.values())
                           or any(self._active.values()))
                if not pending:
                    return
                if not any(e.role != "prefill"
                           and e.dead_reason is None
                           for e in self.endpoints):
                    self._drain_dead()
                    self._fail_pending_no_replica()
                    raise NoHealthyReplicaError(
                        "pending serving work but no live worker")
            if self.pump() == 0:
                time.sleep(self.net.poll_interval_s)
        raise RuntimeError(f"run_until_idle: no quiescence in "
                           f"{max_rounds} rounds")

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._serve_loop, daemon=True,
                name="ds-serving-net-frontend")
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            self._stop.set()
            t.join(timeout=10.0)

    def close(self) -> None:
        self.stop()

    def _serve_loop(self) -> None:
        log_dist("network serving front-end loop started")
        while not self._stop.is_set():
            try:
                n = self.pump()
            except Exception as e:
                warn_once("serving/net-pump", f"pump error ({e!r})")
                n = 0
            if n == 0:
                self._stop.wait(self.net.poll_interval_s)

    # -- internals (lock held) ------------------------------------------------

    def _endpoint_by_id(self, eid: Optional[str]
                        ) -> Optional[ReplicaEndpoint]:
        for e in self.endpoints:
            if e.id == eid:
                return e
        return None

    def _outstanding(self, ep: ReplicaEndpoint) -> int:
        with self._lock:  # reentrant: also called from locked paths
            return sum(len(h.prompt) + h.max_new_tokens - h.consumed
                       for h in self._active.get(ep.id, ()))

    def _requeue(self, h: ServingHandle) -> None:
        """Replica death / torn pipeline: replay the request from its
        prompt elsewhere — delivery resumes past ``h.delivered``."""
        self._reset_replay_cursor(h)
        self._queues[h.klass].insert(0, h)

    def _reset_replay_cursor(self, h: ServingHandle) -> None:
        if h.record is not None:
            h.record.event("replayed", from_replica=h.replica_id,
                           delivered=h.delivered)
        h.replays += 1
        h.consumed = 0
        h.status = "queued"
        h.replica_id = None
        # the dead pipeline's attribution must not leak into the
        # replay (which may run colocated): a stale _transfer_done_at
        # would stamp death-detection + replay time as "decode_ms"
        h.ttft_breakdown = None
        h._transfer_done_at = None

    def _drain_dead(self) -> None:
        for ep in self.endpoints:
            if ep.dead_reason is None or ep.id in self._drained:
                continue
            self._drained.add(ep.id)
            moved = 0
            for h in self._active.pop(ep.id, []):
                if h.record is not None:
                    h.record.event("replica_drained", replica=ep.id,
                                   reason=str(ep.dead_reason)[:120])
                self._requeue(h)
                moved += 1
            if moved:
                self.metrics.inc("requeued_replica_death", moved)
            log_dist(f"serving: worker {ep.id} drained "
                     f"({ep.dead_reason}); {moved} requests re-queued")

    def _fail_pending_no_replica(self) -> None:
        err = NoHealthyReplicaError(
            "all serving workers dead: "
            + "; ".join(f"{e.id}: {e.dead_reason}"
                        for e in self.endpoints))
        n = 0
        for q in self._queues.values():
            for h in q:
                self.metrics.inc("failed")
                h._finish("failed", err)
                n += 1
            q.clear()
        log_dist(f"serving: failed {n} pending requests — "
                 f"no live worker")

    def _admit_all(self) -> None:
        for klass in CLASSES:
            q = self._queues[klass]
            while q:
                if not self._try_admit(q[0]):
                    break
                q.pop(0)
            if q and any(self._active.values()):
                # strict priority: a blocked class head blocks lower
                # classes while ANY work is in flight (its completions
                # free the capacity the head waits on)
                break

    def _try_admit(self, h: ServingHandle) -> bool:
        h.rid = f"{h.uid}.{h.replays}"
        return self._admit_plain(h)

    def _claim_head(self) -> Optional[ServingHandle]:
        """Disaggregated-mode admission: pop the highest-class head
        under the lock, run its pipeline OUTSIDE it (lock held by the
        caller)."""
        for klass in CLASSES:
            q = self._queues[klass]
            if q:
                h = q.pop(0)
                h.status = "admitting"
                return h
        return None

    def _admit_claimed(self, h: ServingHandle) -> None:
        """Run the claimed head's admission with no lock held; seat /
        terminal-fail / re-queue under short lock grabs at the end.  A
        ``cancel`` issued mid-pipeline is finalized here."""
        h.rid = f"{h.uid}.{h.replays}"
        if self.net.disaggregate and self._prefill_endpoints():
            ok = self._admit_disagg(h)
        else:
            ok = self._admit_plain(h)
        with self._lock:
            if getattr(h, "_cancel_requested", False) \
                    and h.status in ("admitting", "queued", "running"):
                ep = self._endpoint_by_id(h.replica_id)
                if ep is not None:
                    try:
                        ep.rpc([{"op": "cancel", "rid": h.rid}])
                    except (ConnectionError, OSError) as e:
                        warn_once("serving/net-cancel",
                                  f"remote cancel failed ({e!r})")
                    lst = self._active.get(ep.id)
                    if lst is not None and h in lst:
                        lst.remove(h)
                self.metrics.inc("cancelled")
                h._finish("cancelled")
                return
            if not ok and h.status in ("admitting", "queued"):
                # capacity / torn pipeline: back to the class front for
                # the next round ("queued" = a torn pipeline already
                # reset the replay cursor)
                h.status = "queued"
                self._queues[h.klass].insert(0, h)

    def _trace_fields(self, h: ServingHandle) -> Dict[str, Any]:
        """The trace context an RPC carries: the id plus the effective
        sampling verdict (head-based, forced once anomalous)."""
        if h.trace_id is None:
            return {}
        out: Dict[str, Any] = {"trace": h.trace_id}
        if h.record is not None:
            out["sampled"] = h.record.propagate_sampled()
        return out

    def _admit_plain(self, h: ServingHandle) -> bool:
        # cheap local budget screen FIRST: a saturated fleet (the
        # normal overload state) must cost zero match RPCs per retry
        serving = self._serving_endpoints()
        candidates = [
            ep for ep in serving
            if (self._outstanding(ep) + len(h.prompt) + h.max_new_tokens
                <= self.params.max_outstanding_tokens)]
        if serving and not candidates:
            # every live worker is over its outstanding-token budget —
            # the network plane's one locally-attributable reason
            from .metrics import count_admission_reject

            count_admission_reject(self.metrics, "token_budget")
        # then prefix affinity (one match RPC per surviving candidate)
        # -> least outstanding -> stable id: the PR-8 placement order
        scored = []
        for ep in candidates:
            affinity = self._affinity_of(ep, h.prompt)
            if affinity < self.params.affinity_min_tokens:
                affinity = 0  # one hot block must not pin placement
            scored.append((-affinity, self._outstanding(ep), ep.id, ep))
        for ep in [t[-1] for t in sorted(scored, key=lambda t: t[:3])]:
            try:
                r = ep.rpc([dict({"op": "submit", "rid": h.rid,
                                  "prompt": h.prompt,
                                  "max_new_tokens": h.max_new_tokens,
                                  "klass": h.klass},
                                 **self._trace_fields(h))])[0]
            except (ConnectionError, OSError):
                continue
            if r.get("ok"):
                self._seat(h, ep)
                return True
            if r.get("kind") == "validation":
                self._fail_terminal(h, ValueError(str(r.get("err"))))
                return True  # leaves the queue — terminally invalid
        if h.record is not None:
            h.record.note_blocked_admission()
        return False

    def _seat(self, h: ServingHandle, ep: ReplicaEndpoint) -> None:
        with self._lock:  # reentrant: also called from locked paths
            h.status = "running"
            h.replica_id = ep.id
            h.admitted_at = self.clock()
            if h.record is not None:
                h.record.event("admitted", replica=ep.id)
            self._active.setdefault(ep.id, []).append(h)

    def _fail_terminal(self, h: ServingHandle, err: Exception) -> None:
        with self._lock:
            self.metrics.inc("failed")
            h._finish("failed", err)

    def _admit_disagg(self, h: ServingHandle) -> bool:
        """prefill replica -> KV-page stream -> decode replica.  The
        first token is delivered as soon as prefill returns; a torn
        pipeline re-queues the handle and the replay splices."""
        if h.max_new_tokens < 2:
            # a one-token request IS its prefill — nothing to
            # disaggregate; the prefill worker's +1-token parking
            # budget (put(prompt, 2)) would also push a boundary-valid
            # request (len+1 == max_seq_len) over the pool's limits
            return self._admit_plain(h)
        pres = sorted(
            self._prefill_endpoints(),
            key=lambda e: (-self._affinity_of(e, h.prompt), e.id))
        decs = self._serving_endpoints()
        if not pres or not decs:
            # prefill fleet gone: colocated fallback keeps serving
            return self._admit_plain(h)
        pre = pres[0]
        import time as _time

        p0 = _time.perf_counter()
        try:
            r = pre.rpc([dict({"op": "prefill", "rid": h.rid,
                               "prompt": h.prompt,
                               "max_new_tokens": h.max_new_tokens,
                               "klass": h.klass},
                              **self._trace_fields(h))])[0]
        except (ConnectionError, OSError):
            return False
        if not r.get("ok"):
            if r.get("kind") == "validation":
                self._fail_terminal(h, ValueError(str(r.get("err"))))
                return True
            return False
        if h.record is not None:
            # the phase as THIS lane saw it (RPC-inclusive); the
            # prefill worker's own lane carries the engine-side number
            h.record.phase("prefill_rpc", start_ts=p0, replica=pre.id,
                           prefill_ms=r.get("prefill_ms"))
        first = int(r["first_token"])
        adopted = None
        for dec in sorted(decs, key=lambda e: (self._outstanding(e),
                                               e.id)):
            try:
                rb = dec.rpc([dict({"op": "adopt_begin", "rid": h.rid,
                                    "prompt": h.prompt,
                                    "max_new_tokens": h.max_new_tokens,
                                    "first_token": first,
                                    "klass": h.klass},
                                   **self._trace_fields(h))])[0]
            except (ConnectionError, OSError):
                continue
            if rb.get("ok"):
                adopted = (dec, list(rb.get("need", [])))
                break
            if rb.get("kind") == "validation":
                self._release_prefill(pre, h.rid)
                self._fail_terminal(h, ValueError(str(rb.get("err"))))
                return True
        if adopted is None:
            self._release_prefill(pre, h.rid)
            return False
        dec, need = adopted
        # TTFT is prefill-bound: the first token goes out NOW, the
        # page stream rides behind it
        if h.consumed == 0:
            h.consumed = 1
            if h.delivered < 1:
                if h.first_token_at is None:
                    h.first_token_at = self.clock()
                    with self._lock:
                        self.metrics.record_ttft(h.klass, h.ttft_ms,
                                                 ref=h.trace_id)
                h.delivered = 1
                if h.record is not None:
                    h.record.event("first_token", replica=pre.id,
                                   disagg=True)
                    h.record.token()
                h._push(first)
        t1 = self.clock()
        x0 = _time.perf_counter()
        try:
            if need:
                kv = pre.rpc([dict({"op": "kv_push", "rid": h.rid,
                                    "to": dec.endpoint, "pages": need,
                                    "chunk_bytes":
                                        self.net.kv_chunk_bytes},
                                   **self._trace_fields(h))],
                             timeout=self.net.rpc_timeout_s)[0]
                if not kv.get("ok"):
                    raise RuntimeError(f"kv_push refused: {kv.get('err')}")
            dc = dec.rpc([{"op": "adopt_commit", "rid": h.rid}])[0]
            if not dc.get("ok"):
                raise RuntimeError(
                    f"adopt_commit refused: {dc.get('err')}")
        except (ConnectionError, OSError, RuntimeError) as e:
            warn_once("serving/disagg-torn",
                      f"disaggregated pipeline torn ({e!r}); replaying")
            try:
                dec.rpc([{"op": "adopt_abort", "rid": h.rid}])
            except (ConnectionError, OSError):
                pass
            self._release_prefill(pre, h.rid)
            self._requeue_inline(h)
            return False
        self._release_prefill(pre, h.rid)
        t2 = self.clock()
        h.ttft_breakdown = {
            "prefill_ms": float(r.get("prefill_ms", 0.0)),
            "transfer_ms": round((t2 - t1) * 1e3, 3)}
        h._transfer_done_at = t2
        if h.record is not None:
            h.record.phase("transfer", start_ts=x0,
                           pages=len(need), from_replica=pre.id,
                           to_replica=dec.id)
        self._seat(h, dec)
        with self._lock:
            self.metrics.record_disagg(h.ttft_breakdown)
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "serving/disagg_requests_total",
            help="requests served through disaggregated prefill/decode")
        return True

    def _requeue_inline(self, h: ServingHandle) -> None:
        """A torn pipeline leaves the handle AT the queue head (it was
        never popped) — only reset its replay cursor."""
        self._reset_replay_cursor(h)

    def _affinity_of(self, ep: ReplicaEndpoint, prompt: List[int]) -> int:
        if len(prompt) < self.params.affinity_min_tokens:
            return 0
        try:
            r = ep.rpc([{"op": "match", "prompt": prompt}])[0]
            return int(r.get("v", 0) or 0)
        except (ConnectionError, OSError):
            return 0

    def _release_prefill(self, pre: ReplicaEndpoint, rid: str) -> None:
        try:
            pre.rpc([{"op": "release", "rid": rid}])
        except (ConnectionError, OSError) as e:
            warn_once("serving/prefill-release",
                      f"prefill release failed ({e!r})")

    def _poll_all(self) -> int:
        """Poll every endpoint's in-flight streams.  Snapshot under
        the lock, RPC outside it (a wedged peer must not stall the
        submit path), re-apply under it — a reply for a handle that
        was cancelled/re-queued mid-RPC is stale and dropped (the rid
        or cursor no longer matches)."""
        with self._lock:
            batches = []
            for ep in self.endpoints:
                handles = self._active.get(ep.id)
                if handles:
                    batches.append(
                        (ep, [(h, h.rid, h.consumed) for h in handles]))
        polled = []
        for ep, items in batches:
            reqs = [{"op": "poll", "rid": rid, "cursor": cur}
                    for _, rid, cur in items]
            try:
                polled.append((ep, items, ep.rpc(reqs)))
            except (ConnectionError, OSError):
                continue  # dead: the trailing _drain_dead re-queues
        n = 0
        with self._lock:
            for ep, items, replies in polled:
                handles = self._active.get(ep.id, [])
                for (h, rid, cursor), r in zip(items, replies):
                    if (h not in handles or h.rid != rid
                            or h.consumed != cursor):
                        continue  # moved on while the RPC was in flight
                    if not r.get("ok"):
                        if r.get("kind") == "unknown_rid":
                            # worker restarted underneath us: replay
                            handles.remove(h)
                            self._requeue(h)
                        continue
                    n += self._deliver_remote(h, r)
                    if r.get("done"):
                        handles.remove(h)
                        self._finish_remote(h, r)
        return n

    def _deliver_remote(self, h: ServingHandle, r: Dict[str, Any]) -> int:
        delivered = 0
        for tok in r.get("tokens", ()):
            h.consumed += 1
            if h.consumed > h.delivered:
                if h.first_token_at is None:
                    h.first_token_at = self.clock()
                    self.metrics.record_ttft(h.klass, h.ttft_ms,
                                             ref=h.trace_id)
                    if h.record is not None:
                        h.record.event("first_token",
                                       replica=h.replica_id)
                bd = h.ttft_breakdown
                if bd is not None and "decode_ms" not in bd:
                    t0 = getattr(h, "_transfer_done_at", None)
                    if t0 is not None:
                        bd["decode_ms"] = round(
                            (self.clock() - t0) * 1e3, 3)
                        self.metrics.record_disagg(
                            {"decode_ms": bd["decode_ms"]}, count=False)
                        if h.record is not None:
                            h.record.phase(
                                "decode_first_burst",
                                dur_ms=bd["decode_ms"],
                                replica=h.replica_id)
                h.delivered += 1
                if h.record is not None:
                    h.record.token()
                h._push(int(tok))
                delivered += 1
        return delivered

    def _finish_remote(self, h: ServingHandle, r: Dict[str, Any]) -> None:
        status = str(r.get("status", "done"))
        if status == "done":
            h.finished_at = self.clock()
            gen_s = (h.finished_at - (h.first_token_at or h.finished_at))
            self.metrics.record_completion(h.klass, h.delivered, gen_s)
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                f"serving/{h.klass}_tokens", v=h.delivered,
                help="generated tokens delivered per latency class")
            h._finish("done")
        elif status == "cancelled":
            self.metrics.inc("cancelled")
            h._finish("cancelled")
        else:
            self.metrics.inc("failed")
            h._finish("failed",
                      RuntimeError(str(r.get("error", "remote failure"))))

    # -- introspection --------------------------------------------------------

    def _aggregate_hit_rate(self) -> float:
        hits = looks = 0
        for ep in self.endpoints:
            if ep.dead_reason is not None:
                continue
            try:
                p = ep.stats().get("prefix")
            except (ConnectionError, OSError):
                continue
            if p:
                hits += int(p.get("hit_tokens", 0))
                looks += int(p.get("lookup_tokens", 0))
        return hits / looks if looks else 0.0

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = dict(self.metrics.snapshot())
            out["queues"] = {c: len(q) for c, q in self._queues.items()}
            out["queued_tokens"] = {
                c: sum(len(h.prompt) + h.max_new_tokens for h in q)
                for c, q in self._queues.items()}
            out["endpoints"] = [e.snapshot() for e in self.endpoints]
            out["active"] = {eid: len(hs)
                             for eid, hs in self._active.items() if hs}
            last_round, rate = self._hit_rate_memo
        if last_round < 0:
            # never pumped: pay the stats RPCs once; after that the
            # pump's memo keeps /v1/metrics scrapes RPC-free (a wedged
            # worker must not stall the metrics endpoint 30s/scrape)
            rate = self._aggregate_hit_rate()
            with self._lock:
                self._hit_rate_memo = (0, rate)
        out["prefix_hit_rate"] = round(rate, 4)
        return out
