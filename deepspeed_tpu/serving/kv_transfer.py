"""Page-granular KV transfer — the disaggregated prefill/decode wire.

Disaggregation (ISSUE 14 tentpole c) splits a request across two
replica processes: a *prefill* replica computes the prompt's KV and the
first token, then the finished KV pages stream DIRECTLY to the *decode*
replica (peer-to-peer — the bytes never transit the front door or the
rendezvous store), which seats the request and decodes from the
received pages.  This module is the wire format and the two pool
boundaries:

* :func:`page_payload` — one pool page as transportable bytes.  Real
  engines ship the page's K and V planes across all layers
  (``pool[kv][:, block]``); the host-only synthetic engine ships a
  deterministic token-derived payload so the transfer machinery
  (chunking, checksum gates, rejection) is exercised end-to-end with no
  device.
* :func:`push_pages` — the client side of the decode worker's
  ``kv_page_begin`` / ``kv_page_chunk`` / ``kv_page_commit`` ops
  (modeled on the tier-2 replica transport): each page is chunked
  base64 with its OWN sha256, verified at the receiver before anything
  touches the pool — a torn or tampered page is rejected
  (``serving/kv_transfer_rejects_total``), never decoded from.
* :func:`inject_pages` — write verified payloads into the adopting
  engine's pool at the reserved block ids.

Counters: ``serving/kv_transfer_pages_total`` / ``_bytes_total`` on the
sending side, ``_received_total`` / ``_rejects_total`` on the receiver,
``serving/kv_transfer_skipped_pages_total`` for pages the decode-side
prefix trie already held (the cluster-wide KV tier at work).
"""

from __future__ import annotations

import base64
import hashlib
from typing import Any, Dict, List, Optional

import numpy as np

DEFAULT_KV_CHUNK_BYTES = 64 * 1024


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def page_payload(engine: Any, prompt: List[int], blocks: List[int],
                 page_index: int) -> Dict[str, Any]:
    """Serialize one KV page of a prefilled request.

    Returns ``{"raw": bytes, "sha256": str, "dtype": str, "shape":
    [...], "synthetic": bool}`` — ``raw`` is K-plane bytes followed by
    V-plane bytes (equal length, concatenated; the receiver splits at
    the midpoint)."""
    bs = int(engine.cache_config.block_size)
    pool = getattr(engine, "pool", None)
    if pool is None:
        # synthetic engine: no device pool — a deterministic payload
        # derived from the page's tokens keeps the checksum gate real
        toks = prompt[page_index * bs:(page_index + 1) * bs]
        arr = np.zeros((bs,), np.int32)
        arr[:len(toks)] = toks
        raw = arr.tobytes()
        return {"raw": raw + raw, "sha256": _sha256(raw + raw),
                "dtype": "int32", "shape": [bs], "synthetic": True}
    block = blocks[page_index]
    k = np.asarray(pool["k"][:, block])
    v = np.asarray(pool["v"][:, block])
    raw = k.tobytes() + v.tobytes()
    return {"raw": raw, "sha256": _sha256(raw), "dtype": str(k.dtype),
            "shape": list(k.shape), "synthetic": False}


def inject_pages(engine: Any, blocks: List[int],
                 staged: Dict[int, Dict[str, Any]]) -> None:
    """Write verified page payloads into ``engine.pool`` at the
    reserved block ids (``staged`` maps page index -> payload dict with
    ``raw``/``dtype``/``shape``).  One batched scatter per plane — a
    per-page functional ``.at[].set`` would copy the whole multi-GB
    pool once per page, under the adopting front-end's lock.  Synthetic
    payloads are content-free bookkeeping — nothing to write."""
    pool = getattr(engine, "pool", None)
    if pool is None or not staged:
        return
    import jax.numpy as jnp

    ids: List[int] = []
    ks: List[np.ndarray] = []
    vs: List[np.ndarray] = []
    for page_index, p in sorted(staged.items()):
        if p.get("synthetic"):
            continue
        raw = p["raw"]
        half = len(raw) // 2
        dt = np.dtype(p["dtype"])
        shape = tuple(int(s) for s in p["shape"])
        ids.append(blocks[page_index])
        ks.append(np.frombuffer(raw[:half], dtype=dt).reshape(shape))
        vs.append(np.frombuffer(raw[half:], dtype=dt).reshape(shape))
    if not ids:
        return
    idx = jnp.asarray(ids)
    # page planes are [L, bs, kh, hd]; stacked on a new axis 1 they
    # line up with pool[:, idx] -> [L, n, bs, kh, hd]
    pool["k"] = pool["k"].at[:, idx].set(
        jnp.asarray(np.stack(ks, axis=1)))
    pool["v"] = pool["v"].at[:, idx].set(
        jnp.asarray(np.stack(vs, axis=1)))


def push_pages(rpc_fn, rid: str, payloads: Dict[int, Dict[str, Any]],
               chunk_bytes: int = DEFAULT_KV_CHUNK_BYTES,
               timeout: Optional[float] = None,
               trace_id: Optional[str] = None) -> Dict[str, int]:
    """Stream page payloads to a decode worker through ``rpc_fn`` (one
    ``rpc(requests) -> replies`` callable bound to the target
    endpoint).  Each page rides its own begin/chunk*/commit triplet so
    the receiver's sha256 gate is PER PAGE — one corrupt page names
    itself instead of poisoning the whole transfer.  ``trace_id``
    stamps each page's ``begin`` message so a packet capture or a
    receiver-side log attributes the transfer to its request (ISSUE
    15 context propagation).  Raises ``RuntimeError`` on refusal
    (checksum mismatch, unknown rid)."""
    step = max(1, int(chunk_bytes))
    reqs: List[Dict[str, Any]] = []
    total = 0
    for page_index, p in sorted(payloads.items()):
        b64 = base64.b64encode(p["raw"]).decode("ascii")
        chunks = [b64[i:i + step] for i in range(0, len(b64), step)] \
            or [""]
        begin = {"op": "kv_page_begin", "rid": rid, "page": page_index,
                 "n": len(chunks), "sha256": p["sha256"],
                 "nbytes": len(p["raw"]), "dtype": p["dtype"],
                 "shape": p["shape"],
                 "synthetic": bool(p.get("synthetic"))}
        if trace_id:
            begin["trace"] = str(trace_id)
        reqs.append(begin)
        reqs += [{"op": "kv_page_chunk", "rid": rid, "page": page_index,
                  "i": i, "v": ch} for i, ch in enumerate(chunks)]
        reqs.append({"op": "kv_page_commit", "rid": rid,
                     "page": page_index})
        total += len(p["raw"])
    replies = rpc_fn(reqs) if timeout is None else rpc_fn(reqs, timeout)
    for r in replies:
        if not r.get("ok"):
            raise RuntimeError(
                f"kv transfer for {rid} refused: {r.get('err')}")
    from ..telemetry import get_telemetry

    tel = get_telemetry()
    tel.inc_counter("serving/kv_transfer_pages_total", v=len(payloads),
                    help="KV pages streamed prefill -> decode")
    tel.inc_counter("serving/kv_transfer_bytes_total", v=total,
                    help="raw KV bytes streamed prefill -> decode")
    return {"pages": len(payloads), "bytes": total}


class PageStager:
    """Receiver-side assembly of one in-flight KV transfer: chunked
    base64 per page, committed only when the page's sha256 matches.
    All calls are made under the owning worker's lock."""

    def __init__(self) -> None:
        #: page index -> {"n", "sha256", "chunks", "dtype", "shape"}
        self._inflight: Dict[int, Dict[str, Any]] = {}
        #: page index -> verified payload ({"raw", "dtype", ...})
        self.ready: Dict[int, Dict[str, Any]] = {}

    def begin(self, page: int, meta: Dict[str, Any]) -> None:
        self._inflight[page] = {
            "n": int(meta["n"]), "sha256": str(meta["sha256"]),
            "dtype": str(meta.get("dtype", "int32")),
            "shape": list(meta.get("shape", [])),
            "synthetic": bool(meta.get("synthetic")),
            "chunks": {}}

    def chunk(self, page: int, i: int, v: str) -> None:
        ent = self._inflight.get(page)
        if ent is None:
            raise ValueError(f"kv chunk for page {page} with no begin")
        ent["chunks"][int(i)] = str(v)

    def commit(self, page: int) -> int:
        """Verify + stage the page; returns its raw byte count.
        Raises ``ValueError`` on a checksum mismatch (the caller maps
        it to a refused reply + reject counter) — a failed page stays
        un-staged and may be retried."""
        ent = self._inflight.pop(page, None)
        if ent is None:
            raise ValueError(f"kv commit for page {page} with no begin")
        b64 = "".join(ent["chunks"].get(i, "")
                      for i in range(ent["n"]))
        raw = base64.b64decode(b64)
        if _sha256(raw) != ent["sha256"]:
            raise ValueError(
                f"kv page {page} failed the transfer checksum gate "
                f"(sha256 {_sha256(raw)[:12]}… != expected "
                f"{ent['sha256'][:12]}…) — page rejected")
        self.ready[page] = {"raw": raw, "dtype": ent["dtype"],
                            "shape": ent["shape"],
                            "synthetic": ent["synthetic"]}
        return len(raw)
