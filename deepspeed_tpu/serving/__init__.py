"""Production serving plane — paged prefix-sharing KV cache, SLO-aware
streaming front-end, multi-replica routing (ROADMAP item 1, the
DeepSpeed-FastGen/MII lineage's service layer, arXiv 2401.08671; prefix
sharing after vLLM's PagedAttention, arXiv 2309.06180).

Layering (each importable on its own):

* :mod:`.prefix_cache` — refcounted page allocator + hash-trie prefix
  index over ``inference/v2``'s block pool.
* :mod:`.scheduler` — :class:`ServingScheduler`, the v2 ragged planner
  with prefix-shared reservations and preemptible decode slots.
* :mod:`.frontend` — submit/stream/cancel, latency-class queues,
  admission control, preemption, replica drain.
* :mod:`.router` — replica health + prefix-affine least-outstanding
  routing.
* :mod:`.synthetic` — the host-only engine for tests and dry-runs.

``build_serving_frontend`` assembles the real thing: N v2 engine
replicas over a model, each with its own KV pool registered in the
memory ledger under distinct per-replica keys.
"""

from __future__ import annotations

from typing import Any, List, Optional

from .frontdoor import (CLASS_HEADER, FrontDoor, FrontDoorParams,
                        door_params_from_config)
from .frontend import (NoHealthyReplicaError, ServingFrontend,
                       ServingHandle, ServingParams)
from .metrics import (CLASSES, LatencyTracker, RequestLog, RequestRecord,
                      ServingMetrics, head_sampled)
from .autoscaler import Autoscaler, ScalingDecision
from .replay import (read_access_log, replay_report, replayable_records,
                     run_replay, synthesize_diurnal_log)
from .slo import (SLOMonitor, SLOObjective, objectives_from_config,
                  render_slo_table, sample_from_rollup,
                  sample_from_snapshot, slo_rows_from_rollup)
from .tracing import (REQUESTS_PREFIX, TRACE_HEADER, AccessLog,
                      assemble_timeline, configure_request_log,
                      configure_tracing_from_config, fetch_request_docs,
                      find_trace, get_request_log, mint_trace_id,
                      render_timeline, sanitize_trace_id,
                      timeline_chrome_trace)
from .prefix_cache import PrefixCache, RefcountedBlockAllocator
from .remote import (NetworkFrontend, NetworkParams, ReplicaEndpoint,
                     discover_endpoints, jsonline_rpc)
from .router import Replica, ReplicaRouter
from .scheduler import ServingScheduler
from .synthetic import FakeClock, SyntheticEngine, synthetic_token
from .worker import SRV_PREFIX, ServingWorker

__all__ = [
    "AccessLog", "Autoscaler", "CLASSES", "CLASS_HEADER", "FakeClock",
    "FrontDoor", "FrontDoorParams", "LatencyTracker", "NetworkFrontend",
    "NetworkParams", "NoHealthyReplicaError", "PrefixCache",
    "REQUESTS_PREFIX", "RefcountedBlockAllocator", "Replica",
    "ReplicaEndpoint", "ReplicaRouter", "RequestLog", "RequestRecord",
    "SLOMonitor", "SLOObjective", "SRV_PREFIX", "ScalingDecision",
    "ServingFrontend", "ServingHandle", "ServingMetrics",
    "ServingParams", "ServingScheduler", "ServingWorker",
    "SyntheticEngine", "TRACE_HEADER", "assemble_timeline",
    "build_serving_frontend", "configure_request_log",
    "configure_tracing_from_config", "discover_endpoints",
    "door_params_from_config", "fetch_request_docs", "find_trace",
    "get_request_log", "head_sampled", "jsonline_rpc", "mint_trace_id",
    "net_params_from_config", "objectives_from_config",
    "params_from_config", "read_access_log", "render_slo_table",
    "render_timeline", "replay_report", "replayable_records",
    "run_replay", "sample_from_rollup", "sample_from_snapshot",
    "sanitize_trace_id", "slo_rows_from_rollup", "synthesize_diurnal_log",
    "synthetic_token", "timeline_chrome_trace",
]


def params_from_config(scfg: Any) -> ServingParams:
    """Map the ``serving.*`` config group onto :class:`ServingParams`."""
    return ServingParams(
        max_outstanding_tokens=int(
            getattr(scfg, "max_outstanding_tokens", 8192)),
        interactive_reserve_frac=float(
            getattr(scfg, "interactive_reserve_frac", 0.10)),
        min_hbm_headroom_frac=float(
            getattr(scfg, "min_hbm_headroom_frac", 0.0)),
        preemption=bool(getattr(scfg, "preemption", True)),
        affinity_min_tokens=int(getattr(scfg, "affinity_min_tokens", 16)),
        temperature=float(getattr(scfg, "temperature", 0.0)),
        eos_token_id=getattr(scfg, "eos_token_id", None),
        stream_buffer=int(getattr(scfg, "stream_buffer", 4096)),
        interactive_ttft_slo_ms=float(
            getattr(scfg, "interactive_ttft_slo_ms", 500.0)),
        preempt_release_pages=bool(
            getattr(scfg, "preempt_release_pages", True)))


def net_params_from_config(ncfg: Any) -> NetworkParams:
    """Map the ``serving.network.*`` config group onto
    :class:`NetworkParams`."""
    return NetworkParams(
        rpc_timeout_s=float(getattr(ncfg, "rpc_timeout_s", 30.0)),
        probe_timeout_s=float(getattr(ncfg, "probe_timeout_s", 2.0)),
        probe_every_s=float(getattr(ncfg, "probe_every_s", 1.0)),
        poll_interval_s=float(getattr(ncfg, "poll_interval_s", 0.005)),
        kv_chunk_bytes=int(getattr(ncfg, "kv_chunk_bytes", 64 * 1024)),
        disaggregate=bool(getattr(ncfg, "disaggregate", False)))


def build_serving_frontend(model: Any, params: Any = None,
                           replicas: int = 1,
                           cache_config: Any = None,
                           max_batch_slots: int = 8,
                           prefill_chunk: int = 128,
                           prefill_batch: int = 2,
                           decode_burst: int = 8,
                           prefix_sharing: bool = True,
                           max_cached_blocks: int = 0,
                           serving_params: Optional[ServingParams] = None,
                           mesh: Any = None) -> ServingFrontend:
    """N real v2 engine replicas behind one front-end.  Each replica
    owns a full KV pool (HBM cost scales with ``replicas``) and is
    registered in the memory ledger under ``serving/replica<i>/*``."""
    import jax

    from ..inference.v2 import build_engine_v2

    if params is None:
        params = model.init_params(jax.random.PRNGKey(0))

    def factory(cc, slots, chunk, pbatch):
        return ServingScheduler(cc, max_batch_slots=slots,
                                prefill_chunk=chunk, prefill_batch=pbatch,
                                prefix_sharing=prefix_sharing,
                                max_cached_blocks=max_cached_blocks)

    reps: List[Replica] = []
    for i in range(int(replicas)):
        eng = build_engine_v2(
            model, params, cache_config=cache_config,
            max_batch_slots=max_batch_slots, prefill_chunk=prefill_chunk,
            prefill_batch=prefill_batch, decode_burst=decode_burst,
            mesh=mesh, scheduler_factory=factory,
            ledger_key=f"serving/replica{i}/kv_pool")
        reps.append(Replica(eng, i))
    return ServingFrontend(reps, params=serving_params)
