"""Access-log traffic replay — recorded production traffic as the
regression workload (ISSUE 16 tentpole b).

The PR-15 front-door access log records everything a load generator
needs: per-request arrival time, latency class, prompt/output lengths,
trace id, and the latency/shed outcome the fleet produced.  This module
turns that log back into load:

* :func:`read_access_log` parses the live file AND its rotated ``.1``
  predecessor (older segment first, so records come back in
  chronological order across the rotation boundary).
* :func:`run_replay` re-issues the recorded ``/v1/generate`` requests
  against a live front door, preserving inter-arrival timing (scaled by
  ``--speed``), request classes, prompt/output lengths, and the
  RECORDED trace ids (the ``X-DS-Trace`` header) — so a replayed
  request is traceable with ``serving trace`` under the exact id the
  original carried.  Prompt *content* is synthesized deterministically
  from the trace id with a per-class shared header, so replays are
  reproducible and exercise the prefix cache the way mixed tenant
  traffic does.
* :func:`replay_report` diffs achieved vs recorded QPS, per-class TTFT
  p99, and 429 rate — replay fidelity is a number, not a vibe — and
  carries the ``serving_net_qps_sustained`` /
  ``serving_net_p99_ttft_ms`` keys the perf sentinel gates.
* :func:`synthesize_diurnal_log` writes the deterministic
  diurnal-burst fixture (two traffic peaks over a quiet baseline) the
  CI replay smoke and the autoscaler chaos test drive.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import logger
from .metrics import CLASSES

#: documented replay-fidelity tolerances at --speed 1.0 against an
#: unchanged fleet (the README walkthrough quotes these): achieved QPS
#: within 20% of recorded, per-class TTFT p99 within 50% (latency is
#: the fleet's answer, not the log's — it only matches when the fleet
#: is genuinely unchanged), 429 rate within 10 percentage points
REPLAY_QPS_REL_TOL = 0.20
REPLAY_TTFT_REL_TOL = 0.50
REPLAY_429_ABS_TOL = 0.10


# ---------------------------------------------------------------------------
# read side
# ---------------------------------------------------------------------------

def read_access_log(path: str) -> List[Dict[str, Any]]:
    """All records for an access log path: the rotated ``.1`` segment
    first (it is strictly older), then the live file.  Malformed lines
    are skipped and counted, never fatal — a log a process died while
    writing must still replay."""
    out: List[Dict[str, Any]] = []
    skipped = 0
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
                else:
                    skipped += 1
    if skipped:
        logger.warning(f"replay: skipped {skipped} malformed access-log "
                       f"line(s) under {path}")
    return out


def replayable_records(records: List[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """The subset of access-log records replay can re-issue: generate
    requests that carried a class and a prompt length.  Shed (429)
    records ARE replayable — they were load the fleet saw; only
    validation rejects (400s never admitted) and probe GETs drop."""
    out = []
    for r in records:
        if r.get("method") != "POST":
            continue
        if not str(r.get("path", "")).startswith("/v1/generate"):
            continue
        if r.get("klass") not in CLASSES:
            continue
        if not r.get("prompt_tokens"):
            continue
        if int(r.get("status", 0)) not in (200, 429, 503):
            continue
        out.append(r)
    out.sort(key=lambda r: float(r.get("ts", 0.0)))
    return out


# ---------------------------------------------------------------------------
# deterministic prompt synthesis
# ---------------------------------------------------------------------------

def _det_tokens(seed_text: str, n: int, vocab: int = 29000,
                lo: int = 2) -> List[int]:
    """``n`` tokens in [lo, vocab) from a SHA1 stream over
    ``seed_text`` — stable across processes and Python hash seeds."""
    out: List[int] = []
    counter = 0
    span = max(1, vocab - lo)
    while len(out) < n:
        h = hashlib.sha1(f"{seed_text}:{counter}".encode()).digest()
        for i in range(0, len(h) - 1, 2):
            if len(out) >= n:
                break
            out.append(lo + (h[i] << 8 | h[i + 1]) % span)
        counter += 1
    return out


def synthesize_prompt(trace_id: str, klass: str, prompt_tokens: int,
                      shared_header: int = 48) -> List[int]:
    """The replayed prompt: a per-class shared header (cross-request
    prefix hits, like real tenant traffic) + a per-trace tail.  Fully
    deterministic in (trace_id, klass, length)."""
    n = max(1, int(prompt_tokens))
    head = min(shared_header, n - 1) if n > 1 else 0
    prompt = _det_tokens(f"replay-header:{klass}", head)
    prompt += _det_tokens(f"replay-tail:{trace_id}", n - head)
    return prompt


# ---------------------------------------------------------------------------
# the load generator
# ---------------------------------------------------------------------------

def run_replay(host: str, port: int, records: List[Dict[str, Any]],
               speed: float = 1.0, timeout_s: float = 120.0,
               max_requests: int = 0,
               stop_event: Optional[threading.Event] = None
               ) -> Dict[str, Any]:
    """Re-issue ``records`` (from :func:`replayable_records`) against a
    live door, preserving recorded inter-arrival gaps scaled by
    ``1/speed``.  Returns ``{results, elapsed_s, aborted}`` where each
    result pairs the source record with the achieved outcome."""
    from .cli import http_generate_stream

    recs = records[:int(max_requests)] if max_requests else list(records)
    if not recs:
        return {"results": [], "elapsed_s": 0.0, "aborted": False}
    speed = max(1e-3, float(speed))
    t_base = float(recs[0].get("ts", 0.0))
    stop = stop_event or threading.Event()
    results: List[Optional[Dict[str, Any]]] = [None] * len(recs)
    t0 = time.monotonic()

    def one(i: int, rec: Dict[str, Any]) -> None:
        due = (float(rec.get("ts", t_base)) - t_base) / speed
        while not stop.is_set():
            delay = due - (time.monotonic() - t0)
            if delay <= 0:
                break
            stop.wait(min(delay, 0.5))
        if stop.is_set():
            return
        trace = rec.get("trace") or None
        prompt = synthesize_prompt(trace or f"anon-{i}", rec["klass"],
                                   int(rec["prompt_tokens"]))
        sent = time.monotonic()
        try:
            out = http_generate_stream(
                host, port, prompt,
                int(rec.get("max_new_tokens") or 16),
                rec["klass"], timeout=timeout_s, trace=trace)
        except OSError as e:
            out = {"status_code": -1, "error": repr(e), "tokens": []}
        out["offset_s"] = round(sent - t0, 3)
        results[i] = {"record": rec, "achieved": out}

    threads = [threading.Thread(target=one, args=(i, r), daemon=True,
                                name=f"ds-replay-{i}")
               for i, r in enumerate(recs)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + (
        (float(recs[-1].get("ts", t_base)) - t_base) / speed
        + timeout_s + 30.0)
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.monotonic()))
    aborted = stop.is_set() or any(t.is_alive() for t in threads)
    stop.set()  # releases any straggler waiting on its due time
    return {"results": [r for r in results if r is not None],
            "elapsed_s": round(time.monotonic() - t0, 3),
            "aborted": aborted}


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------

def _p99(xs: List[float]) -> Optional[float]:
    if not xs:
        return None
    s = sorted(xs)
    return s[min(len(s) - 1, int(round(0.99 * (len(s) - 1))))]


def _side_stats(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One side's summary (recorded or achieved) from rows of
    ``{klass, status, ttft_ms, ts_or_offset}``."""
    n = len(rows)
    times = sorted(float(r["at"]) for r in rows)
    span = (times[-1] - times[0]) if len(times) >= 2 else 0.0
    shed = sum(1 for r in rows if int(r["status"]) == 429)
    failed = sum(1 for r in rows
                 if int(r["status"]) not in (200, 429))
    out: Dict[str, Any] = {
        "requests": n,
        "qps": round(n / span, 3) if span > 0 else None,
        "rate_429": round(shed / n, 4) if n else None,
        "failed": failed,
    }
    for c in CLASSES:
        ttfts = [float(r["ttft_ms"]) for r in rows
                 if r["klass"] == c and r.get("ttft_ms") is not None]
        if ttfts:
            out[f"ttft_p99_ms_{c}"] = round(_p99(ttfts), 3)
    return out


def _rel(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None or b is None or b == 0:
        return None
    return round((a - b) / b, 4)


def replay_report(replay_out: Dict[str, Any],
                  speed: float = 1.0) -> Dict[str, Any]:
    """Diff achieved vs recorded.  Recorded QPS is compared after
    ``speed`` scaling (a 2x replay SHOULD run at 2x the recorded
    rate).  Carries the sentinel-gated ``serving_net_*`` keys."""
    results = replay_out.get("results") or []
    recorded = [{"klass": r["record"]["klass"],
                 "status": int(r["record"].get("status", 0)),
                 "ttft_ms": r["record"].get("ttft_ms"),
                 "at": float(r["record"].get("ts", 0.0))}
                for r in results]
    achieved = [{"klass": r["record"]["klass"],
                 "status": int(r["achieved"].get("status_code", -1)),
                 "ttft_ms": r["achieved"].get("ttft_ms"),
                 "at": float(r["achieved"].get("offset_s", 0.0))}
                for r in results]
    rec, ach = _side_stats(recorded), _side_stats(achieved)
    rec_qps_scaled = (rec["qps"] * float(speed)
                      if rec.get("qps") else None)
    diff: Dict[str, Any] = {
        "qps_rel": _rel(ach.get("qps"), rec_qps_scaled),
        "rate_429_delta": (
            round(ach["rate_429"] - rec["rate_429"], 4)
            if ach.get("rate_429") is not None
            and rec.get("rate_429") is not None else None),
    }
    for c in CLASSES:
        k = f"ttft_p99_ms_{c}"
        if ach.get(k) is not None and rec.get(k) is not None:
            diff[f"{k}_rel"] = _rel(ach[k], rec[k])
    within = True
    if diff["qps_rel"] is not None \
            and abs(diff["qps_rel"]) > REPLAY_QPS_REL_TOL:
        within = False
    if diff["rate_429_delta"] is not None \
            and abs(diff["rate_429_delta"]) > REPLAY_429_ABS_TOL:
        within = False
    for c in CLASSES:
        rel = diff.get(f"ttft_p99_ms_{c}_rel")
        if rel is not None and abs(rel) > REPLAY_TTFT_REL_TOL:
            within = False
    report = {
        "replayed": len(results),
        "speed": float(speed),
        "elapsed_s": replay_out.get("elapsed_s"),
        "aborted": bool(replay_out.get("aborted")),
        "recorded": rec,
        "achieved": ach,
        "diff": diff,
        "within_tolerance": within,
        "tolerances": {"qps_rel": REPLAY_QPS_REL_TOL,
                       "ttft_rel": REPLAY_TTFT_REL_TOL,
                       "rate_429_abs": REPLAY_429_ABS_TOL},
        # the sentinel-gated keys: replay joins the perf baseline
        "serving_net_qps_sustained": ach.get("qps") or 0.0,
        "serving_net_p99_ttft_ms":
            ach.get("ttft_p99_ms_interactive") or 0.0,
    }
    return report


# ---------------------------------------------------------------------------
# the diurnal-burst fixture
# ---------------------------------------------------------------------------

def synthesize_diurnal_log(path: str, n: int = 200, seed: int = 7,
                           base_ts: float = 1700000000.0,
                           day_s: float = 40.0) -> List[Dict[str, Any]]:
    """Write a deterministic ~``n``-request diurnal access log: a quiet
    baseline with two traffic peaks (the compressed day), interactive-
    heavy at the peaks, batch/background in the valleys, a few 429s at
    the worst burst.  Checked in as the regression workload
    (``tests/fixtures/serving/diurnal_access.log``); this function is
    how that file was produced and how a test proves it reproducible."""
    rows: List[Dict[str, Any]] = []
    ts = float(base_ts)
    for i in range(int(n)):
        h = hashlib.sha1(f"diurnal:{seed}:{i}".encode()).digest()
        u1, u2, u3 = h[0] / 255.0, h[1] / 255.0, h[2] / 255.0
        phase = (ts - base_ts) % day_s / day_s
        # two peaks (morning/evening): intensity in [0.15, 1.0]
        import math
        intensity = 0.15 + 0.85 * max(
            0.0, math.sin(2.0 * math.pi * phase)) ** 2 \
            + 0.35 * max(0.0, math.sin(4.0 * math.pi * phase + 1.3)) ** 2
        intensity = min(1.0, intensity)
        # exponential-ish inter-arrival thinned by intensity
        gap = -math.log(max(1e-6, 1.0 - u1)) * 0.12 / max(0.2, intensity)
        ts += min(gap, 1.5)
        if u2 < 0.55 + 0.3 * intensity:
            klass = "interactive"
        elif u2 < 0.85:
            klass = "batch"
        else:
            klass = "background"
        prompt = {"interactive": 24 + int(u3 * 40),
                  "batch": 48 + int(u3 * 80),
                  "background": 32 + int(u3 * 48)}[klass]
        max_new = {"interactive": 8 + int(u1 * 8),
                   "batch": 16 + int(u1 * 16),
                   "background": 12 + int(u1 * 12)}[klass]
        shed = intensity > 0.95 and u3 > 0.7
        ttft = None
        if not shed:
            base = {"interactive": 60.0, "batch": 140.0,
                    "background": 110.0}[klass]
            ttft = round(base * (0.7 + 1.2 * intensity) * (0.8 + u3), 3)
        rows.append({
            "ts": round(ts, 3), "method": "POST",
            "path": "/v1/generate",
            "status": 429 if shed else 200, "klass": klass,
            "trace": hashlib.sha1(
                f"diurnal-trace:{seed}:{i}".encode()).hexdigest()[:16],
            "duration_ms": None if shed else round(
                (ttft or 0.0) + max_new * 12.0, 3),
            "tokens": 0 if shed else max_new,
            "prompt_tokens": prompt, "max_new_tokens": max_new,
            "ttft_ms": ttft,
            "close": "shed" if shed else "done", "peer": "127.0.0.1"})
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    os.replace(tmp, path)
    return rows
