"""Rollup-driven fleet autoscaler — the control loop that closes the
observability loop (ISSUE 16 tentpole c).

A policy loop over the signals PR 13–15 made observable:

* **replacement** — a worker whose process died (or whose telemetry
  publication went stale on the rollup: the kill -9 case, where no EOF
  ever reaches the router) is drained through the existing kill-safe
  path and replaced through the launcher immediately, cooldown-exempt.
  In-flight streams splice exactly (the PR-14 guarantee — replacement
  rides the same ``_drain_dead`` re-queue a crash does).
* **scale UP decode** on queue depth (queued requests per live decode
  worker) or token-budget saturation (outstanding tokens per worker as
  a fraction of ``serving.max_outstanding_tokens``).
* **scale UP prefill** on TTFT prefill share (disaggregated fleets:
  the fraction of disaggregated TTFT spent in the prefill stage).
* **scale DOWN** only through :meth:`NetworkFrontend.remove_endpoint`
  (drain first, SIGTERM after) and only below the low-queue watermark
  with the fleet above ``min_workers``.

Breaches must persist ``hysteresis_ticks`` consecutive evaluations, and
non-replacement actions respect ``cooldown_s`` — a bursty queue cannot
flap the fleet.

**Every decision is a traced event**: the autoscaler opens a
trace-id-stamped :class:`~.metrics.RequestRecord` (class
``autoscaler``) in the process request log, so the decision rides the
PR-13 rollup into ``cluster_requests.json`` / ``cluster_trace.json``
and is retrievable with ``serving trace <id>`` exactly like a user
request — the operator answers "why did we scale at 14:02" from one
trace.  Decisions also land as flight-recorder annotations and
``serving/autoscaler_*`` gauges/counters.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import debug_once, log_dist, logger, warn_once
from .tracing import get_request_log, mint_trace_id

#: dead_reason prefix for intentional scale-downs — the replacement
#: logic must not resurrect a worker the policy removed on purpose
SCALE_DOWN_REASON = "scale_down (autoscaler)"


@dataclasses.dataclass
class ScalingDecision:
    """One decision, as returned by :meth:`Autoscaler.tick` (the
    structured twin of the traced record)."""

    action: str            # "scale_up" | "scale_down" | "replace"
    role: str              # "mixed" | "prefill"
    reason: str
    trace_id: str
    worker_id: Optional[str] = None
    endpoint: Optional[str] = None
    ok: bool = True
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class Autoscaler:
    """Policy loop over a :class:`~.remote.NetworkFrontend` and its
    launched worker fleet.

    ``spawn_fn(worker_id, role) -> WorkerProc`` abstracts the launcher
    (tests inject fakes); the default spawns real
    ``python -m deepspeed_tpu.serving worker`` processes via
    :func:`~..launcher.serving_fleet.spawn_serving_worker`.
    """

    def __init__(self, frontend: Any, fleet: List[Any], cfg: Any,
                 spawn_fn: Optional[Callable[..., Any]] = None,
                 engine: str = "synthetic",
                 store_endpoint: Optional[str] = None,
                 worker_extra_args: Optional[List[str]] = None,
                 max_outstanding_tokens: int = 8192,
                 stale_ticks: int = 5,
                 registry: Optional[Any] = None,
                 recorder: Optional[Any] = None):
        self.frontend = frontend
        #: the launched worker processes, autoscaler-owned from here on
        #: (mutated in place so the integration site's shutdown sees
        #: spawned replacements too)
        self.fleet = fleet
        self.cfg = cfg
        self.engine = str(engine)
        self.store_endpoint = store_endpoint
        self.worker_extra_args = list(worker_extra_args or [])
        self.max_outstanding_tokens = int(max_outstanding_tokens)
        #: rollup-staleness threshold: a worker whose telemetry
        #: publication seq hasn't advanced for this many ticks is dead
        #: even if no RPC has failed yet (the idle kill -9 case)
        self.stale_ticks = int(stale_ticks)
        self.registry = registry
        self.recorder = recorder
        self._spawn_fn = spawn_fn
        self._spawned = 0
        self._uid = 0
        #: consecutive-breach counters per rule
        self._breach: Dict[str, int] = {}
        self._last_action_mono = 0.0
        #: worker ids the policy removed on purpose (never resurrected)
        self._scaled_down: set = set()
        #: node -> (last seen publication seq, ticks unchanged)
        self._pub_seen: Dict[str, List[int]] = {}
        self.decisions: List[ScalingDecision] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._client: Optional[Any] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ds-serving-autoscaler")
        self._thread.start()
        log_dist(f"serving autoscaler started "
                 f"(min={self.cfg.min_workers} max={self.cfg.max_workers})")

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception as e:
                logger.debug(f"autoscaler store client close: {e!r}")

    def _rollup_view(self) -> Optional[Any]:
        """The fleet's current rollup straight from the store (feeds
        the staleness detector); None without a store or mid-outage."""
        if not self.store_endpoint:
            return None
        try:
            # hold the lock only for the handle swap — collect_rollup
            # does network I/O and must not serialize against tick()
            with self._lock:
                client = self._client
                if client is None:
                    from ..elasticity.rendezvous import RendezvousClient

                    client = RendezvousClient(self.store_endpoint,
                                              retries=1,
                                              backoff_s=0.05)
                    self._client = client
            from ..telemetry.rollup import collect_rollup

            return collect_rollup(client,
                                  [w.id for w in self.fleet])
        except Exception as e:
            warn_once("serving/autoscaler-rollup",
                      f"rollup collect degraded ({e!r})")
            return None

    def _loop(self) -> None:
        every = max(0.05, float(getattr(self.cfg, "evaluate_every_s",
                                        1.0)))
        while not self._stop.wait(every):
            try:
                self.tick(self._rollup_view())
            except Exception as e:
                warn_once("serving/autoscaler-tick",
                          f"autoscaler tick failed ({e!r})")

    # -- fleet plumbing ----------------------------------------------------

    def _spawn(self, worker_id: str, role: str) -> Any:
        if self._spawn_fn is not None:
            return self._spawn_fn(worker_id, role)
        from ..launcher.serving_fleet import spawn_serving_worker

        return spawn_serving_worker(
            worker_id, role=role, engine=self.engine,
            store=self.store_endpoint,
            extra_args=self.worker_extra_args or None)

    def _next_worker_id(self, role: str) -> str:
        # fresh ids always: the router's drain ledger is id-keyed
        self._spawned += 1
        tag = "p" if role == "prefill" else "d"
        return f"serving-as{tag}{self._spawned}-{int(time.time()) % 100000}"

    def _fleet_by_id(self) -> Dict[str, Any]:
        return {w.id: w for w in self.fleet}

    def _decode_endpoints(self) -> List[Any]:
        return [e for e in self.frontend.endpoints if e.role != "prefill"]

    def _live(self, eps: List[Any]) -> List[Any]:
        return [e for e in eps if e.dead_reason is None]

    # -- signal collection -------------------------------------------------

    def observe_rollup(self, rollup: Any) -> List[str]:
        """Fold one rollup view in; returns worker node ids whose
        telemetry publication has been stale for ``stale_ticks``
        consecutive observations.  THE kill -9 detector: a SIGKILLed
        worker holds its TCP listener's backlog open (nothing fails
        fast) but its publisher beat stops instantly."""
        stale: List[str] = []
        fleet_ids = set(self._fleet_by_id())
        for nid in rollup.node_ids():
            if nid not in fleet_ids:
                continue
            doc = rollup.node_doc(nid) or {}
            seq = int(doc.get("seq", 0))
            seen = self._pub_seen.setdefault(nid, [seq, 0])
            if seq == seen[0]:
                seen[1] += 1
            else:
                seen[0], seen[1] = seq, 0
            if seen[1] >= self.stale_ticks:
                stale.append(nid)
        return stale

    def _signals(self) -> Dict[str, Any]:
        snap = {}
        try:
            snap = self.frontend.snapshot()
        except Exception as e:
            warn_once("serving/autoscaler-snap",
                      f"frontend snapshot failed ({e!r})")
        decode = self._decode_endpoints()
        live = self._live(decode)
        n = max(1, len(live))
        queues = snap.get("queues") or {}
        queued = sum(int(v) for v in queues.values())
        outstanding = 0
        for ep in live:
            try:
                outstanding += int(self.frontend._outstanding(ep))
            except Exception as e:
                debug_once("serving/autoscaler-outstanding",
                           f"outstanding probe failed for {ep.id} "
                           f"({e!r})")
        prefill_share = None
        disagg = snap.get("disagg_ttft") or {}
        if disagg:
            p50 = {k: float((v or {}).get("p50_ms", 0.0))
                   for k, v in disagg.items()}
            total = sum(p50.values())
            if total > 0:
                prefill_share = p50.get("prefill_ms", 0.0) / total
        return {
            "decode_live": len(live),
            "decode_total": len(decode),
            "prefill_live": len(self._live(
                [e for e in self.frontend.endpoints
                 if e.role == "prefill"])),
            "queued_requests": queued,
            "queue_depth_per_worker": queued / n,
            "outstanding_tokens": outstanding,
            "token_saturation": (outstanding / n
                                 / max(1, self.max_outstanding_tokens)),
            "ttft_prefill_share": prefill_share,
        }

    # -- decision tracing --------------------------------------------------

    def _record_decision(self, action: str, role: str, reason: str,
                         signals: Dict[str, Any]
                         ) -> "tuple[Any, ScalingDecision]":
        trace_id = mint_trace_id()
        self._uid += 1
        rlog = get_request_log()
        # sampled=True: a scaling decision is never below the sampling
        # floor — it must reach cluster_trace.json every time
        rec = rlog.start(trace_id, f"autoscale-{self._uid}",
                         "autoscaler", 0, 0, sampled=True)
        rec.event("decision", action=action, role=role,
                  reason=reason[:200],
                  **{k: v for k, v in signals.items() if v is not None})
        dec = ScalingDecision(action=action, role=role, reason=reason,
                              trace_id=trace_id)
        return rec, dec

    def _finalize(self, rec: Any, dec: ScalingDecision) -> None:
        rec.finish("completed" if dec.ok else "failed")
        try:
            get_request_log().commit(rec)
        except Exception as e:
            warn_once("serving/autoscaler-trace",
                      f"decision record commit failed ({e!r})")
        if self.recorder is not None:
            try:
                self.recorder.annotate("autoscaler", dec.to_dict())
            except Exception as e:
                logger.debug(f"autoscaler annotation failed: {e!r}")
        reg = self.registry
        if reg is not None:
            try:
                reg.counter("serving/autoscaler_decisions_total",
                            "autoscaler scaling decisions").inc()
                reg.counter(
                    f"serving/autoscaler_{dec.action}_total",
                    f"autoscaler {dec.action} decisions").inc()
            except Exception as e:
                logger.debug(f"autoscaler metrics failed: {e!r}")
        with self._lock:
            self.decisions.append(dec)
        log_dist(f"autoscaler: {dec.action} {dec.role} "
                 f"({'ok' if dec.ok else 'FAILED'}) trace={dec.trace_id} "
                 f"worker={dec.worker_id} — {dec.reason}")

    # -- actions -----------------------------------------------------------

    def _do_scale_up(self, rec: Any, dec: ScalingDecision) -> None:
        from .remote import ReplicaEndpoint

        wid = self._next_worker_id(dec.role)
        dec.worker_id = wid
        try:
            w = self._spawn(wid, dec.role)
            rec.event("spawned", worker=wid, pid=getattr(w, "pid", None),
                      endpoint=getattr(w, "endpoint", None))
            self.fleet.append(w)
            ep = ReplicaEndpoint(w.id, w.endpoint, role=w.role)
            self.frontend.add_endpoint(ep)
            rec.event("endpoint_added", endpoint=w.endpoint)
            dec.endpoint = w.endpoint
        except Exception as e:
            dec.ok = False
            dec.error = repr(e)
            rec.event("spawn_failed", error=repr(e)[:200])
            warn_once("serving/autoscaler-spawn",
                      f"scale-up spawn failed ({e!r})")

    def _do_scale_down(self, rec: Any, dec: ScalingDecision,
                       victim_ep: Any) -> None:
        dec.worker_id = victim_ep.id
        dec.endpoint = victim_ep.endpoint
        self._scaled_down.add(victim_ep.id)
        # drain FIRST: after remove_endpoint nothing new lands on the
        # victim and its in-flight work re-queues splice-exact; only
        # then is the process told to exit
        self.frontend.remove_endpoint(victim_ep.id,
                                      reason=SCALE_DOWN_REASON)
        rec.event("drained", worker=victim_ep.id)
        w = self._fleet_by_id().get(victim_ep.id)
        if w is not None and w.proc.poll() is None:
            try:
                w.proc.terminate()
                rec.event("terminated", worker=victim_ep.id, pid=w.pid)
            except OSError as e:
                dec.error = repr(e)
                rec.event("terminate_failed", error=repr(e)[:120])

    def _replace_dead(self, signals: Dict[str, Any],
                      stale_nodes: List[str]) -> List[ScalingDecision]:
        """Dead-worker replacement (cooldown-exempt).  Dead means: the
        process exited, the router marked the endpoint dead (and not by
        our own scale-down), or the rollup publication went stale."""
        out: List[ScalingDecision] = []
        fleet_by_id = self._fleet_by_id()
        for ep in list(self.frontend.endpoints):
            if ep.id in self._scaled_down:
                continue
            reason = None
            w = fleet_by_id.get(ep.id)
            if ep.dead_reason is not None \
                    and not str(ep.dead_reason).startswith("scale_down"):
                reason = f"endpoint dead: {ep.dead_reason}"
            elif w is not None and w.proc.poll() is not None:
                reason = f"worker process exited rc={w.proc.poll()}"
            elif ep.id in stale_nodes:
                reason = (f"telemetry publication stale for "
                          f"{self.stale_ticks} ticks (rollup gap)")
            if reason is None:
                continue
            # count the corpse out of the fleet and drain it (the
            # stale-publication path may reach here before any RPC
            # failed — remove_endpoint makes the drain immediate
            # instead of waiting for a transport error)
            self._scaled_down.add(ep.id)
            self.frontend.remove_endpoint(
                ep.id, reason=f"autoscaler replace: {reason}")
            if w is not None and w.proc.poll() is None:
                try:
                    w.proc.kill()
                except OSError:
                    pass
            rec, dec = self._record_decision(
                "replace", "prefill" if ep.role == "prefill" else "mixed",
                reason, signals)
            rec.event("dead_worker", worker=ep.id,
                      endpoint=ep.endpoint)
            if len(self._live(self._decode_endpoints())) \
                    + signals.get("prefill_live", 0) \
                    < int(self.cfg.max_workers):
                self._do_scale_up(rec, dec)
            else:
                dec.ok = False
                dec.error = "fleet at max_workers"
            self._finalize(rec, dec)
            out.append(dec)
        return out

    # -- the policy tick ---------------------------------------------------

    def _breach_tick(self, rule: str, breached: bool) -> bool:
        """Hysteresis: True only after ``hysteresis_ticks`` CONSECUTIVE
        breaches (and resets the streak when it trips)."""
        n = self._breach.get(rule, 0) + 1 if breached else 0
        self._breach[rule] = n
        if n >= int(self.cfg.hysteresis_ticks):
            self._breach[rule] = 0
            return True
        return False

    def tick(self, rollup: Optional[Any] = None) -> List[ScalingDecision]:
        """One evaluation.  ``rollup`` (optional) feeds the staleness
        detector; the serve/bench integration passes the view its
        telemetry beat already collects."""
        signals = self._signals()
        stale = self.observe_rollup(rollup) if rollup is not None else []
        out = self._replace_dead(signals, stale)
        if self.registry is not None:
            try:
                self.registry.gauge(
                    "serving/autoscaler_workers",
                    "live decode workers the autoscaler sees"
                ).set(float(signals["decode_live"]))
                self.registry.gauge(
                    "serving/autoscaler_queue_depth",
                    "queued requests per live decode worker"
                ).set(float(signals["queue_depth_per_worker"]))
            except Exception as e:
                logger.debug(f"autoscaler gauges failed: {e!r}")
        now = time.monotonic()
        # _last_action_mono == 0.0 means "no action yet": monotonic
        # time counts from boot, so a fresh autoscaler on a young host
        # must not start its life inside the cooldown
        in_cooldown = (self._last_action_mono > 0.0
                       and now - self._last_action_mono
                       < float(self.cfg.cooldown_s))
        n_live = signals["decode_live"] + signals["prefill_live"]
        # scale UP decode: queue depth or token saturation
        up_q = self._breach_tick(
            "up_queue", signals["queue_depth_per_worker"]
            > float(self.cfg.queue_depth_high))
        up_t = self._breach_tick(
            "up_tokens", signals["token_saturation"]
            > float(self.cfg.token_saturation_high))
        up_p = self._breach_tick(
            "up_prefill", signals["ttft_prefill_share"] is not None
            and signals["ttft_prefill_share"]
            > float(self.cfg.ttft_prefill_share_high))
        down = self._breach_tick(
            "down_queue", signals["queue_depth_per_worker"]
            < float(self.cfg.queue_depth_low)
            and signals["token_saturation"] < 0.5
            and signals["decode_live"] > 1)
        if not in_cooldown and (up_q or up_t) \
                and n_live < int(self.cfg.max_workers):
            reason = (f"queue depth {signals['queue_depth_per_worker']:.2f}"
                      f" > {self.cfg.queue_depth_high:g}/worker" if up_q
                      else f"token saturation "
                           f"{signals['token_saturation']:.2f} > "
                           f"{self.cfg.token_saturation_high:g}")
            rec, dec = self._record_decision("scale_up", "mixed", reason,
                                             signals)
            self._do_scale_up(rec, dec)
            self._finalize(rec, dec)
            self._last_action_mono = now
            out.append(dec)
        elif not in_cooldown and up_p \
                and n_live < int(self.cfg.max_workers):
            rec, dec = self._record_decision(
                "scale_up", "prefill",
                f"TTFT prefill share {signals['ttft_prefill_share']:.2f}"
                f" > {self.cfg.ttft_prefill_share_high:g}", signals)
            self._do_scale_up(rec, dec)
            self._finalize(rec, dec)
            self._last_action_mono = now
            out.append(dec)
        elif not in_cooldown and down \
                and n_live > int(self.cfg.min_workers):
            live = self._live(self._decode_endpoints())
            if len(live) > 1:
                # the youngest decode worker drains with the least
                # affinity loss (prefix trees are warmest on veterans)
                victim = live[-1]
                rec, dec = self._record_decision(
                    "scale_down", "mixed",
                    f"queue depth {signals['queue_depth_per_worker']:.2f}"
                    f" < {self.cfg.queue_depth_low:g}/worker with "
                    f"{len(live)} live decode workers", signals)
                self._do_scale_down(rec, dec, victim)
                self._finalize(rec, dec)
                self._last_action_mono = now
                out.append(dec)
        return out

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            decs = [d.to_dict() for d in self.decisions[-16:]]
        return {"decisions": decs,
                "total": len(self.decisions),
                "fleet": [{"id": w.id, "role": w.role,
                           "endpoint": w.endpoint,
                           "alive": w.proc.poll() is None}
                          for w in self.fleet]}
