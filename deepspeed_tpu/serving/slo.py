"""SLO monitors — the consumer side of the serving telemetry (ISSUE 16).

PR 13–15 made every serving signal observable (per-worker gauges in the
cross-process rollup, 429 backpressure counters, per-class latency
percentiles, request trace lanes); nothing *read* them.  This module
closes that loop: declarative objectives evaluated continuously against
the rollup with fast/slow multi-window burn rates, so a breach pages
only when the error budget is burning NOW (fast window) and the burn is
sustained (slow window) — the Google-SRE multi-window shape, uniform
across objective kinds.

Every objective reduces to a **bad-event fraction** per evaluation
sample:

* ``availability``   — (429 + 5xx) / requests over the window, from
  front-door counter deltas.
* ``ttft_<class>`` / ``tpot_<class>`` — 1.0 when the published
  percentile gauge exceeds its bound at this sample, else 0.0 (the SLO
  allows the percentile over its bound at most ``1 − target`` of the
  time).
* ``token_budget``   — 1.0 when the worst class's queued-token fraction
  exceeds the saturation bound (the leading indicator for the 429s the
  availability objective counts after the fact).

``burn_rate(window) = mean(bad fraction over window) / (1 − target)``;
the alert FIRES when both windows burn ≥ ``burn_rate_threshold`` and
CLEARS when the fast window drops back under it (the slow window alone
keeps an old incident's tail from re-paging).

Alert transitions are published everywhere an operator could look:
:class:`~..telemetry.health.HealthEvent`\\ s (kind ``slo_burn`` /
``slo_clear``) through the registry counters + ``kind="health"`` event
stream, flight-recorder annotations (so every debug bundle carries the
recent alert history), and ``serving/slo_*`` gauges that ride the PR-13
rollup into ``telemetry top --serving``, the merged Prometheus export
(``serving_slo_*``), and the perf baseline
(``serving_slo_burn_rate_p99``).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from ..telemetry.health import SEV_CRITICAL, SEV_WARNING, HealthEvent
from ..utils.logging import debug_once, logger
from .metrics import CLASSES

#: gauge-name prefix — prom_name() renders these ``serving_slo_*``
SLO_GAUGE_PREFIX = "serving/slo_"


@dataclasses.dataclass
class SLOObjective:
    """One declarative objective.

    ``bad_frac(sample) -> Optional[float]`` maps a fleet sample to the
    bad-event fraction in [0, 1] for this evaluation (None = the signal
    is absent this tick — e.g. no requests yet — and the window simply
    doesn't advance)."""

    id: str
    kind: str                     # "latency" | "availability" | "saturation"
    target: float                 # compliance objective in (0, 1)
    bad_frac: Callable[[Dict[str, Any]], Optional[float]]
    description: str = ""

    @property
    def budget(self) -> float:
        """The error budget: the bad fraction the SLO tolerates."""
        return max(1e-9, 1.0 - float(self.target))


class _Window:
    """Time-bounded ring of ``(ts, bad_frac, weight)`` samples."""

    def __init__(self, span_s: float):
        self.span_s = float(span_s)
        self._ring: "collections.deque" = collections.deque()

    def push(self, ts: float, bad: float, weight: float = 1.0) -> None:
        self._ring.append((float(ts), float(bad), max(0.0, float(weight))))
        self._trim(ts)

    def _trim(self, now: float) -> None:
        while self._ring and now - self._ring[0][0] > self.span_s:
            self._ring.popleft()

    def mean(self, now: float) -> Optional[float]:
        """Weighted mean bad fraction over the window (None: no data)."""
        self._trim(now)
        wsum = sum(w for _, _, w in self._ring)
        if wsum <= 0.0:
            return None
        return sum(b * w for _, b, w in self._ring) / wsum


@dataclasses.dataclass
class SLOState:
    """Per-objective alert state, readable by renderers."""

    objective: SLOObjective
    burn_fast: Optional[float] = None
    burn_slow: Optional[float] = None
    alerting: bool = False
    fired_ts: float = 0.0
    cleared_ts: float = 0.0
    transitions: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {"id": self.objective.id, "kind": self.objective.kind,
                "target": self.objective.target,
                "burn_fast": self.burn_fast, "burn_slow": self.burn_slow,
                "alerting": self.alerting, "transitions": self.transitions}


# ---------------------------------------------------------------------------
# fleet samples — ONE dict shape, two producers
# ---------------------------------------------------------------------------

def _sample_from_merged(sums: Dict[str, float], maxes: Dict[str, float],
                        queue_token_budget: int) -> Dict[str, Any]:
    sample: Dict[str, Any] = {"ts": time.time()}
    sample["requests_total"] = sums.get("serving/http_requests_total")
    sample["rejected_total"] = (
        sums.get("serving/backpressure_429_total", 0.0)
        + sums.get("serving/http_5xx_total", 0.0))
    for c in CLASSES:
        sample[f"ttft_p99_ms_{c}"] = maxes.get(f"serving/{c}_ttft_p99_ms")
        sample[f"tpot_p50_ms_{c}"] = maxes.get(f"serving/{c}_tpot_p50_ms")
    queued = [maxes.get(f"serving/door_queued_tokens_{c}") for c in CLASSES]
    queued = [q for q in queued if q is not None]
    if queued and queue_token_budget > 0:
        sample["token_budget_frac"] = max(queued) / float(queue_token_budget)
    return sample


def _merge_snapshot(snap: Dict[str, Any], sums: Dict[str, float],
                    maxes: Dict[str, float]) -> None:
    for name, m in (snap.get("counters") or {}).items():
        sums[name] += float(m.get("value", 0.0))
    for name, m in (snap.get("gauges") or {}).items():
        v = float(m.get("value", 0.0))
        maxes[name] = max(maxes[name], v) if name in maxes else v


def sample_from_snapshot(snap: Dict[str, Any],
                         queue_token_budget: int = 0) -> Dict[str, Any]:
    """The fleet sample from ONE registry snapshot — the front door's
    local evaluation path (its registry already holds the per-class
    percentile gauges, the 429/5xx counters, and the queued-token
    gauges it publishes)."""
    sums: Dict[str, float] = collections.defaultdict(float)
    maxes: Dict[str, float] = {}
    _merge_snapshot(snap or {}, sums, maxes)
    return _sample_from_merged(sums, maxes, queue_token_budget)


def sample_from_rollup(rollup: Any,
                       queue_token_budget: int = 0) -> Dict[str, Any]:
    """Reduce a :class:`~..telemetry.rollup.MetricsRollup` to the flat
    fleet sample the objectives read.  Counters sum across nodes (each
    process owns its own monotonic series); percentile and queued-token
    gauges take the max across publishers (the worst front-end is the
    one the SLO is about)."""
    sums: Dict[str, float] = collections.defaultdict(float)
    maxes: Dict[str, float] = {}
    for nid in rollup.node_ids():
        doc = rollup.node_doc(nid) or {}
        _merge_snapshot(doc.get("snapshot") or {}, sums, maxes)
    return _sample_from_merged(sums, maxes, queue_token_budget)


# ---------------------------------------------------------------------------
# objective construction from config
# ---------------------------------------------------------------------------

def _latency_bad(field: str, bound_ms: float
                 ) -> Callable[[Dict[str, Any]], Optional[float]]:
    def bad(sample: Dict[str, Any]) -> Optional[float]:
        v = sample.get(field)
        if v is None:
            return None
        return 1.0 if float(v) > bound_ms else 0.0
    return bad


def _availability_bad(sample: Dict[str, Any]) -> Optional[float]:
    # counter LEVELS — SLOMonitor differentiates them into per-tick
    # deltas before this runs; here the fields are already deltas
    req = sample.get("_d_requests")
    bad = sample.get("_d_rejected")
    if not req:
        return None
    return min(1.0, max(0.0, float(bad or 0.0)) / float(req))


def _saturation_bad(bound: float
                    ) -> Callable[[Dict[str, Any]], Optional[float]]:
    def bad(sample: Dict[str, Any]) -> Optional[float]:
        v = sample.get("token_budget_frac")
        if v is None:
            return None
        return 1.0 if float(v) > bound else 0.0
    return bad


def objectives_from_config(slo_cfg: Any) -> List[SLOObjective]:
    """The declarative objective set for a ``serving.slo`` config group
    (``ServingSLOConfig`` or anything with its fields)."""
    target = float(getattr(slo_cfg, "availability_target", 0.999))
    out: List[SLOObjective] = []
    for c in CLASSES:
        bound = float(getattr(slo_cfg, f"{c}_ttft_p99_ms", 0.0) or 0.0)
        if bound > 0:
            out.append(SLOObjective(
                id=f"ttft_{c}", kind="latency", target=target,
                bad_frac=_latency_bad(f"ttft_p99_ms_{c}", bound),
                description=f"{c} TTFT p99 <= {bound:g} ms"))
    tpot = float(getattr(slo_cfg, "interactive_tpot_p50_ms", 0.0) or 0.0)
    if tpot > 0:
        out.append(SLOObjective(
            id="tpot_interactive", kind="latency", target=target,
            bad_frac=_latency_bad("tpot_p50_ms_interactive", tpot),
            description=f"interactive TPOT p50 <= {tpot:g} ms/token"))
    out.append(SLOObjective(
        id="availability", kind="availability", target=target,
        bad_frac=_availability_bad,
        description=f"1 - (429+5xx)/requests >= {target:g}"))
    sat = float(getattr(slo_cfg, "token_budget_saturation", 0.0) or 0.0)
    if sat > 0:
        out.append(SLOObjective(
            id="token_budget", kind="saturation", target=target,
            bad_frac=_saturation_bad(sat),
            description=f"queued-token saturation <= {sat:g}"))
    return out


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------

class SLOMonitor:
    """Feed :meth:`observe` a fleet sample per evaluation tick; alert
    transitions are returned AND published (registry gauges + health
    events + flight-recorder annotations, all optional and guarded)."""

    def __init__(self, objectives: List[SLOObjective],
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 300.0,
                 burn_rate_threshold: float = 2.0,
                 registry: Optional[Any] = None,
                 recorder: Optional[Any] = None):
        self.objectives = list(objectives)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_rate_threshold = float(burn_rate_threshold)
        self.registry = registry
        self.recorder = recorder
        self._fast = {o.id: _Window(self.fast_window_s)
                      for o in self.objectives}
        self._slow = {o.id: _Window(self.slow_window_s)
                      for o in self.objectives}
        self.states: Dict[str, SLOState] = {
            o.id: SLOState(objective=o) for o in self.objectives}
        #: previous availability-counter levels for differentiation
        self._prev_req: Optional[float] = None
        self._prev_rej: Optional[float] = None
        self.events_total = 0

    @classmethod
    def from_config(cls, slo_cfg: Any, registry: Optional[Any] = None,
                    recorder: Optional[Any] = None) -> "SLOMonitor":
        return cls(objectives_from_config(slo_cfg),
                   fast_window_s=float(
                       getattr(slo_cfg, "fast_window_s", 60.0)),
                   slow_window_s=float(
                       getattr(slo_cfg, "slow_window_s", 300.0)),
                   burn_rate_threshold=float(
                       getattr(slo_cfg, "burn_rate_threshold", 2.0)),
                   registry=registry, recorder=recorder)

    # -- evaluation --------------------------------------------------------

    def _differentiate(self, sample: Dict[str, Any]) -> None:
        """Turn availability counter LEVELS into per-tick deltas (the
        windows accumulate deltas; a restarted publisher's counter reset
        shows as a negative delta and is clamped to 'no data')."""
        req, rej = sample.get("requests_total"), sample.get("rejected_total")
        if req is None:
            return
        if self._prev_req is not None and float(req) >= self._prev_req:
            sample["_d_requests"] = float(req) - self._prev_req
            sample["_d_rejected"] = max(
                0.0, float(rej or 0.0) - (self._prev_rej or 0.0))
        self._prev_req = float(req)
        self._prev_rej = float(rej or 0.0)

    def observe(self, sample: Dict[str, Any]) -> List[HealthEvent]:
        """One evaluation tick.  Returns the alert-transition events
        (fire and clear) this sample caused, already published."""
        now = float(sample.get("ts") or time.time())
        self._differentiate(sample)
        out: List[HealthEvent] = []
        for obj in self.objectives:
            st = self.states[obj.id]
            try:
                bad = obj.bad_frac(sample)
            except Exception as e:  # an objective bug must not stop others
                debug_once(f"slo/{obj.id}",
                           f"objective {obj.id} evaluation failed ({e!r})")
                continue
            if bad is not None:
                # availability windows weight by request volume so one
                # quiet tick can't wash out a burst of errors
                weight = float(sample.get("_d_requests", 1.0) or 1.0) \
                    if obj.kind == "availability" else 1.0
                self._fast[obj.id].push(now, bad, weight)
                self._slow[obj.id].push(now, bad, weight)
            fast = self._fast[obj.id].mean(now)
            slow = self._slow[obj.id].mean(now)
            st.burn_fast = None if fast is None else fast / obj.budget
            st.burn_slow = None if slow is None else slow / obj.budget
            thr = self.burn_rate_threshold
            if (not st.alerting and st.burn_fast is not None
                    and st.burn_slow is not None
                    and st.burn_fast >= thr and st.burn_slow >= thr):
                st.alerting = True
                st.fired_ts = now
                st.transitions += 1
                sev = SEV_CRITICAL if st.burn_fast >= 2 * thr else SEV_WARNING
                out.append(HealthEvent(
                    "slo_burn", sev, 0,
                    f"SLO {obj.id} burning error budget at "
                    f"{st.burn_fast:.1f}x (fast {self.fast_window_s:g}s) / "
                    f"{st.burn_slow:.1f}x (slow {self.slow_window_s:g}s), "
                    f"threshold {thr:g}x — {obj.description}",
                    st.burn_fast, thr))
            elif st.alerting and (st.burn_fast is None
                                  or st.burn_fast < thr):
                st.alerting = False
                st.cleared_ts = now
                st.transitions += 1
                out.append(HealthEvent(
                    "slo_clear", SEV_WARNING, 0,
                    f"SLO {obj.id} alert cleared after "
                    f"{now - st.fired_ts:.1f}s (fast-window burn "
                    f"{0.0 if st.burn_fast is None else st.burn_fast:.2f}x "
                    f"< {thr:g}x)", st.burn_fast or 0.0, thr))
        for ev in out:
            self._publish(ev)
        self._publish_gauges()
        return out

    # -- publication -------------------------------------------------------

    def _publish(self, ev: HealthEvent) -> None:
        self.events_total += 1
        if self.recorder is not None:
            try:
                self.recorder.record_health(ev)
                self.recorder.annotate("slo", ev.to_dict())
            except Exception as e:
                debug_once("slo/recorder",
                           f"SLO event recording failed ({e!r})")
        reg = self.registry
        if reg is None:
            return
        try:
            reg.counter("health/events_total",
                        "training-health anomaly events").inc()
            reg.counter(f"health/{ev.kind}_total",
                        f"{ev.kind} events").inc()
            reg.emit_event("health", ev.to_dict())
        except Exception as e:
            debug_once("slo/metrics",
                       f"SLO event metrics publish failed ({e!r})")
        logger.warning(f"[slo] {ev.message}")

    def _publish_gauges(self) -> None:
        """``serving/slo_*`` gauges — they ride push_node_telemetry into
        the rollup, so ``telemetry top --serving``, the merged
        Prometheus export (``serving_slo_*``), and the perf baseline
        read alert state without talking to this process."""
        reg = self.registry
        if reg is None:
            return
        try:
            active, worst = 0, 0.0
            for oid, st in self.states.items():
                if st.burn_fast is not None:
                    reg.gauge(f"{SLO_GAUGE_PREFIX}{oid}_burn_fast",
                              f"fast-window burn rate, {oid}"
                              ).set(st.burn_fast)
                if st.burn_slow is not None:
                    reg.gauge(f"{SLO_GAUGE_PREFIX}{oid}_burn_slow",
                              f"slow-window burn rate, {oid}"
                              ).set(st.burn_slow)
                    worst = max(worst, st.burn_slow)
                reg.gauge(f"{SLO_GAUGE_PREFIX}{oid}_alert",
                          f"1 while the {oid} SLO alert is firing"
                          ).set(1.0 if st.alerting else 0.0)
                active += 1 if st.alerting else 0
            reg.gauge(f"{SLO_GAUGE_PREFIX}alerts_active",
                      "SLO alerts currently firing").set(float(active))
            lat = [st.burn_slow for st in self.states.values()
                   if st.objective.kind == "latency"
                   and st.burn_slow is not None]
            if lat:
                # the sentinel-gated summary metric: worst sustained
                # latency-objective burn rate (serving_slo_burn_rate_p99)
                reg.gauge(f"{SLO_GAUGE_PREFIX}burn_rate_p99",
                          "worst slow-window burn rate across p99 "
                          "latency objectives").set(max(lat))
        except Exception as e:
            debug_once("slo/gauges", f"SLO gauge publish failed ({e!r})")

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return {"threshold": self.burn_rate_threshold,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "objectives": [self.states[o.id].to_dict()
                               for o in self.objectives]}


# ---------------------------------------------------------------------------
# stateless render — `telemetry top --serving` and `serving slo` read
# the PUBLISHED gauges (any process's view of the rollup), not a live
# monitor
# ---------------------------------------------------------------------------

def slo_rows_from_rollup(rollup: Any) -> List[Dict[str, Any]]:
    """Recover per-objective SLO state from the ``serving/slo_*`` gauges
    riding the rollup.  Works against any node's publication (the door
    runs the monitor); rows sort alerting-first, worst burn first."""
    merged: Dict[str, Dict[str, float]] = {}
    for nid in rollup.node_ids():
        doc = rollup.node_doc(nid) or {}
        snap = doc.get("snapshot") or {}
        for name, m in (snap.get("gauges") or {}).items():
            if not name.startswith(SLO_GAUGE_PREFIX):
                continue
            suffix = name[len(SLO_GAUGE_PREFIX):]
            for tail in ("_burn_fast", "_burn_slow", "_alert"):
                if suffix.endswith(tail):
                    oid, field = suffix[:-len(tail)], tail[1:]
                    break
            else:
                continue
            row = merged.setdefault(oid, {})
            row[field] = max(row.get(field, float("-inf")),
                             float(m.get("value", 0.0)))
    rows = [{"objective": oid, **vals} for oid, vals in merged.items()]
    rows.sort(key=lambda r: (-(r.get("alert") or 0.0),
                             -(r.get("burn_fast") or 0.0), r["objective"]))
    return rows


def render_slo_table(rows: List[Dict[str, Any]]) -> str:
    if not rows:
        return "no SLO state published (is the front door running with " \
               "serving.slo.enabled?)"
    lines = [f"{'OBJECTIVE':<20} {'BURN_FAST':>10} {'BURN_SLOW':>10} "
             f"{'STATE':<8}"]
    for r in rows:
        state = "FIRING" if (r.get("alert") or 0.0) >= 1.0 else "ok"
        bf, bs = r.get("burn_fast"), r.get("burn_slow")
        lines.append(
            f"{r['objective']:<20} "
            f"{'-' if bf is None else format(bf, '.2f'):>10} "
            f"{'-' if bs is None else format(bs, '.2f'):>10} "
            f"{state:<8}")
    return "\n".join(lines)
