"""HTTP/SSE front door — the network edge of the serving plane
(ISSUE 14 tentpole a).

Stdlib-only (``http.server``/``socketserver``, the same dependency
posture as the rendezvous store): one :class:`FrontDoor` wraps either
the in-process :class:`~.frontend.ServingFrontend` or the
process-per-replica :class:`~.remote.NetworkFrontend` — a request
enters over a socket and (in network mode) exits over a socket.

API:

* ``GET /healthz`` — 200 with replica health when at least one replica
  is live, 503 otherwise.  The CLI smoke and load balancers probe it.
* ``GET /v1/metrics`` — the serving snapshot (per-class TTFT/TPOT,
  queue depths, counters, prefix hit rate, disaggregated TTFT
  attribution) as JSON.
* ``POST /v1/generate`` — body ``{"prompt": [ints], "max_new_tokens":
  N, "class": "interactive", "stream": true}``.  The admission class
  may also ride the ``X-DS-Class`` header (the header wins — edge
  proxies stamp it without touching the body).

  - Validation failures map to **400** naming the offending field
    (the scheduler's own messages), malformed/oversized bodies to
    400/413, wrong methods/paths to 405/404.
  - **Backpressure**: when the class queue is over its token budget
    the door answers **429** with a ``Retry-After`` header instead of
    queueing — the SLO stays honest under overload.
  - ``"stream": true`` (default) answers ``text/event-stream``:
    ``event: token`` per generated token, comment heartbeats while
    idle (dead-socket detection between tokens), and a final ``event:
    done`` carrying the TTFT (split prefill/transfer/decode when the
    request ran disaggregated).  A client that disconnects mid-stream
    CANCELS the request (``serving/cancelled_on_disconnect_total``) —
    abandoned work never holds pages or decode slots.
  - ``"stream": false`` blocks and answers one JSON document.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ..utils.logging import log_dist, logger, warn_once
from .frontend import NoHealthyReplicaError
from .metrics import CLASSES
from .tracing import (TRACE_HEADER, AccessLog, mint_trace_id,
                      sanitize_trace_id)

#: admission-class request header (overrides the body's "class")
CLASS_HEADER = "X-DS-Class"


@dataclasses.dataclass
class FrontDoorParams:
    """HTTP-layer knobs (``serving.network.*`` maps the overlap)."""

    #: per-class queued-token budget: a submit that would push the
    #: class queue past it is answered 429 + Retry-After
    queue_token_budget: int = 32768
    retry_after_s: float = 1.0
    #: SSE idle heartbeat period (comment lines; also the cadence at
    #: which a dead client socket is discovered between tokens)
    sse_heartbeat_s: float = 5.0
    max_body_bytes: int = 1 << 20
    #: non-streaming requests block at most this long
    result_timeout_s: float = 600.0
    #: structured access log (ISSUE 15): one JSONL line per request —
    #: ts, method, path, status, class, trace id, duration_ms, tokens
    #: streamed, close reason (incl. cancel-on-disconnect).  "" = off.
    access_log: str = ""
    #: size cap before the live file rotates to ``<path>.1``
    access_log_max_bytes: int = 8 << 20


def door_params_from_config(ncfg: Any) -> FrontDoorParams:
    """Map the HTTP-layer knobs of the ``serving.network.*`` config
    group onto :class:`FrontDoorParams`."""
    return FrontDoorParams(
        queue_token_budget=int(
            getattr(ncfg, "queue_token_budget", 32768)),
        retry_after_s=float(getattr(ncfg, "retry_after_s", 1.0)),
        sse_heartbeat_s=float(getattr(ncfg, "sse_heartbeat_s", 5.0)),
        access_log=str(getattr(ncfg, "access_log", "") or ""),
        access_log_max_bytes=int(
            getattr(ncfg, "access_log_max_bytes", 8 << 20)))


class _DoorHandler(BaseHTTPRequestHandler):
    server_version = "ds-serving-frontdoor/1"
    protocol_version = "HTTP/1.1"

    # -- plumbing ------------------------------------------------------------

    def log_message(self, format: str, *args) -> None:
        logger.debug("frontdoor: " + format % args)

    def _door(self) -> "FrontDoor":
        return self.server.door  # type: ignore[attr-defined]

    def _send_json(self, code: int, doc: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
        body = (json.dumps(doc) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        trace = getattr(self, "_trace_id", None)
        if trace is not None:
            # the trace id is echoed on EVERY reply — a 429 or a 400 is
            # exactly when the client wants something to correlate with
            self.send_header(TRACE_HEADER, trace)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _log_access(self, status: int, klass: Optional[str] = None,
                    tokens: int = 0, close: str = "done",
                    t0: Optional[float] = None,
                    ttft_ms: Optional[float] = None) -> None:
        door = self._door()
        if self.command == "POST" and self.path.startswith("/v1/generate"):
            door.count_request(int(status), close)
        log = door.access_log
        if log is None:
            return
        import time as _time

        # prompt/max-new lengths and TTFT make the line a REPLAYABLE
        # record (serving/replay.py): the load the request carried and
        # the latency it saw, not just that it happened
        meta = getattr(self, "_req_meta", None)
        log.write(method=self.command, path=self.path, status=int(status),
                  klass=klass, trace=getattr(self, "_trace_id", None),
                  duration_ms=(round((_time.perf_counter() - t0) * 1e3, 3)
                               if t0 is not None else None),
                  tokens=int(tokens), close=str(close),
                  prompt_tokens=(meta[0] if meta else None),
                  max_new_tokens=(meta[1] if meta else None),
                  ttft_ms=(round(float(ttft_ms), 3)
                           if ttft_ms is not None else None),
                  peer=(self.client_address[0]
                        if self.client_address else None))

    # -- admin: fleet profiler capture (ISSUE 20) ----------------------------

    def _handle_debug_profile(self, t0: float) -> None:
        """``POST /debug/profile`` — the serving fleet's capture trigger.
        Body (all optional): ``{"duration_ms": 250, "steps": 4,
        "mode": "duration"}``.  Answers with the request id; lanes are
        collected with ``telemetry profile`` (or ``profile report``)
        against the same store."""
        door = self._door()
        if not door.store_endpoint:
            self._send_json(503, {
                "error": "no rendezvous store — the door was started "
                         "without store_endpoint, so there is no command "
                         "channel to the workers"})
            self._log_access(503, close="no_store", t0=t0)
            return
        body: Dict[str, Any] = {}
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
            if length > 0:
                body = json.loads(self.rfile.read(length))
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as e:
            self.close_connection = True
            self._send_json(400, {"error": f"malformed body: {e}"},
                            headers={"Connection": "close"})
            self._log_access(400, close="validation", t0=t0)
            return
        try:
            from ..elasticity.rendezvous import RendezvousClient
            from ..telemetry.profiler import post_capture_command

            client = RendezvousClient(door.store_endpoint)
            req = post_capture_command(
                client,
                steps=int(body.get("steps", 4)),
                lead=int(body.get("lead", 3)),
                mode=str(body.get("mode", "duration")),
                duration_ms=float(body.get("duration_ms", 250.0)))
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
            self._log_access(400, close="validation", t0=t0)
            return
        except (ConnectionError, OSError) as e:
            self._send_json(503, {
                "error": f"rendezvous store unreachable: {e}"})
            self._log_access(503, close="store_down", t0=t0)
            return
        self._send_json(202, {
            "req": req,
            "mode": str(body.get("mode", "duration")),
            "hint": f"collect with: telemetry profile capture "
                    f"--endpoint {door.store_endpoint}"})
        self._log_access(202, t0=t0)

    # -- GET -----------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (http.server convention)
        import time as _time

        t0 = _time.perf_counter()
        self._trace_id = None
        door = self._door()
        if self.path == "/healthz":
            healthy = door.frontend.healthy_count()
            doc = {"ok": healthy > 0, "healthy_replicas": healthy,
                   "mode": door.mode}
            code = 200 if healthy > 0 else 503
            self._send_json(code, doc)
            self._log_access(code, t0=t0)
            return
        if self.path == "/v1/metrics":
            self._send_json(200, door.frontend.snapshot())
            self._log_access(200, t0=t0)
            return
        self._send_json(404, {"error": f"no such path {self.path!r}"})
        self._log_access(404, close="bad_path", t0=t0)

    # -- POST ----------------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802
        import time as _time

        t0 = _time.perf_counter()
        self._req_meta = None
        # accept the edge's trace id, else mint one: every request is
        # traceable, and the id is echoed on every reply either way
        self._trace_id = (sanitize_trace_id(self.headers.get(TRACE_HEADER))
                          or mint_trace_id())
        if self.path == "/debug/profile":
            # fleet profiler capture (ISSUE 20): post a capture command
            # through the rendezvous store — every serving worker's beat
            # loop arms a duration-mode jax.profiler window and publishes
            # its decode-burst device lanes back
            self._handle_debug_profile(t0)
            return
        if self.path != "/v1/generate":
            self._send_json(404, {"error": f"no such path {self.path!r}"})
            self._log_access(404, close="bad_path", t0=t0)
            return
        door = self._door()
        params = door.params
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            # the body length is unknowable, so it cannot be drained:
            # close, or the unread bytes desync the next keep-alive
            # request on this connection
            self.close_connection = True
            self._send_json(400, {"error": "bad Content-Length"},
                            headers={"Connection": "close"})
            self._log_access(400, close="validation", t0=t0)
            return
        if length <= 0:
            # no usable Content-Length (absent, zero, or a chunked
            # body we don't read): anything the client DID send would
            # desync the next keep-alive request — close
            self.close_connection = True
            self._send_json(400, {"error": "empty request body "
                                           "(Content-Length required)"},
                            headers={"Connection": "close"})
            self._log_access(400, close="validation", t0=t0)
            return
        if length > params.max_body_bytes:
            # replying without reading the oversized body leaves it in
            # the socket — close instead of parsing it as a "request"
            self.close_connection = True
            self._send_json(413, {
                "error": f"body of {length} bytes exceeds "
                         f"{params.max_body_bytes}"},
                headers={"Connection": "close"})
            self._log_access(413, close="validation", t0=t0)
            return
        try:
            body = json.loads(self.rfile.read(length))
        except ValueError as e:
            self._send_json(400, {"error": f"malformed JSON body: {e}"})
            self._log_access(400, close="validation", t0=t0)
            return
        if not isinstance(body, dict):
            self._send_json(400, {"error": "body must be a JSON object"})
            self._log_access(400, close="validation", t0=t0)
            return
        klass = (self.headers.get(CLASS_HEADER)
                 or body.get("class") or "interactive")
        if klass not in CLASSES:
            self._send_json(400, {
                "error": f"class: unknown latency class {klass!r} "
                         f"(one of {', '.join(CLASSES)})"})
            self._log_access(400, close="validation", t0=t0)
            return
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            self._send_json(400, {
                "error": "prompt: must be a non-empty token list"})
            self._log_access(400, klass=klass, close="validation", t0=t0)
            return
        if not all(isinstance(t, int) and not isinstance(t, bool)
                   for t in prompt):
            self._send_json(400, {
                "error": "prompt: every token must be an integer"})
            self._log_access(400, klass=klass, close="validation", t0=t0)
            return
        max_new = body.get("max_new_tokens", 64)
        try:
            max_new = int(max_new)
            self._req_meta = (len(prompt), max_new)
            door.frontend.validate(prompt, max_new)
        except (TypeError, ValueError) as e:
            self._send_json(400, {"error": str(e)})
            self._log_access(400, klass=klass, close="validation", t0=t0)
            return
        # backpressure BEFORE anything is queued: the class budget is
        # in tokens, so one huge batch request cannot hide behind a
        # small queue length
        tokens = len(prompt) + max_new
        queued = door.frontend.queued_tokens(klass)
        if queued + tokens > params.queue_token_budget:
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                "serving/backpressure_429_total",
                help="requests shed with 429 (class token budget full)")
            self._send_json(
                429,
                {"error": f"{klass} queue over its token budget "
                          f"({queued}/{params.queue_token_budget} "
                          f"queued); retry later",
                 "queued_tokens": queued},
                headers={"Retry-After":
                         str(max(1, int(round(params.retry_after_s))))})
            self._log_access(429, klass=klass, close="shed", t0=t0)
            return
        try:
            handle = door.frontend.submit(prompt, max_new, klass,
                                          trace_id=self._trace_id)
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
            self._log_access(400, klass=klass, close="validation", t0=t0)
            return
        except NoHealthyReplicaError as e:
            self._send_json(503, {"error": str(e)})
            self._log_access(503, klass=klass, close="no_replica", t0=t0)
            return
        if bool(body.get("stream", True)):
            self._stream_sse(handle, t0)
        else:
            self._blocking_result(handle, t0)

    # -- response modes -------------------------------------------------------

    def _summary(self, handle: Any) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "status": handle.status,
            "tokens_delivered": handle.delivered,
            "replays": handle.replays,
            "trace_id": handle.trace_id,
            "ttft_ms": (round(handle.ttft_ms, 3)
                        if handle.ttft_ms is not None else None)}
        if handle.ttft_breakdown:
            out["ttft_breakdown_ms"] = {
                k.replace("_ms", ""): round(v, 3)
                for k, v in handle.ttft_breakdown.items()}
        return out

    def _blocking_result(self, handle: Any, t0: float) -> None:
        try:
            toks = handle.result(
                timeout=self._door().params.result_timeout_s)
        except Exception as e:
            self._send_json(500, {"error": str(e),
                                  "status": handle.status})
            self._log_access(500, klass=handle.klass,
                             tokens=handle.delivered, close="error",
                             t0=t0)
            return
        doc = {"tokens": toks}
        doc.update(self._summary(handle))
        self._send_json(200, doc)
        self._log_access(200, klass=handle.klass, tokens=len(toks),
                         close="done", t0=t0, ttft_ms=handle.ttft_ms)

    def _stream_sse(self, handle: Any, t0: float) -> None:
        door = self._door()
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        trace = getattr(self, "_trace_id", None)
        if trace is not None:
            self.send_header(TRACE_HEADER, trace)
        # close-delimited body: no Content-Length for an unbounded
        # stream, and the close tells the client the stream is over
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        i = 0
        try:
            while True:
                kind, value = handle.next_event(
                    timeout=door.params.sse_heartbeat_s)
                if kind == "timeout":
                    # comment heartbeat: keeps proxies open AND makes a
                    # vanished client raise here instead of never
                    self.wfile.write(b": hb\n\n")
                    self.wfile.flush()
                    continue
                if kind == "token":
                    payload = json.dumps({"i": i, "token": value})
                    self.wfile.write(
                        f"event: token\ndata: {payload}\n\n".encode())
                    self.wfile.flush()
                    i += 1
                    continue
                # done
                err = value
                if err is not None:
                    payload = json.dumps({"error": str(err),
                                          "status": handle.status,
                                          "trace_id": handle.trace_id})
                    self.wfile.write(
                        f"event: error\ndata: {payload}\n\n".encode())
                else:
                    # the done event carries the trace id (_summary):
                    # the SSE client's end of the correlation contract
                    payload = json.dumps(self._summary(handle))
                    self.wfile.write(
                        f"event: done\ndata: {payload}\n\n".encode())
                self.wfile.flush()
                self._log_access(200, klass=handle.klass, tokens=i,
                                 close=("error" if err is not None
                                        else "done"), t0=t0,
                                 ttft_ms=handle.ttft_ms)
                return
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client went away mid-stream: cancel so abandoned
            # work frees its decode slot and KV pages immediately
            try:
                door.frontend.cancel(handle)
            finally:
                from ..telemetry import get_telemetry

                get_telemetry().inc_counter(
                    "serving/cancelled_on_disconnect_total",
                    help="streams cancelled because the client "
                         "disconnected")
                self._log_access(200, klass=handle.klass, tokens=i,
                                 close="client_disconnect", t0=t0)


class FrontDoor:
    """The HTTP server around a serving front-end.  ``frontend`` is a
    :class:`~.frontend.ServingFrontend` or
    :class:`~.remote.NetworkFrontend`; the door starts the front-end's
    pump thread with :meth:`start` and owns its shutdown."""

    def __init__(self, frontend: Any, host: str = "127.0.0.1",
                 port: int = 0,
                 params: Optional[FrontDoorParams] = None,
                 own_frontend: bool = True,
                 store_endpoint: Optional[str] = None,
                 node_id: str = "frontdoor",
                 telemetry_push_every_s: float = 1.0,
                 slo_cfg: Optional[Any] = None):
        self.frontend = frontend
        self.params = params or FrontDoorParams()
        #: the SLO monitor (ISSUE 16) lives with the door: its registry
        #: holds every signal the objectives read (per-class percentile
        #: gauges, 429/5xx counters, the queued-token gauges published
        #: each beat), and its publisher ships the resulting
        #: ``serving/slo_*`` gauges + health events on the rollup
        self.slo: Optional[Any] = None
        if slo_cfg is not None and getattr(slo_cfg, "enabled", False):
            from ..telemetry import get_telemetry
            from ..telemetry.flight_recorder import get_flight_recorder
            from .slo import SLOMonitor

            self.slo = SLOMonitor.from_config(
                slo_cfg, registry=get_telemetry().registry,
                recorder=get_flight_recorder())
            self._slo_every_s = max(
                0.1, float(getattr(slo_cfg, "evaluate_every_s", 1.0)))
        self._slo_last_mono = 0.0
        self.own_frontend = bool(own_frontend)
        self.mode = ("network"
                     if hasattr(frontend, "endpoints") else "local")
        self.access_log: Optional[AccessLog] = None
        if self.params.access_log:
            self.access_log = AccessLog(
                self.params.access_log,
                max_bytes=self.params.access_log_max_bytes)
        #: with a store endpoint, the door publishes its telemetry —
        #: registry snapshot AND its request-record stream — on the
        #: PR-13 rollup transport, clock-synced: the front-door lane of
        #: every `serving trace` timeline comes from here
        self.store_endpoint = store_endpoint
        self.node_id = str(node_id)
        self.telemetry_push_every_s = float(telemetry_push_every_s)
        self._push_stop = threading.Event()
        self._push_thread: Optional[threading.Thread] = None
        self._srv = ThreadingHTTPServer((host, int(port)), _DoorHandler)
        self._srv.daemon_threads = True
        self._srv.door = self  # type: ignore[attr-defined]
        self.host = host or "127.0.0.1"
        self.port = int(self._srv.server_address[1])
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def count_request(self, status: int, close: str = "done") -> None:
        """Availability accounting for every POST /v1/generate reply —
        the denominators and numerators the ``availability`` SLO
        differentiates (a stream that 200-OKed its headers but ended in
        ``event: error`` counts as a failure too)."""
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        tel.inc_counter("serving/http_requests_total",
                        help="front-door /v1/generate requests")
        if status >= 500 or close == "error":
            tel.inc_counter("serving/http_5xx_total",
                            help="front-door 5xx replies and failed "
                                 "streams")

    def slo_tick(self, now_mono: Optional[float] = None,
                 force: bool = False) -> None:
        """One SLO evaluation: publish the door's queued-token gauges,
        reduce the local registry snapshot to a fleet sample, feed the
        monitor.  Called from the publisher beat; tests call it
        directly (no store required).  ``force`` skips the cadence gate
        (a final end-of-run evaluation must not be dropped)."""
        if self.slo is None:
            return
        import time as _time

        now = _time.monotonic() if now_mono is None else now_mono
        if not force and now - self._slo_last_mono < self._slo_every_s:
            return
        self._slo_last_mono = now
        from ..telemetry import get_telemetry
        from .slo import sample_from_snapshot

        tel = get_telemetry()
        try:
            for c in CLASSES:
                tel.set_gauge(
                    f"serving/door_queued_tokens_{c}",
                    float(self.frontend.queued_tokens(c)),
                    help=f"tokens queued at the door, class {c}")
        except Exception as e:
            warn_once("serving/door-queued-gauges",
                      f"queued-token gauge publish failed ({e!r})")
        self.slo.observe(sample_from_snapshot(
            tel.registry.snapshot(),
            queue_token_budget=self.params.queue_token_budget))

    def start(self) -> None:
        if self._thread is not None:
            return
        self.frontend.start()
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name="ds-serving-frontdoor")
        self._thread.start()
        if self.store_endpoint and self._push_thread is None:
            self._push_thread = threading.Thread(
                target=self._push_loop, daemon=True,
                name="ds-serving-frontdoor-publish")
            self._push_thread.start()
        log_dist(f"serving front door ({self.mode} mode) at "
                 f"http://{self.endpoint}")

    def _push_loop(self) -> None:
        """The door's publisher beat (mirrors the worker's): clock sync
        + registry/request-record push, degraded-mode tolerant."""
        from ..elasticity.rendezvous import RendezvousClient
        from ..telemetry import maybe_sync_clock, push_node_telemetry

        client = None
        try:
            client = RendezvousClient(self.store_endpoint)
            if self.access_log is not None:
                # one registration, not a stream: `telemetry collect`
                # copies the live file + its rotated `.1` segment into
                # the archive from here (ISSUE 16 satellite)
                import os as _os

                client.set(f"telemetry/accesslog/{self.node_id}",
                           {"node": self.node_id,
                            "path": _os.path.abspath(
                                self.access_log.path)})
            while not self._push_stop.wait(self.telemetry_push_every_s):
                try:
                    maybe_sync_clock(client, node_id=self.node_id)
                    self.slo_tick()
                    push_node_telemetry(client, self.node_id)
                except Exception as e:  # store down: degraded, retry
                    warn_once("serving/frontdoor-push",
                              f"front-door telemetry push degraded "
                              f"({e!r})")
        except Exception as e:
            warn_once("serving/frontdoor-push-boot",
                      f"front-door publisher not started ({e!r})")
        finally:
            if client is not None:
                try:
                    client.close()
                except Exception as e:
                    logger.debug(f"frontdoor publisher close: {e!r}")

    def shutdown(self) -> None:
        self._push_stop.set()
        if self._push_thread is not None:
            self._push_thread.join(timeout=5.0)
            self._push_thread = None
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.own_frontend:
            self.frontend.close()
