"""Paged prefix-sharing KV cache — refcounted pages + hash-trie index.

vLLM's PagedAttention (arXiv 2309.06180 [P]) made the case that the
load-bearing primitive for multi-tenant serving throughput is not a
faster kernel but a *shared, reference-counted page pool*: identical
prompt prefixes (system prompts, few-shot headers) map to the SAME
immutable KV pages, so N concurrent requests with a 1k-token header pay
its HBM and its prefill FLOPs once.  This module is the TPU-native
version over ``inference/v2``'s block pool:

* :class:`RefcountedBlockAllocator` — the v2 free-list allocator plus a
  per-page reference count and a *cached-free* LRU tier: a page whose
  last holder releases it but whose content is indexed by the prefix
  trie goes to the cached tier instead of the free list.  Allocation
  prefers truly-free pages and only reclaims cached pages LRU-oldest —
  so prefix KV survives across requests exactly as long as the pool has
  slack, and evicts itself under pressure with zero policy code in the
  scheduler.
* :class:`PrefixCache` — a hash-trie keyed by *block-size token chunks*
  (dict lookup hashes the chunk; tuple equality makes collisions
  harmless).  ``match()`` walks a prompt down the trie and returns the
  shared pages covering its longest indexed prefix; ``insert()`` indexes
  a freshly prefilled prompt's full pages.

Copy-on-write lives at the divergence boundary: shared pages are
immutable (refcount > 1 or trie-indexed), and all KV writes happen in
full-page units, so when a prompt diverges *mid-block* from an indexed
chunk the writer gets a fresh private page and recomputes it — the
"copy" is a recompute because a partial-page device copy would cost more
than the chunk's prefill.  The ``cow_events`` counter makes the boundary
observable.  Decode writes can never land on a shared page by
construction: sharing is capped at the last *full* block strictly before
the prompt's final token, and decode appends strictly after the prompt.

Host-side only (like all v2 page bookkeeping): the device never sees any
of this — tables of ints go into the same compiled programs.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..inference.v2.kv_cache import BlockAllocator


class RefcountedBlockAllocator(BlockAllocator):
    """Free-list allocator + refcounts + a cached-free LRU tier.

    Page states: *free* (on the base free list), *active* (refcount >=
    1), *cached* (refcount 0, content still indexed by the prefix trie,
    reclaimable LRU-oldest-first).  ``num_available`` — free + cached —
    is what admission control budgets against.
    """

    def __init__(self, num_blocks: int, max_cached: int = 0,
                 evict_callback: Optional[Callable[[int], None]] = None):
        super().__init__(num_blocks)
        self._refs: Dict[int, int] = {}
        #: page -> None, insertion order == LRU order (oldest first)
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        #: cached pages kept at most (0 = bounded only by the pool)
        self.max_cached = int(max_cached)
        #: called with the page id when a cached page is reclaimed so the
        #: prefix trie drops the now-dangling index entry
        self._evict_callback = evict_callback

    def set_evict_callback(self, fn: Callable[[int], None]) -> None:
        self._evict_callback = fn

    # -- state queries -----------------------------------------------------

    @property
    def num_cached(self) -> int:
        return len(self._cached)

    @property
    def num_available(self) -> int:
        """Pages an allocation could obtain: truly free + reclaimable."""
        return self.num_free + len(self._cached)

    def refcount(self, b: int) -> int:
        return self._refs.get(b, 0)

    def is_cached(self, b: int) -> bool:
        return b in self._cached

    def _check_active(self, b: int) -> None:
        if b not in self._refs:
            raise ValueError(
                f"page {b} is not an active allocation (refcount 0): "
                f"double release, or a caller holding a stale block table")

    # -- allocation --------------------------------------------------------

    def allocate(self, n: int) -> List[int]:
        if n > self.num_available:
            raise MemoryError(
                f"KV pool exhausted: want {n} pages, {self.num_free} free "
                f"+ {len(self._cached)} cached-reclaimable")
        out: List[int] = []
        for _ in range(n):
            if self.num_free:
                b = self._free.pop()
                self._free_set.discard(b)
            else:
                b = self._reclaim_oldest_cached()
            self._refs[b] = 1
            out.append(b)
        return out

    def _reclaim_oldest_cached(self) -> int:
        b, _ = self._cached.popitem(last=False)
        if self._evict_callback is not None:
            # the callback prunes the trie subtree under this page, which
            # may UNCACHE further pages (they land on the plain free
            # list) — safe mid-allocation, the loop above re-checks
            self._evict_callback(b)
        return b

    def acquire(self, b: int) -> bool:
        """Add a reference to a shared page: retains an active page, or
        revives a cached one.  Returns True when the page was revived
        from the cached tier (a prefix *reuse across requests*)."""
        if b in self._refs:
            self._refs[b] += 1
            return False
        if b in self._cached:
            del self._cached[b]
            self._refs[b] = 1
            return True
        raise ValueError(
            f"page {b} is neither active nor cached — the prefix index "
            f"returned a page the allocator no longer tracks")

    # -- release -----------------------------------------------------------

    def release(self, blocks: List[int],
                cache_fn: Optional[Callable[[int], bool]] = None
                ) -> List[int]:
        """Drop one reference per page; pages reaching refcount 0 either
        enter the cached tier (``cache_fn(page)`` true — the trie still
        indexes them) or return to the free list.  Returns the pages
        that became reclaimable/free this call."""
        freed: List[int] = []
        for b in blocks:
            self._check_active(b)
            self._refs[b] -= 1
            if self._refs[b] > 0:
                continue
            del self._refs[b]
            freed.append(b)
            if cache_fn is not None and cache_fn(b):
                self._cached[b] = None
                self._enforce_cap()
            else:
                super().free([b])
        return freed

    def uncache(self, b: int) -> None:
        """Move a cached page to the plain free list (trie pruned it)."""
        if b in self._cached:
            del self._cached[b]
            super().free([b])

    def _enforce_cap(self) -> None:
        if self.max_cached <= 0:
            return
        while len(self._cached) > self.max_cached:
            b = self._reclaim_oldest_cached()
            super().free([b])

    def free(self, blocks: List[int]) -> None:
        """Base-scheduler compatibility: a plain free is a release that
        never caches.  Refcounted pages must go through :meth:`release`;
        freeing a page other holders still reference is the exact bug
        refcounting exists to prevent, so it raises."""
        for b in blocks:
            self._check_active(b)
            if self._refs[b] > 1:
                raise ValueError(
                    f"free of page {b} with refcount {self._refs[b]}: "
                    f"other requests still read this shared page — use "
                    f"release()")
        self.release(blocks)


class PrefixCache:
    """Hash-trie over block-size token chunks -> shared page ids.

    One node per indexed chunk; the path from the root spells a prompt
    prefix in whole blocks.  Children are keyed ``(parent_node, chunk
    tuple)`` in one flat dict, so matching a prompt is O(blocks) dict
    hits.  The trie holds **no references** of its own — liveness is the
    allocator's cached tier; when the allocator reclaims a cached page
    the eviction callback prunes the page's node *and its subtree*
    (descendant chunks are unreachable without their parent).
    """

    _ROOT = -1

    def __init__(self, allocator: RefcountedBlockAllocator,
                 block_size: int, enabled: bool = True):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.enabled = bool(enabled)
        #: node id -> {parent, chunk, block, children}; the synthetic
        #: root node anchors first-block chunks
        self._nodes: Dict[int, Dict[str, Any]] = {
            self._ROOT: {"parent": None, "chunk": (), "block": 0,
                         "children": []}}
        self._children: Dict[Tuple[int, Tuple[int, ...]], int] = {}
        self._by_block: Dict[int, int] = {}
        self._next_id = 0
        # counters (read by serving metrics)
        self.lookup_tokens = 0
        self.hit_tokens = 0
        self.cow_events = 0
        self.inserts = 0
        self.evictions = 0
        self.revivals = 0
        allocator.set_evict_callback(self._on_evict)

    # -- matching ----------------------------------------------------------

    def _walk(self, prompt: List[int]
              ) -> Tuple[List[int], int, Optional[Tuple[int, ...]]]:
        """Walk the trie along ``prompt``'s whole-block chunks →
        (shared page ids in sequence order, node where the walk
        stopped, the first unmatched chunk — ``None`` if every whole
        block matched)."""
        bs = self.block_size
        blocks: List[int] = []
        parent = self._ROOT
        for i in range(len(prompt) // bs):
            chunk = tuple(prompt[i * bs:(i + 1) * bs])
            node_id = self._children.get((parent, chunk))
            if node_id is None:
                return blocks, parent, chunk
            blocks.append(self._nodes[node_id]["block"])
            parent = node_id
        return blocks, parent, None

    def match(self, prompt: List[int]) -> List[int]:
        """Longest indexed prefix of ``prompt`` in whole blocks →
        the shared page ids, in sequence order.  Strictly read-only: no
        refcount movement (``acquire`` commits a match at admission) and
        no counter movement (``count_mid_block_divergence`` records CoW
        only when a reservation commits)."""
        if not self.enabled:
            return []
        return self._walk(prompt)[0]

    def count_mid_block_divergence(self, prompt: List[int]) -> bool:
        """Count one copy-on-write event if ``prompt`` diverges from the
        trie *mid-block* — some indexed chunk shares a proper prefix
        with the diverging chunk, so an unpaged design would have shared
        that page and forked it.  Called ONLY when a reservation
        commits: advisory matches (admission checks, router affinity
        scoring) AND capacity-deferred reservations re-walk the same
        queued prompt every pump round — a page-blocked head at the
        front of the waiting deque must not inflate the counter."""
        if not self.enabled:
            return False
        _, parent, stopped = self._walk(prompt)
        if stopped is not None and self._diverges_mid_block(parent, stopped):
            self.cow_events += 1
            return True
        return False

    def _diverges_mid_block(self, parent: int, chunk: Tuple[int, ...]
                            ) -> bool:
        for nid in self._nodes[parent]["children"]:
            other = self._nodes[nid]["chunk"]
            if other and chunk and other[0] == chunk[0] and other != chunk:
                return True
        return False

    def acquire(self, blocks: List[int]) -> None:
        """Commit a match: one reference per shared page for the
        admitted request (revivals counted — those are the cross-request
        reuse the cache exists for)."""
        for b in blocks:
            if self.allocator.acquire(b):
                self.revivals += 1

    def record_lookup(self, prompt_tokens: int, reused_tokens: int) -> None:
        self.lookup_tokens += int(prompt_tokens)
        self.hit_tokens += int(reused_tokens)

    @property
    def hit_rate(self) -> float:
        """Fraction of looked-up prompt tokens served from shared pages."""
        return self.hit_tokens / self.lookup_tokens if self.lookup_tokens \
            else 0.0

    # -- insertion ---------------------------------------------------------

    def insert(self, prompt: List[int], blocks: List[int]) -> int:
        """Index a prefilled prompt's full pages.  Chunks already present
        keep their existing (shared) page — the request's private
        duplicate page stays private and frees normally.  Returns the
        number of new trie nodes."""
        if not self.enabled:
            return 0
        bs = self.block_size
        parent = self._ROOT
        added = 0
        for i in range(len(prompt) // bs):
            chunk = tuple(prompt[i * bs:(i + 1) * bs])
            key = (parent, chunk)
            node_id = self._children.get(key)
            if node_id is None:
                if i >= len(blocks):
                    break
                node_id = self._next_id
                self._next_id += 1
                self._nodes[node_id] = {"parent": parent, "chunk": chunk,
                                        "block": blocks[i], "children": []}
                self._children[key] = node_id
                self._by_block[blocks[i]] = node_id
                self._nodes[parent]["children"].append(node_id)
                added += 1
            parent = node_id
        self.inserts += added
        return added

    def is_indexed(self, b: int) -> bool:
        """The allocator's ``cache_fn``: released pages the trie still
        points at enter the cached tier instead of the free list."""
        return b in self._by_block

    # -- eviction ----------------------------------------------------------

    def _on_evict(self, block: int) -> None:
        """Allocator reclaimed cached page ``block``: prune its node and
        the whole subtree under it (children are unreachable without the
        parent).  Subtree pages still in the cached tier move to the
        plain free list; active descendants cannot exist — an active
        child implies the request also holds the parent, which would
        have kept it out of the cached tier."""
        node_id = self._by_block.pop(block, None)
        if node_id is None:
            return
        stack = [node_id]
        while stack:
            nid = stack.pop()
            node = self._nodes.pop(nid, None)
            if node is None:
                continue
            self._children.pop((node["parent"], node["chunk"]), None)
            if node["parent"] in self._nodes:
                try:
                    self._nodes[node["parent"]]["children"].remove(nid)
                except ValueError:
                    pass
            b = node["block"]
            if b != block:  # the triggering page is being reallocated
                self._by_block.pop(b, None)
                self.allocator.uncache(b)
            stack.extend(node["children"])
            self.evictions += 1

    def drop_all(self) -> None:
        """Evict every cached prefix page (operator flush / test seam)."""
        while self.allocator.num_cached:
            b = next(iter(self.allocator._cached))
            self.allocator.uncache(b)
            self._on_evict(b)

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {"nodes": len(self._nodes),
                "cached_blocks": self.allocator.num_cached,
                "lookup_tokens": self.lookup_tokens,
                "hit_tokens": self.hit_tokens,
                "hit_rate": round(self.hit_rate, 4),
                "cow_events": self.cow_events,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "revivals": self.revivals}
