"""SLO-aware streaming front-end — submit / stream / cancel over
latency-class queues with admission control and preemption.

This is the layer that turns the v2 engine loop into a *service*:

* **submit(prompt, klass)** validates the request (the scheduler's
  field-naming validation runs at the front door), assigns it a
  latency class (``interactive`` / ``batch`` / ``background``), and
  queues it.  The returned :class:`ServingHandle` streams tokens as
  they are accepted (``stream()``), collects them (``result()``), or
  aborts (``cancel()``).
* **Admission control** drains class queues in strict priority order
  each pump: a request is admitted to its routed replica only when (a)
  the replica has a free decode slot and enough KV pages (prefix
  matches counted — a 90%-shared prompt is cheap to admit), (b) the
  replica's outstanding-token budget has room, (c) for non-interactive
  classes, admission leaves an interactive page reserve, and (d) the
  PR-7 memory ledger's HBM headroom (when it has device numbers) is
  above the configured floor — under memory pressure only interactive
  work is admitted.
* **Preemption**: when the interactive queue cannot place its head, a
  RUNNING background request is bumped out of its decode slot
  (``ServingScheduler.preempt`` — KV pages stay referenced, host state
  intact) and re-queued at the front of its class; it resumes in place
  later.  Interactive latency is bounded by a burst length, not by a
  background request's remaining budget.
* **Replica drain**: a replica that goes unhealthy (probe, device
  latch, watchdog trip) has its in-flight work re-queued onto healthy
  replicas.  Already-streamed tokens are not re-delivered: re-execution
  regenerates the sequence and delivery resumes past the high-water
  mark (exact for greedy decode; sampled streams may diverge at the
  splice point, which is recorded on the handle).

The front-end is driven either manually (``pump()`` — deterministic,
what the tests and an external event loop use) or by its own thread
(``start()``/``stop()``).  All mutable front-end state is guarded by
one re-entrant lock; token delivery to consumers goes through
per-handle thread-safe queues.  The clock is injectable, so SLO tests
measure TTFT distributions deterministically against a fake clock
advanced by the synthetic engine.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from ..utils.logging import log_dist, warn_once
from .metrics import CLASSES, ServingMetrics
from .router import Replica, ReplicaRouter

_DONE = object()


class NoHealthyReplicaError(RuntimeError):
    """Every replica behind the front-end is dead (probe / device latch
    / watchdog) — pending work cannot make progress."""


@dataclasses.dataclass
class ServingParams:
    """Resolved front-end knobs (the ``serving.*`` config group maps
    onto this; tests construct it directly)."""

    #: per-replica admitted-but-unfinished token budget
    max_outstanding_tokens: int = 8192
    #: fraction of the allocatable pool kept free of batch/background
    #: reservations so interactive admission never waits on pages
    interactive_reserve_frac: float = 0.10
    #: admit only interactive work when the memory ledger reports HBM
    #: headroom below this fraction (0 disables the check)
    min_hbm_headroom_frac: float = 0.0
    #: allow interactive to preempt background decode slots
    preemption: bool = True
    #: router prefix-affinity threshold (tokens)
    affinity_min_tokens: int = 16
    #: sampling temperature for every decode dispatch (0 = greedy;
    #: greedy is what makes replica-death re-queue splice-exact)
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    #: per-handle stream bound (tokens): a consumer that stalls past
    #: this many unread tokens loses the OLDEST ones (drop-oldest), so
    #: the pump never blocks — size it to the longest generation whose
    #: full transcript must survive an unread buffer (``result()`` only
    #: returns what the buffer retained)
    stream_buffer: int = 4096
    #: interactive TTFT target (ms) — exported with the metrics so the
    #: bench/SLO gate reads the bound it asserts against
    interactive_ttft_slo_ms: float = 500.0
    #: under the HBM-headroom floor, preemption RELEASES the victim's
    #: KV pages back to the cached-free LRU tier (trie-indexed prompt
    #: pages stay revivable; re-admission recomputes the rest and the
    #: stream splices past the delivered high-water mark) instead of
    #: keeping them resident
    preempt_release_pages: bool = True


class ServingHandle:
    """One submitted request: stream / result / cancel surface."""

    def __init__(self, uid: int, prompt: List[int], max_new_tokens: int,
                 klass: str, submitted_at: float, frontend:
                 "ServingFrontend", stream_buffer: int):
        self.uid = uid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.klass = klass
        self.submitted_at = submitted_at
        self.status = "queued"  # queued|running|done|cancelled|failed
        self.replica_id: Optional[int] = None
        self.request: Any = None          # live scheduler Request
        self.preempted = False
        self.pinned_replica: Optional[int] = None
        self.delivered = 0                # tokens pushed to the stream
        self.consumed = 0                 # tokens read off request
        self.dropped = 0                  # tokens evicted unread (full
                                          # buffer, stalled consumer)
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.admitted_at: Optional[float] = None
        self.error: Optional[BaseException] = None
        self.replays = 0                  # replica-death re-executions
        #: disaggregated serving: {"prefill_ms", "transfer_ms",
        #: "decode_ms"} TTFT attribution (None for colocated requests)
        self.ttft_breakdown: Optional[Dict[str, float]] = None
        #: distributed tracing (ISSUE 15): the propagated trace id and
        #: this process's lifecycle record for the request
        self.trace_id: Optional[str] = None
        self.record: Any = None
        self._frontend = frontend
        # a REAL bound: when a stalled consumer lets it fill, _push
        # drops the oldest undelivered token — the pump never blocks
        self._queue: "queue.Queue" = queue.Queue(
            maxsize=max(1, int(stream_buffer)))

    # -- consumer surface --------------------------------------------------

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield generated tokens as they arrive; raises the handle's
        error if the request failed.  With ``timeout`` per token."""
        while True:
            item = self._queue.get(timeout=timeout)
            if item is _DONE:
                if self.error is not None:
                    raise self.error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        return list(self.stream(timeout=timeout))

    def drain(self) -> "tuple[List[int], bool]":
        """Non-blocking: every currently-buffered token plus a
        completion flag.  The replica-worker protocol's ``poll`` op
        reads the stream this way (a socket peer cannot park in
        :meth:`stream`)."""
        toks: List[int] = []
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return toks, False
            if item is _DONE:
                return toks, True
            toks.append(int(item))

    def next_event(self, timeout: Optional[float] = None) -> "tuple":
        """One stream event for push-style consumers (the SSE writer):
        ``("token", t)`` / ``("done", error)`` / ``("timeout", None)``
        when nothing arrived within ``timeout`` — the caller emits a
        heartbeat and retries, detecting dead sockets between tokens."""
        try:
            item = self._queue.get(timeout=timeout)
        except queue.Empty:
            return ("timeout", None)
        if item is _DONE:
            return ("done", self.error)
        return ("token", int(item))

    def cancel(self) -> None:
        self._frontend.cancel(self)

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.submitted_at) * 1e3

    def _put_drop_oldest(self, item: Any) -> None:
        """Bounded stream, slow consumer: evict the oldest unread token
        so the pump never blocks (``dropped`` makes the loss visible —
        completion still lands even on a full buffer)."""
        while True:
            try:
                self._queue.put_nowait(item)
                return
            except queue.Full:
                try:
                    self._queue.get_nowait()
                    self.dropped += 1
                except queue.Empty:  # consumer drained it concurrently
                    pass

    def _push(self, tok: int) -> None:
        self._put_drop_oldest(tok)

    def _finish(self, status: str,
                error: Optional[BaseException] = None) -> None:
        self.status = status
        self.error = error
        if self.record is not None:
            # the ONE terminal point both front-ends and the worker's
            # local pump share: close + commit the lifecycle record
            # (the ring decides sampled-or-anomalous)
            from .tracing import get_request_log

            self.record.finish(status, ttft_ms=self.ttft_ms, error=error,
                               breakdown=self.ttft_breakdown)
            get_request_log().commit(self.record)
        self._put_drop_oldest(_DONE)


class ServingFrontend:
    def __init__(self, replicas: List[Replica],
                 params: Optional[ServingParams] = None,
                 clock=time.monotonic):
        self.params = params or ServingParams()
        self.router = ReplicaRouter(
            replicas, affinity_min_tokens=self.params.affinity_min_tokens)
        self.clock = clock
        self.metrics = ServingMetrics()
        self._queues: Dict[str, List[ServingHandle]] = {
            c: [] for c in CLASSES}
        self._uid = 0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._drained: set = set()  # replica ids already drained
        self._watchdogs: List[Any] = []  # for detach on close()
        self._round = 0  # pump round counter: probe-memo invalidation
        self._attach_recorder()

    def _attach_recorder(self) -> None:
        """Every debug bundle gets a ``serving`` section."""
        try:
            from ..telemetry import get_flight_recorder

            rec = get_flight_recorder()
            if rec is not None:
                rec.register_context("serving", self.snapshot)
        except Exception as e:
            warn_once("serving/recorder",
                      f"flight-recorder attach failed ({e!r})")

    def attach_watchdog(self, watchdog: Any) -> None:
        """Replica health rides the existing hang watchdog: a trip means
        the process's device work is stuck, so every in-process replica
        drains (their queued work would blackhole otherwise)."""
        watchdog.add_trip_listener(self._on_watchdog_trip)
        self._watchdogs.append(watchdog)

    def close(self) -> None:
        """Stop the pump thread and detach from the process-global hooks
        (flight-recorder context provider, watchdog trip listeners).
        Without this, those hooks keep the front-end — and through it
        every replica's engine, model params, and KV pool — alive for
        the life of the process."""
        self.stop()
        for wd in self._watchdogs:
            try:
                wd.remove_trip_listener(self._on_watchdog_trip)
            except Exception as e:
                warn_once("serving/watchdog-detach",
                          f"watchdog detach failed ({e!r})")
        self._watchdogs.clear()
        try:
            from ..telemetry import get_flight_recorder

            rec = get_flight_recorder()
            if rec is not None:
                rec.unregister_context("serving")
        except Exception as e:
            warn_once("serving/recorder-detach",
                      f"flight-recorder detach failed ({e!r})")

    def _on_watchdog_trip(self, reason: str, bundle: Optional[str]) -> None:
        # deliberately LOCKLESS: the trip fires precisely when a pump
        # thread may be wedged inside a device call while holding
        # self._lock — taking it here would deadlock the watchdog (and
        # every listener behind us, including the emergency snapshot).
        # mark_dead is a sticky one-shot attribute write on a replica
        # list that never mutates; the pump observes it at its next
        # health check.
        for r in self.router.replicas:
            if r.dead_reason is None:
                r.mark_dead(f"watchdog trip: {reason}")

    # -- request surface ---------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 64,
               klass: str = "interactive",
               trace_id: Optional[str] = None,
               sampled: Optional[bool] = None) -> ServingHandle:
        """``trace_id``/``sampled`` propagate the distributed trace
        context (ISSUE 15): the front door passes the minted/accepted
        id through; absent, one is minted here so every request is
        traceable.  ``sampled`` overrides the head-based decision (an
        upstream hop that knows the request is anomalous forces it)."""
        if klass not in CLASSES:
            raise ValueError(f"klass: unknown latency class {klass!r} "
                             f"(one of {', '.join(CLASSES)})")
        with self._lock:
            healthy = self.router.healthy()
            if not healthy:
                raise NoHealthyReplicaError(
                    "submit rejected: no healthy replica "
                    + "; ".join(f"replica{r.id}: {r.dead_reason}"
                                for r in self.router.replicas))
            # field-naming validation at the front door (the scheduler's
            # checks — empty prompt, max_new_tokens<=0, pool-impossible)
            healthy[0].scheduler.validate(list(prompt), max_new_tokens)
            if max_new_tokens >= self.params.stream_buffer:
                # the bounded buffer cannot hold the full generation: a
                # consumer that only reads after completion (the
                # submit -> run_until_idle -> result() pattern) will see
                # a truncated transcript (handle.dropped counts it)
                warn_once(
                    "serving/stream-buffer",
                    f"max_new_tokens {max_new_tokens} >= stream_buffer "
                    f"{self.params.stream_buffer}: an unread stream "
                    f"drops its oldest tokens")
            h = ServingHandle(self._uid, list(prompt), int(max_new_tokens),
                              klass, self.clock(), self,
                              self.params.stream_buffer)
            self._uid += 1
            from .tracing import get_request_log, mint_trace_id

            h.trace_id = trace_id or mint_trace_id()
            h.record = get_request_log().start(
                h.trace_id, h.uid, klass, len(prompt),
                int(max_new_tokens), sampled=sampled)
            h.record.event("submitted")
            self._queues[klass].append(h)
            self.metrics.inc("submitted")
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                f"serving/{klass}_submitted",
                help="requests submitted per latency class")
            return h

    def validate(self, prompt: List[int], max_new_tokens: int) -> None:
        """The scheduler's request validation, surfaced for the network
        front door: raises ``ValueError`` naming the offending field —
        the HTTP layer maps it to a 400 BEFORE anything is queued."""
        with self._lock:
            reps = self.router.replicas
            (self.router.healthy() or reps)[0].scheduler.validate(
                list(prompt), int(max_new_tokens))

    def queued_tokens(self, klass: str) -> int:
        """Admission-queue depth in TOKENS (prompt + generation budget)
        for one latency class — the front door's backpressure signal
        (429 + Retry-After when a class is over its token budget)."""
        with self._lock:
            return sum(len(h.prompt) + h.max_new_tokens
                       for h in self._queues.get(klass, ()))

    def healthy_count(self) -> int:
        """Replicas not marked dead (cheap — no probe RPCs): the
        ``/healthz`` answer."""
        return sum(1 for r in self.router.replicas
                   if r.dead_reason is None)

    def match_tokens(self, prompt: List[int]) -> int:
        """Best prefix-affinity score across replicas — the network
        router's placement signal (the worker protocol's ``match``)."""
        with self._lock:
            best = 0
            for r in self.router.replicas:
                sched = r.scheduler
                if hasattr(sched, "match_tokens"):
                    best = max(best, sched.match_tokens(list(prompt)))
            return best

    # -- disaggregated adoption (decode side) ------------------------------

    def adopt_begin(self, prompt: List[int], max_new_tokens: int,
                    klass: str = "interactive",
                    trace_id: Optional[str] = None,
                    sampled: Optional[bool] = None) -> "tuple":
        """Reserve pages + a slot for a request prefilled ELSEWHERE.
        Returns ``(handle, need)`` — ``need`` is the list of prompt-page
        indices the KV transfer must fill (trie-shared pages excluded)
        — or ``(None, None)`` when capacity is unavailable."""
        with self._lock:
            healthy = self.router.healthy()
            if not healthy:
                raise NoHealthyReplicaError(
                    "adopt rejected: no healthy replica")
            rep = healthy[0]
            got = rep.scheduler.adopt_reserve(list(prompt),
                                              int(max_new_tokens))
            if got is None:
                return None, None
            req, need = got
            h = ServingHandle(self._uid, list(prompt), int(max_new_tokens),
                              klass, self.clock(), self,
                              self.params.stream_buffer)
            self._uid += 1
            from .tracing import get_request_log, mint_trace_id

            h.trace_id = trace_id or mint_trace_id()
            h.record = get_request_log().start(
                h.trace_id, h.uid, klass, len(prompt),
                int(max_new_tokens), sampled=sampled)
            h.record.event("adopt_reserve", replica=rep.id,
                           need_pages=len(need))
            h.request = req
            h.status = "adopting"
            h.replica_id = rep.id
            h.pinned_replica = rep.id
            return h, need

    def adopt_commit(self, handle: ServingHandle, first_token: int,
                     inject_fn=None) -> None:
        """The transferred pages arrived (verified): write them into
        the pool (``inject_fn`` runs under the front-end lock — the
        pump must not step the engine mid-write) and seat the request
        RUNNING.  Token delivery flows through the normal pump."""
        with self._lock:
            rep = self._replica_by_id(handle.pinned_replica)
            if rep is None or not rep.healthy():
                raise NoHealthyReplicaError(
                    "adopt_commit: adopting replica died mid-transfer")
            if inject_fn is not None:
                inject_fn()
            rep.scheduler.adopt_commit(handle.request, int(first_token),
                                       self.params.eos_token_id)
            handle.status = "running"
            handle.admitted_at = self.clock()
            if handle.record is not None:
                handle.record.event("admitted", replica=rep.id,
                                    adopted=True)
            rep.active.append(handle)

    def adopt_abort(self, handle: ServingHandle,
                    error: Optional[BaseException] = None) -> None:
        """Transfer failed: release the reservation and fail the
        handle (the caller re-routes at ITS layer with a fresh one)."""
        with self._lock:
            rep = self._replica_by_id(handle.pinned_replica)
            if rep is not None and handle.request is not None:
                rep.scheduler.adopt_abort(handle.request)
            handle._finish("failed", error)

    def cancel(self, handle: ServingHandle) -> None:
        with self._lock:
            if handle.status == "queued":
                try:
                    self._queues[handle.klass].remove(handle)
                except ValueError:
                    pass
                if handle.request is not None:
                    # preempted: pages are still reserved on its replica
                    rep = self._replica_by_id(handle.pinned_replica)
                    if rep is not None:
                        rep.scheduler.cancel(handle.request)
                self.metrics.inc("cancelled")
                handle._finish("cancelled")
            elif handle.status == "running":
                rep = self._replica_by_id(handle.replica_id)
                if rep is not None:
                    rep.scheduler.cancel(handle.request)
                    if handle in rep.active:
                        rep.active.remove(handle)
                self.metrics.inc("cancelled")
                handle._finish("cancelled")
            elif handle.status == "adopting":
                # reserved for a KV transfer that no longer matters
                rep = self._replica_by_id(handle.pinned_replica)
                if rep is not None and handle.request is not None:
                    rep.scheduler.adopt_abort(handle.request)
                self.metrics.inc("cancelled")
                handle._finish("cancelled")

    # -- the pump ----------------------------------------------------------

    def pump(self) -> int:
        """One serving round: drain dead replicas, admit (with
        preemption), step every replica with work, deliver tokens.
        Returns tokens processed — 0 means idle."""
        with self._lock:
            # one health-probe evaluation per replica per round: every
            # healthy() call below this reuses the memoized verdict
            self._round += 1
            for r in self.router.replicas:
                r.new_round(self._round)
            self._drain_dead()
            if not self.router.healthy():
                # pump/start() mode has no caller to raise to (that is
                # run_until_idle's job): fail pending handles so
                # consumers parked in stream()/result() unblock instead
                # of hanging forever
                if any(self._queues.values()):
                    self._fail_pending_no_replica()
                return 0
            self._admit_all()
            if self.params.preemption and self._queues["interactive"]:
                if self._preempt_for_interactive():
                    self._admit_all()
            n = 0
            for rep in self.router.healthy():
                if rep.scheduler.has_work:
                    n += rep.engine.step(
                        temperature=self.params.temperature,
                        eos_token_id=self.params.eos_token_id)
                self._deliver(rep)
                rep.update_ledger()
            self.metrics.publish(
                {c: len(q) for c, q in self._queues.items()},
                self._aggregate_hit_rate(),
                moe_imbalance={r.id: imb for r in self.router.replicas
                               for imb in [r.moe_load_imbalance()]
                               if imb > 0.0} or None)
            return n

    def run_until_idle(self, max_rounds: int = 100_000) -> None:
        """Pump until no queued or in-flight work remains.  Raises
        :class:`NoHealthyReplicaError` if work is pending with every
        replica dead."""
        for _ in range(max_rounds):
            with self._lock:
                pending = (any(self._queues.values())
                           or any(r.active for r in self.router.replicas))
                if not pending:
                    return
                if not self.router.healthy():
                    # fail the pending handles BEFORE raising: other
                    # threads parked in stream()/result() would wait on
                    # queues that will never see _DONE otherwise
                    self._drain_dead()
                    self._fail_pending_no_replica()
                    raise NoHealthyReplicaError(
                        "pending serving work but no healthy replica")
            self.pump()
        raise RuntimeError(f"run_until_idle: no quiescence in "
                           f"{max_rounds} rounds")

    # -- background drive --------------------------------------------------

    def start(self, idle_sleep_s: float = 0.001) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._serve_loop, args=(idle_sleep_s,),
                daemon=True, name="ds-serving-frontend")
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            self._stop.set()
            t.join(timeout=10.0)

    def _serve_loop(self, idle_sleep_s: float) -> None:
        log_dist("serving front-end loop started")
        while not self._stop.is_set():
            if self.pump() == 0:
                self._stop.wait(idle_sleep_s)

    # -- internals (lock held) ---------------------------------------------

    def _replica_by_id(self, rid: Optional[int]) -> Optional[Replica]:
        for r in self.router.replicas:
            if r.id == rid:
                return r
        return None

    def _aggregate_hit_rate(self) -> float:
        hits = looks = 0
        for r in self.router.replicas:
            p = getattr(r.scheduler, "prefix", None)
            if p is not None:
                hits += p.hit_tokens
                looks += p.lookup_tokens
        return hits / looks if looks else 0.0

    def _reset_for_replay(self, h: ServingHandle) -> None:
        """The dead engine's scheduler state is unreachable; the handle
        restarts from its prompt on a healthy replica, delivery resumes
        past the already-streamed high-water mark."""
        if h.record is not None:
            h.record.event("replayed", from_replica=h.pinned_replica,
                           delivered=h.delivered)
        h.request = None
        h.replica_id = None
        h.pinned_replica = None
        h.preempted = False
        h.consumed = 0
        h.replays += 1
        h.status = "queued"

    def _drain_dead(self) -> None:
        for rep in self.router.replicas:
            if rep.healthy() or rep.id in self._drained:
                continue
            self._drained.add(rep.id)
            moved = 0
            # preempted handles sit in the class queues (not rep.active)
            # but are still pinned to this replica's now-unreachable KV
            # pages — reset them in place so _try_admit restarts them on
            # a healthy replica instead of retrying the dead pin forever
            for q in self._queues.values():
                for h in q:
                    if h.request is not None and h.pinned_replica == rep.id:
                        self._reset_for_replay(h)
                        moved += 1
            # re-queue in-flight work at the class front, earliest
            # admission first (walk newest-first while inserting at 0)
            for h in reversed(rep.active):
                self._reset_for_replay(h)
                self._queues[h.klass].insert(0, h)
                moved += 1
            rep.active.clear()
            if moved:
                self.metrics.inc("requeued_replica_death", moved)
            log_dist(f"serving: replica{rep.id} drained "
                     f"({rep.dead_reason}); {moved} requests re-queued")

    def _fail_pending_no_replica(self) -> None:
        err = NoHealthyReplicaError(
            "all replicas dead: "
            + "; ".join(f"replica{r.id}: {r.dead_reason}"
                        for r in self.router.replicas))
        n = 0
        for q in self._queues.values():
            for h in q:
                self.metrics.inc("failed")
                h._finish("failed", err)
                n += 1
            q.clear()
        log_dist(f"serving: failed {n} pending requests — "
                 f"no healthy replica")

    def _headroom_degraded(self) -> bool:
        floor = self.params.min_hbm_headroom_frac
        if floor <= 0:
            return False
        from ..telemetry.memory import get_memory_ledger

        led = get_memory_ledger()
        if not led.enabled:
            return False
        hb = led.heartbeat_summary().get("hbm_headroom")
        return hb is not None and hb < floor

    def _admit_all(self) -> None:
        degraded = self._headroom_degraded()
        for klass in CLASSES:
            if degraded and klass != "interactive":
                if self._queues[klass]:
                    self.metrics.inc("admission_deferred_headroom")
                    from .metrics import count_admission_reject

                    count_admission_reject(self.metrics, "headroom")
                continue
            q = self._queues[klass]
            while q:
                if not self._try_admit(q[0]):
                    break  # FIFO within a class: no overtaking
                q.pop(0)
            if q:
                # strict priority: a class that could not fully drain
                # blocks lower classes this round (no SLO inversion) —
                # unless nothing is seated anywhere: then only a
                # lower-class admission/resume can ever complete and
                # free the pages this head is waiting on, so blocking
                # them would deadlock the whole service
                if any(r.scheduler.has_work for r in self.router.healthy()):
                    break

    def _reserve_pages(self, rep: Replica, klass: str) -> int:
        if klass == "interactive":
            return 0
        allocatable = rep.scheduler.cache.num_blocks - 1
        return int(self.params.interactive_reserve_frac * allocatable)

    def _try_admit(self, h: ServingHandle) -> bool:
        if h.request is not None:
            # preempted: pinned to the replica holding its KV pages
            rep = self._replica_by_id(h.pinned_replica)
            if rep is None or not rep.healthy():
                return False
            if not rep.scheduler.resume(h.request):
                if h.record is not None:
                    h.record.note_blocked_admission()
                return False
            h.status = "running"
            h.replica_id = rep.id
            if h.record is not None:
                h.record.event("resumed", replica=rep.id)
            rep.active.append(h)
            return True
        rejected = {"slots": 0, "pages": 0, "token_budget": 0}
        for rep in self.router.route_candidates(h.prompt):
            if (rep.outstanding_tokens() + len(h.prompt)
                    + h.max_new_tokens
                    > self.params.max_outstanding_tokens):
                rejected["token_budget"] += 1
                continue
            reserve = self._reserve_pages(rep, h.klass)
            if not rep.scheduler.can_admit(h.prompt, h.max_new_tokens,
                                           reserve_pages=reserve):
                # the pages-only re-check tells slot-blocked (more
                # workers help) from page-blocked (more HBM helps)
                if rep.scheduler.can_admit(h.prompt, h.max_new_tokens,
                                           reserve_pages=reserve,
                                           ignore_slots=True):
                    rejected["slots"] += 1
                else:
                    rejected["pages"] += 1
                continue
            h.request = rep.engine.put(h.prompt, h.max_new_tokens)
            h.request.priority = CLASSES.index(h.klass)
            rep.scheduler.admit_now(h.request)
            h.status = "running"
            h.replica_id = rep.id
            h.pinned_replica = rep.id
            h.admitted_at = self.clock()
            if h.record is not None:
                h.record.event("admitted", replica=rep.id)
            rep.active.append(h)
            return True
        if h.record is not None:
            h.record.note_blocked_admission()
        if any(rejected.values()):
            from .metrics import count_admission_reject

            count_admission_reject(
                self.metrics,
                max(("slots", "pages", "token_budget"),
                    key=lambda r: rejected[r]))
        return False

    def _preempt_for_interactive(self) -> bool:
        """Free a decode slot for the interactive head by bumping a
        RUNNING background request; True when a preemption happened."""
        head = self._queues["interactive"][0]
        preempted = False
        # under the HBM-headroom floor the victim's pages are RELEASED
        # (cached-free tier), not retained — so preemption can help a
        # page-blocked head too, and HBM actually shrinks
        release = (self.params.preempt_release_pages
                   and self._headroom_degraded())
        for rep in self.router.healthy():
            if rep.scheduler.can_admit(head.prompt, head.max_new_tokens):
                return False  # admissible without preemption
        for rep in self.router.healthy():
            if not release and not rep.scheduler.can_admit(
                    head.prompt, head.max_new_tokens, ignore_slots=True):
                # the head is page-blocked here, not slot-blocked:
                # retaining preemption keeps the victim's KV pages
                # resident, so bumping it cannot free what the head
                # needs — let the running work finish and release its
                # pages instead
                continue
            victims = [h for h in rep.active
                       if h.klass == "background" and h.request is not None
                       and h.request.slot >= 0
                       and h.request.state.value in ("running", "prefill")]
            if not victims:
                continue
            # bump the request expected to hold its slot longest: decode
            # with the most remaining budget first, else a prefill
            victim = max(victims, key=lambda h: h.request.remaining_budget)
            if victim.record is not None:
                victim.record.event("preempted", replica=rep.id,
                                    release=release)
            if release:
                pages = rep.scheduler.preempt_release(victim.request)
                rep.active.remove(victim)
                # the request object is retired with its pages: the
                # handle replays through a fresh admission, where the
                # prefix trie revives what the cached tier still holds
                # and delivery splices past the high-water mark
                self._reset_for_replay(victim)
                self._queues["background"].insert(0, victim)
                self.metrics.inc("preempt_pages_released", pages)
            else:
                rep.scheduler.preempt(victim.request)
                rep.active.remove(victim)
                victim.status = "queued"
                victim.preempted = True
                self._queues["background"].insert(0, victim)
            self.metrics.inc("preemptions")
            preempted = True
            break
        return preempted

    def _deliver(self, rep: Replica) -> None:
        for h in list(rep.active):
            req = h.request
            new = req.generated[h.consumed:]
            for tok in new:
                h.consumed += 1
                if h.consumed > h.delivered:
                    if h.first_token_at is None:
                        h.first_token_at = self.clock()
                        self.metrics.record_ttft(h.klass, h.ttft_ms,
                                                 ref=h.trace_id)
                        if h.record is not None:
                            h.record.event("first_token",
                                           replica=rep.id)
                    h.delivered += 1
                    if h.record is not None:
                        h.record.token()
                    h._push(int(tok))
            if req.state.value == "done" and h.status == "running":
                rep.active.remove(h)
                h.finished_at = self.clock()
                gen_s = (h.finished_at - (h.first_token_at
                                          or h.finished_at))
                self.metrics.record_completion(h.klass, h.delivered, gen_s)
                from ..telemetry import get_telemetry

                get_telemetry().inc_counter(
                    f"serving/{h.klass}_tokens", v=h.delivered,
                    help="generated tokens delivered per latency class")
                h._finish("done")

    # -- introspection -----------------------------------------------------

    #: bound on the snapshot lock wait — the flight recorder evaluates
    #: this provider inside dump(), and the watchdog dumps BEFORE firing
    #: trip listeners: exactly when a pump thread may be wedged in a
    #: device call while still holding self._lock.  A blocking acquire
    #: here would deadlock the watchdog thread — no bundle written,
    #: replicas never marked dead.  Sized to outlast a ROUTINE long
    #: device step (pump() holds the lock across engine.step), so a
    #: healthy-system dump waits for the full snapshot and only a
    #: genuine wedge degrades; on the watchdog-trip path the pump has
    #: already been stuck for hang_timeout_s, so the extra wait is
    #: noise.  (Class attribute: a test seam.)
    _snapshot_lock_timeout_s: float = 5.0

    def snapshot(self) -> Dict[str, Any]:
        if not self._lock.acquire(timeout=self._snapshot_lock_timeout_s):
            # mirror the lockless _on_watchdog_trip design: emit a
            # best-effort lock-free view instead of a bundle with no
            # serving section at all
            out = self._snapshot_best_effort()
            out["degraded"] = ("frontend lock held beyond "
                               f"{self._snapshot_lock_timeout_s}s (pump "
                               "wedged or in a long device call) — "
                               "lock-free best-effort reads")
            return out
        try:
            return self._snapshot_best_effort()
        finally:
            self._lock.release()

    def _snapshot_best_effort(self) -> Dict[str, Any]:
        """The one section list for BOTH snapshot branches (locked and
        lock-timeout fallback), so they cannot drift.  In the fallback
        the holder may be a LIVE pump in a long device call (not
        wedged), still mutating underneath us — so every section is
        guarded independently: a torn read (e.g. a metrics deque
        resized mid-sort) costs that one section, never the whole
        serving view.  Under the lock the guards never fire."""
        out: Dict[str, Any] = {}
        for build in (
                self.metrics.snapshot,
                lambda: {"queues": {c: len(q)
                                    for c, q in self._queues.items()}},
                lambda: {"queued_tokens":
                         {c: sum(len(h.prompt) + h.max_new_tokens
                                 for h in q)
                          for c, q in self._queues.items()}},
                lambda: {"router": self.router.snapshot()},
                lambda: {"prefix_hit_rate":
                         round(self._aggregate_hit_rate(), 4)},
                lambda: {"params": dataclasses.asdict(self.params)}):
            try:
                out.update(build())
            except Exception as e:
                out.setdefault("section_errors", []).append(repr(e))
        return out
