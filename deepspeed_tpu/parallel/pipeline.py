"""Pipeline parallelism as a scanned collective-permute loop.

Reference: ``deepspeed/runtime/pipe/`` [K] — ``PipelineEngine`` executes a
1F1B instruction stream (LoadMicroBatch / ForwardPass / SendActivation /
RecvActivation / BackwardPass / SendGrad / RecvGrad / ReduceGrads /
OptimizerStep) with explicit torch P2P between stage ranks (SURVEY §3.5).

TPU-native: none of that instruction machinery survives.  Stage params are
the layer-stacked pytree ``[L, ...]`` sharded over the ``pipe`` mesh axis
(each rank holds its L/P layer slice); the microbatch loop is ONE
``lax.scan`` whose body runs every stage in lockstep and moves boundary
activations with ``lax.ppermute`` (collective-permute is ICI-native).  The
whole schedule — forward fill/drain AND its exact transpose for backward —
is differentiated by jax.grad through the scan, so SendGrad/RecvGrad is the
autodiff of ppermute and "ReduceGrads" is GSPMD's reduction over ``data``.
GPipe-style scheduling; gradients are bit-identical to 1F1B (1F1B only
reorders eager-mode memory traffic, which XLA schedules itself).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from .mesh import AXIS_PIPE

P = PartitionSpec


def pipeline_spec(n_dims_map: Any) -> Any:
    """PartitionSpecs putting the leading (layer-stack) dim on ``pipe``."""
    return jax.tree.map(
        lambda nd: P(*((AXIS_PIPE,) + (None,) * (int(nd) - 1))), n_dims_map)


def pipeline_bubble_fraction(n_micro: int, pp: int,
                             virtual_stages: int = 1) -> float:
    """Idle fraction of the schedule (fill+drain over total ticks).

    GPipe: (pp-1)/(M+pp-1).  Interleaved (virtual_stages=v): each tick is a
    1/v-stage chunk, so the same (pp-1)-tick fill/drain costs v× less —
    (pp-1)/(vM+pp-1) (Megatron interleaved-1F1B bubble math; here realized
    by the circulating-ring schedule below).
    """
    v, M = int(virtual_stages), int(n_micro)
    total = v * M + pp - 1
    return (pp - 1) / total if total > 0 else 0.0


def _interleaved_apply(layer_fn, stacked_params, microbatches, mesh,
                       virtual_stages: int):
    """Interleaved pipeline: rank r owns layer chunks {r, r+pp, …} (v of
    them); one activation per rank circulates the ``pipe`` ring, each tick
    applying the chunk its position indexes, so fill/drain bubbles shrink
    by v (chunk = 1/v stage).  Rank 0 retires finished activations
    (position == v·pp) and injects waiting microbatches into empty slots;
    jax.grad differentiates the whole ring (SendGrad = ppermute cotangent).
    """
    pp = int(mesh.shape[AXIS_PIPE])
    v = int(virtual_stages)
    tmap = jax.tree.map
    M = jax.tree.leaves(microbatches)[0].shape[0]
    n_chunks = v * pp
    # scan ticks: bursts of pp injections every v·pp ticks (ring circuit),
    # +pp to drain the final burst; exact minimum when pp | M
    T = v * pp * (-(-M // pp)) + pp

    def chunked(p):
        # [L, ...] → [n_chunks, L/n_chunks, ...], reordered so rank r's
        # CONTIGUOUS shard [r·v, (r+1)·v) holds round-robin chunks
        # {r, r+pp, …} (shard_map shards dim 0 contiguously).  This gather
        # reshards ~half the param bytes over ICI each step (and its
        # scatter transpose in backward); storing params pre-permuted in
        # ring order would make it free but leaks the interleave layout
        # into optimizer/checkpoint/import — deliberate correctness-first
        # trade-off, revisit if profiling shows it on the critical path
        L = p.shape[0]
        c = p.reshape(n_chunks, L // n_chunks, *p.shape[1:])
        order = jnp.asarray([j * pp + r for r in range(pp) for j in range(v)])
        return c[order]

    stacked_params = tmap(chunked, stacked_params)

    def per_stage(params_local, xs):
        stage = jax.lax.axis_index(AXIS_PIPE)
        zero = tmap(lambda a: jnp.zeros_like(a[0]), xs)
        outs0 = tmap(jnp.zeros_like, xs)

        def apply_chunk(j, act):
            cp = tmap(lambda p: jax.lax.dynamic_index_in_dim(
                p, j, 0, keepdims=False), params_local)

            def body(h, lp):
                return layer_fn(lp, h), None

            out, _ = jax.lax.scan(body, act, cp)
            return out

        def tick(carry, _):
            act, pos, mb, next_mb, outs = carry
            # -- rank 0: retire a full-circle activation, refill the slot
            retired = (stage == 0) & (pos == n_chunks)
            outs = tmap(
                lambda acc, a: jnp.where(
                    retired,
                    jax.lax.dynamic_update_index_in_dim(
                        acc, a, jnp.clip(mb, 0, M - 1), 0),
                    acc),
                outs, act)
            empty = retired | (pos < 0)
            inject = (stage == 0) & empty & (next_mb < M)
            act = tmap(
                lambda a, x: jnp.where(
                    inject,
                    jax.lax.dynamic_index_in_dim(
                        x, jnp.clip(next_mb, 0, M - 1), 0, keepdims=False),
                    a),
                act, xs)
            pos = jnp.where(inject, 0, jnp.where(retired, -1, pos))
            mb = jnp.where(inject, next_mb, mb)
            next_mb = next_mb + inject.astype(jnp.int32)
            # -- every rank: apply the chunk this activation has reached
            active = (pos >= 0) & (pos < n_chunks)
            j = jnp.clip(pos // pp, 0, v - 1)
            new_act = apply_chunk(j, act)
            act = tmap(lambda n, a: jnp.where(active, n, a), new_act, act)
            pos = jnp.where(active, pos + 1, pos)
            # -- circulate (activation + its position/microbatch id)
            ring = [(i, (i + 1) % pp) for i in range(pp)]
            act = tmap(lambda a: jax.lax.ppermute(a, AXIS_PIPE, ring), act)
            pos = jax.lax.ppermute(pos, AXIS_PIPE, ring)
            mb = jax.lax.ppermute(mb, AXIS_PIPE, ring)
            return (act, pos, mb, next_mb, outs), None

        init = (zero, jnp.int32(-1), jnp.int32(0), jnp.int32(0), outs0)
        (_, _, _, _, outs), _ = jax.lax.scan(tick, init, None, length=T)
        outs = tmap(lambda o: jax.lax.psum(
            jnp.where(stage == 0, o, jnp.zeros_like(o)), AXIS_PIPE), outs)
        return outs

    in_specs = (pipeline_spec(jax.tree.map(jnp.ndim, stacked_params)),
                jax.tree.map(lambda _: P(), microbatches))
    return jax.shard_map(per_stage, mesh=mesh,
                         in_specs=in_specs,
                         out_specs=jax.tree.map(lambda _: P(), microbatches),
                         check_vma=False,
                         axis_names={AXIS_PIPE})(stacked_params, microbatches)


def pipeline_apply(layer_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any,
                   microbatches: jnp.ndarray,
                   mesh: Mesh, virtual_stages: int = 1) -> Any:
    """Run ``microbatches [M, b, ...]`` through the stage pipeline.

    ``layer_fn(layer_params, x) -> x`` applies ONE layer (leaf shapes =
    ``stacked_params`` minus the leading layer dim); stages apply their local
    slice with an inner scan.  Returns outputs ``[M, b, ...]`` (replicated
    over pipe).  M must be ≥ the pipe size to keep bubbles sane (M < P still
    computes correctly).

    The function must be called inside jit (it builds a shard_map over the
    ``pipe`` axis; every other mesh axis stays in GSPMD "auto" mode so
    ZeRO/TP/SP sharding constraints inside ``layer_fn`` keep working).
    """
    pp = int(mesh.shape[AXIS_PIPE])
    if pp == 1:
        def scan_all(x):
            def body(h, lp):
                return layer_fn(lp, h), None
            out, _ = jax.lax.scan(body, x, stacked_params)
            return out

        return jax.lax.map(scan_all, microbatches)
    if int(virtual_stages) > 1:
        L = jax.tree.leaves(stacked_params)[0].shape[0]
        if L % (pp * int(virtual_stages)):
            raise ValueError(
                f"num_layers {L} not divisible by pp*virtual_stages "
                f"{pp}*{virtual_stages}")
        return _interleaved_apply(layer_fn, stacked_params, microbatches,
                                  mesh, int(virtual_stages))

    M = jax.tree.leaves(microbatches)[0].shape[0]
    T = M + pp - 1  # fill + steady + drain ticks

    def stage_fn(params_local, x):
        """Apply this stage's L/P layers (inner scan over the local slice)."""
        def body(h, lp):
            return layer_fn(lp, h), None

        out, _ = jax.lax.scan(body, x, params_local)
        return out

    def per_stage(params_local, xs):
        stage = jax.lax.axis_index(AXIS_PIPE)
        tmap = jax.tree.map
        zero = tmap(lambda a: jnp.zeros_like(a[0]), xs)
        outs0 = tmap(jnp.zeros_like, xs)

        def tick(carry, t):
            recv, outs = carry
            # stage 0 pulls microbatch t (clipped; garbage beyond M is
            # dropped at write time), others consume the permuted input
            mb = tmap(lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(t, 0, M - 1), 0, keepdims=False), xs)
            inp = tmap(lambda m, r: jnp.where(stage == 0, m, r), mb, recv)
            out = stage_fn(params_local, inp)
            # last stage owns microbatch t-(pp-1) once t >= pp-1
            idx = t - (pp - 1)
            write = (stage == pp - 1) & (idx >= 0)
            outs = tmap(
                lambda acc, o: jnp.where(
                    write,
                    jax.lax.dynamic_update_index_in_dim(
                        acc, o, jnp.clip(idx, 0, M - 1), 0),
                    acc),
                outs, out)
            nxt = tmap(lambda o: jax.lax.ppermute(
                o, AXIS_PIPE, [(i, (i + 1) % pp) for i in range(pp)]), out)
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(tick, (zero, outs0), jnp.arange(T))
        # replicate the last stage's outputs across the pipe axis
        outs = tmap(lambda o: jax.lax.psum(
            jnp.where(stage == pp - 1, o, jnp.zeros_like(o)), AXIS_PIPE),
            outs)
        return outs

    in_specs = (pipeline_spec(jax.tree.map(jnp.ndim, stacked_params)),
                jax.tree.map(lambda _: P(), microbatches))
    return jax.shard_map(per_stage, mesh=mesh,
                         in_specs=in_specs, out_specs=jax.tree.map(
                             lambda _: P(), microbatches),
                         check_vma=False,
                         axis_names={AXIS_PIPE})(stacked_params, microbatches)
