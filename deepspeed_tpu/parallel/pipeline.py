"""Pipeline parallelism as a scanned collective-permute loop.

Reference: ``deepspeed/runtime/pipe/`` [K] — ``PipelineEngine`` executes a
1F1B instruction stream (LoadMicroBatch / ForwardPass / SendActivation /
RecvActivation / BackwardPass / SendGrad / RecvGrad / ReduceGrads /
OptimizerStep) with explicit torch P2P between stage ranks (SURVEY §3.5).

TPU-native: none of that instruction machinery survives.  Stage params are
the layer-stacked pytree ``[L, ...]`` sharded over the ``pipe`` mesh axis
(each rank holds its L/P layer slice); the microbatch loop is ONE
``lax.scan`` whose body runs every stage in lockstep and moves boundary
activations with ``lax.ppermute`` (collective-permute is ICI-native).  The
whole schedule — forward fill/drain AND its exact transpose for backward —
is differentiated by jax.grad through the scan, so SendGrad/RecvGrad is the
autodiff of ppermute and "ReduceGrads" is GSPMD's reduction over ``data``.
GPipe-style scheduling; gradients are bit-identical to 1F1B (1F1B only
reorders eager-mode memory traffic, which XLA schedules itself).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from .mesh import AXIS_PIPE

P = PartitionSpec


def pipeline_spec(n_dims_map: Any) -> Any:
    """PartitionSpecs putting the leading (layer-stack) dim on ``pipe``."""
    return jax.tree.map(
        lambda nd: P(*((AXIS_PIPE,) + (None,) * (int(nd) - 1))), n_dims_map)


def pipeline_apply(layer_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any,
                   microbatches: jnp.ndarray,
                   mesh: Mesh) -> Any:
    """Run ``microbatches [M, b, ...]`` through the stage pipeline.

    ``layer_fn(layer_params, x) -> x`` applies ONE layer (leaf shapes =
    ``stacked_params`` minus the leading layer dim); stages apply their local
    slice with an inner scan.  Returns outputs ``[M, b, ...]`` (replicated
    over pipe).  M must be ≥ the pipe size to keep bubbles sane (M < P still
    computes correctly).

    The function must be called inside jit (it builds a shard_map over the
    ``pipe`` axis; every other mesh axis stays in GSPMD "auto" mode so
    ZeRO/TP/SP sharding constraints inside ``layer_fn`` keep working).
    """
    pp = int(mesh.shape[AXIS_PIPE])
    if pp == 1:
        def scan_all(x):
            def body(h, lp):
                return layer_fn(lp, h), None
            out, _ = jax.lax.scan(body, x, stacked_params)
            return out

        return jax.lax.map(scan_all, microbatches)

    M = jax.tree.leaves(microbatches)[0].shape[0]
    T = M + pp - 1  # fill + steady + drain ticks

    def stage_fn(params_local, x):
        """Apply this stage's L/P layers (inner scan over the local slice)."""
        def body(h, lp):
            return layer_fn(lp, h), None

        out, _ = jax.lax.scan(body, x, params_local)
        return out

    def per_stage(params_local, xs):
        stage = jax.lax.axis_index(AXIS_PIPE)
        tmap = jax.tree.map
        zero = tmap(lambda a: jnp.zeros_like(a[0]), xs)
        outs0 = tmap(jnp.zeros_like, xs)

        def tick(carry, t):
            recv, outs = carry
            # stage 0 pulls microbatch t (clipped; garbage beyond M is
            # dropped at write time), others consume the permuted input
            mb = tmap(lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(t, 0, M - 1), 0, keepdims=False), xs)
            inp = tmap(lambda m, r: jnp.where(stage == 0, m, r), mb, recv)
            out = stage_fn(params_local, inp)
            # last stage owns microbatch t-(pp-1) once t >= pp-1
            idx = t - (pp - 1)
            write = (stage == pp - 1) & (idx >= 0)
            outs = tmap(
                lambda acc, o: jnp.where(
                    write,
                    jax.lax.dynamic_update_index_in_dim(
                        acc, o, jnp.clip(idx, 0, M - 1), 0),
                    acc),
                outs, out)
            nxt = tmap(lambda o: jax.lax.ppermute(
                o, AXIS_PIPE, [(i, (i + 1) % pp) for i in range(pp)]), out)
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(tick, (zero, outs0), jnp.arange(T))
        # replicate the last stage's outputs across the pipe axis
        outs = tmap(lambda o: jax.lax.psum(
            jnp.where(stage == pp - 1, o, jnp.zeros_like(o)), AXIS_PIPE),
            outs)
        return outs

    in_specs = (pipeline_spec(jax.tree.map(jnp.ndim, stacked_params)),
                jax.tree.map(lambda _: P(), microbatches))
    return jax.shard_map(per_stage, mesh=mesh,
                         in_specs=in_specs, out_specs=jax.tree.map(
                             lambda _: P(), microbatches),
                         check_vma=False,
                         axis_names={AXIS_PIPE})(stacked_params, microbatches)
