"""Pipeline parallelism as a scanned collective-permute loop.

Reference: ``deepspeed/runtime/pipe/`` [K] — ``PipelineEngine`` executes a
1F1B instruction stream (LoadMicroBatch / ForwardPass / SendActivation /
RecvActivation / BackwardPass / SendGrad / RecvGrad / ReduceGrads /
OptimizerStep) with explicit torch P2P between stage ranks (SURVEY §3.5).

TPU-native: none of that instruction machinery survives.  Stage params are
the layer-stacked pytree ``[L, ...]`` sharded over the ``pipe`` mesh axis
(each rank holds its L/P layer slice); the microbatch loop is ONE
``lax.scan`` whose body runs every stage in lockstep and moves boundary
activations with ``lax.ppermute`` (collective-permute is ICI-native).  The
whole schedule — forward fill/drain AND its exact transpose for backward —
is differentiated by jax.grad through the scan, so SendGrad/RecvGrad is the
autodiff of ppermute and "ReduceGrads" is GSPMD's reduction over ``data``.
GPipe-style scheduling; gradients are bit-identical to 1F1B (1F1B only
reorders eager-mode memory traffic, which XLA schedules itself).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..comm.comm import ppermute as _ppermute, psum as _psum
from .mesh import AXIS_PIPE
from ..utils.jax_compat import shard_map as _shard_map

P = PartitionSpec


def pipeline_spec(n_dims_map: Any) -> Any:
    """PartitionSpecs putting the leading (layer-stack) dim on ``pipe``."""
    return jax.tree.map(
        lambda nd: P(*((AXIS_PIPE,) + (None,) * (int(nd) - 1))), n_dims_map)


def pipeline_bubble_fraction(n_micro: int, pp: int,
                             virtual_stages: int = 1) -> float:
    """Idle fraction of the schedule (fill+drain over total ticks).

    GPipe: (pp-1)/(M+pp-1).  Interleaved (virtual_stages=v): each tick is a
    1/v-stage chunk, so the same (pp-1)-tick fill/drain costs v× less —
    (pp-1)/(vM+pp-1) (Megatron interleaved-1F1B bubble math; here realized
    by the circulating-ring schedule below).
    """
    v, M = int(virtual_stages), int(n_micro)
    total = v * M + pp - 1
    return (pp - 1) / total if total > 0 else 0.0


def _interleaved_apply(layer_fn, stacked_params, microbatches, mesh,
                       virtual_stages: int):
    """Interleaved pipeline: rank r owns layer chunks {r, r+pp, …} (v of
    them); one activation per rank circulates the ``pipe`` ring, each tick
    applying the chunk its position indexes, so fill/drain bubbles shrink
    by v (chunk = 1/v stage).  Rank 0 retires finished activations
    (position == v·pp) and injects waiting microbatches into empty slots;
    jax.grad differentiates the whole ring (SendGrad = ppermute cotangent).
    """
    pp = int(mesh.shape[AXIS_PIPE])
    v = int(virtual_stages)
    tmap = jax.tree.map
    M = jax.tree.leaves(microbatches)[0].shape[0]
    n_chunks = v * pp
    # scan ticks: bursts of pp injections every v·pp ticks (ring circuit),
    # +pp to drain the final burst; exact minimum when pp | M
    T = v * pp * (-(-M // pp)) + pp

    def chunked(p):
        # [L, ...] → [n_chunks, L/n_chunks, ...], reordered so rank r's
        # CONTIGUOUS shard [r·v, (r+1)·v) holds round-robin chunks
        # {r, r+pp, …} (shard_map shards dim 0 contiguously).  This gather
        # reshards ~half the param bytes over ICI each step (and its
        # scatter transpose in backward); storing params pre-permuted in
        # ring order would make it free but leaks the interleave layout
        # into optimizer/checkpoint/import — deliberate correctness-first
        # trade-off, revisit if profiling shows it on the critical path
        L = p.shape[0]
        c = p.reshape(n_chunks, L // n_chunks, *p.shape[1:])
        order = jnp.asarray([j * pp + r for r in range(pp) for j in range(v)])
        return c[order]

    stacked_params = tmap(chunked, stacked_params)

    def per_stage(params_local, xs):
        stage = jax.lax.axis_index(AXIS_PIPE)
        zero = tmap(lambda a: jnp.zeros_like(a[0]), xs)
        outs0 = tmap(jnp.zeros_like, xs)

        def apply_chunk(j, act):
            cp = tmap(lambda p: jax.lax.dynamic_index_in_dim(
                p, j, 0, keepdims=False), params_local)

            def body(h, lp):
                return layer_fn(lp, h), None

            out, _ = jax.lax.scan(body, act, cp)
            return out

        def tick(carry, _):
            act, pos, mb, next_mb, outs = carry
            # -- rank 0: retire a full-circle activation, refill the slot
            retired = (stage == 0) & (pos == n_chunks)
            outs = tmap(
                lambda acc, a: jnp.where(
                    retired,
                    jax.lax.dynamic_update_index_in_dim(
                        acc, a, jnp.clip(mb, 0, M - 1), 0),
                    acc),
                outs, act)
            empty = retired | (pos < 0)
            inject = (stage == 0) & empty & (next_mb < M)
            act = tmap(
                lambda a, x: jnp.where(
                    inject,
                    jax.lax.dynamic_index_in_dim(
                        x, jnp.clip(next_mb, 0, M - 1), 0, keepdims=False),
                    a),
                act, xs)
            pos = jnp.where(inject, 0, jnp.where(retired, -1, pos))
            mb = jnp.where(inject, next_mb, mb)
            next_mb = next_mb + inject.astype(jnp.int32)
            # -- every rank: apply the chunk this activation has reached
            active = (pos >= 0) & (pos < n_chunks)
            j = jnp.clip(pos // pp, 0, v - 1)
            new_act = apply_chunk(j, act)
            act = tmap(lambda n, a: jnp.where(active, n, a), new_act, act)
            pos = jnp.where(active, pos + 1, pos)
            # -- circulate (activation + its position/microbatch id)
            ring = [(i, (i + 1) % pp) for i in range(pp)]
            act = tmap(lambda a: _ppermute(a, ring, AXIS_PIPE), act)
            pos = _ppermute(pos, ring, AXIS_PIPE)
            mb = _ppermute(mb, ring, AXIS_PIPE)
            return (act, pos, mb, next_mb, outs), None

        init = (zero, jnp.int32(-1), jnp.int32(0), jnp.int32(0), outs0)
        (_, _, _, _, outs), _ = jax.lax.scan(tick, init, None, length=T)
        outs = tmap(lambda o: _psum(
            jnp.where(stage == 0, o, jnp.zeros_like(o)), AXIS_PIPE), outs)
        return outs

    in_specs = (pipeline_spec(jax.tree.map(jnp.ndim, stacked_params)),
                jax.tree.map(lambda _: P(), microbatches))
    return _shard_map(per_stage, mesh=mesh,
                         in_specs=in_specs,
                         out_specs=jax.tree.map(lambda _: P(), microbatches),
                         check_vma=False,
                         axis_names={AXIS_PIPE})(stacked_params, microbatches)


def pipeline_train_1f1b(layer_fn: Callable[[Any, Any], Any],
                        stacked_params: Any,
                        embed_fn: Callable[[Any, Any], Any],
                        embed_params: Any,
                        head_fn: Callable[[Any, Any, Any], jnp.ndarray],
                        head_params: Any,
                        microbatches: Any,
                        mesh: Mesh,
                        manual_axes: tuple = (),
                        trunk_specs: Any = None,
                        head_specs: Any = None):
    """1F1B training schedule: mean loss + grads in ONE pass with O(pp)
    stashed activations per stage — vs GPipe-through-autodiff, which keeps
    all M microbatch activations live until the backward drain.

    Reference: ``runtime/pipe/schedule.py`` ``TrainSchedule`` [K] — warmup
    forwards, steady-state alternating 1F1B, cooldown backwards.  TPU-native
    realization: the instruction stream is a ``lax.scan`` over lockstep
    ticks inside ``shard_map``; Send/RecvActivation is the forward
    ``ppermute`` ring, Send/RecvGrad the backward ring, and per-stage weight
    residency is simply the pipe-sharded ``[L, ...]`` stack (params never
    move).  Schedule (tick ``t``, stage ``s``, micro ``m``):

        F_s(m) at t = m + s              (stage 0 embeds micro m at t = m)
        B_s(m) at t = m + 2·pp - 2 - s   (last stage fuses F+loss+B)

    so stage ``s`` holds at most ``2(pp-s)-1 ≤ 2pp-1`` stashed activations
    regardless of M.  Backward recomputes the stage forward from the stashed
    stage INPUT (activation checkpointing at stage boundaries — the same
    memory/compute trade the reference runs PP with).

    ``layer_fn(lp, x) -> x`` — one trunk layer (``x`` may be a pytree);
    ``embed_fn(ep, micro) -> x`` — builds stage-0 input from one microbatch;
    ``head_fn(hp, x, micro) -> scalar`` — per-micro loss (mean over rows);
    ``microbatches`` — pytree with leading dim M.  Call inside jit.

    Returns ``(loss_mean, (trunk_grads, embed_grads, head_grads), stats)``;
    ``stats["stash_depth"]`` is the per-stage live-activation bound (the
    GPipe equivalent is M).
    """
    pp = int(mesh.shape[AXIS_PIPE])
    tmap = jax.tree.map
    M = int(jax.tree.leaves(microbatches)[0].shape[0])
    S = 2 * pp - 1            # stash ring depth — the 1F1B memory bound
    T = M + 2 * pp - 2        # warmup + steady + cooldown ticks

    def chunk_fwd(pl, x):
        def body(h, lp):
            return layer_fn(lp, h), None
        out, _ = jax.lax.scan(body, x, pl)
        return out

    def per_stage(pl, ep, hp, micros):
        s = jax.lax.axis_index(AXIS_PIPE)
        is_last = s == pp - 1
        micro0 = tmap(lambda a: a[0], micros)
        x0 = embed_fn(ep, micro0)
        # shape/dtype-only zeros: the model may constrain x0 with a
        # concrete-mesh sharding, which zeros_like would drag into the
        # manual-pipe context (mesh mismatch)
        zero_act = tmap(lambda z: jnp.zeros(z.shape, z.dtype), x0)
        stash0 = tmap(lambda z: jnp.zeros((S,) + z.shape, z.dtype), zero_act)

        def zlike(tree):
            return tmap(lambda a: jnp.zeros(a.shape, jnp.float32), tree)

        def tick(carry, t):
            f_recv, b_recv, stash, gacc, ge, gh, loss_acc = carry
            # ---------------- forward ----------------
            m_f = t - s
            f_active = (m_f >= 0) & (m_f < M)
            micro_f = tmap(lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(m_f, 0, M - 1), 0, keepdims=False), micros)
            x_embed = embed_fn(ep, micro_f)   # consumed by stage 0 only
            x_in = tmap(lambda e, r: jnp.where(s == 0, e, r),
                        x_embed, f_recv)
            slot_f = jnp.clip(jnp.remainder(m_f, S), 0, S - 1)
            stash = tmap(
                lambda st, xi: jnp.where(
                    f_active,
                    jax.lax.dynamic_update_index_in_dim(st, xi, slot_f, 0),
                    st), stash, x_in)

            # forward branch: 0 = idle, 1 = plain F, 2 = last-stage F+loss+B
            def idle_f(xi, mf):
                return (xi, jnp.float32(0.0), zlike(hp), zlike(pl),
                        zero_act)

            def plain_f(xi, mf):
                return (chunk_fwd(pl, xi), jnp.float32(0.0), zlike(hp),
                        zlike(pl), zero_act)

            def fused_fb(xi, mf):
                x2, cvjp = jax.vjp(chunk_fwd, pl, xi)
                loss_m, hvjp = jax.vjp(
                    lambda hp_, xx: head_fn(hp_, xx, mf), hp, x2)
                dhp, dx2 = hvjp(jnp.asarray(1.0 / M, loss_m.dtype))
                dpl, dxi = cvjp(dx2)
                return (x2, loss_m.astype(jnp.float32),
                        tmap(lambda a: a.astype(jnp.float32), dhp),
                        tmap(lambda a: a.astype(jnp.float32), dpl), dxi)

            branch = jnp.where(f_active, jnp.where(is_last, 2, 1), 0)
            x_out, loss_m, dhp, dpl_f, dxi_last = jax.lax.switch(
                branch, (idle_f, plain_f, fused_fb), x_in, micro_f)
            loss_acc = loss_acc + loss_m
            gh = tmap(jnp.add, gh, dhp)

            # ---------------- backward (non-last stages) ----------------
            m_b = t - (2 * pp - 2 - s)
            b_active = (m_b >= 0) & (m_b < M) & jnp.logical_not(is_last)
            slot_b = jnp.clip(jnp.remainder(m_b, S), 0, S - 1)
            x_b = tmap(lambda st: jax.lax.dynamic_index_in_dim(
                st, slot_b, 0, keepdims=False), stash)

            def do_bwd(xb, brecv):
                _, cvjp = jax.vjp(chunk_fwd, pl, xb)
                dpl, dxi = cvjp(brecv)
                return tmap(lambda a: a.astype(jnp.float32), dpl), dxi

            def skip_bwd(xb, brecv):
                return zlike(pl), zero_act

            dpl_b, dxi_b = jax.lax.cond(b_active, do_bwd, skip_bwd,
                                        x_b, b_recv)
            gacc = tmap(lambda g, a, b: g + a + b, gacc, dpl_f, dpl_b)

            # stage 0's dx is the embed-output cotangent → embed grads.
            # When stage 0 IS the last stage (pp == 1, or generally the
            # fused branch at s == 0) the cotangent comes from the fused
            # F+B (dxi_last) in the SAME tick (m_b == m_f there).
            micro_b = tmap(lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(m_b, 0, M - 1), 0, keepdims=False), micros)
            dxi_0 = tmap(lambda a, b: jnp.where(is_last, a, b),
                         dxi_last, dxi_b)
            emb_active = (s == 0) & (b_active | (is_last & f_active))

            def do_emb(mb, dxi):
                _, evjp = jax.vjp(lambda ep_: embed_fn(ep_, mb), ep)
                (dep,) = evjp(dxi)
                return tmap(lambda a: a.astype(jnp.float32), dep)

            def skip_emb(mb, dxi):
                return zlike(ep)

            ge = tmap(jnp.add, ge, jax.lax.cond(
                emb_active, do_emb, skip_emb, micro_b, dxi_0))

            # ---------------- rings ----------------
            fwd_ring = [(i, (i + 1) % pp) for i in range(pp)]
            bwd_ring = [(i, (i - 1) % pp) for i in range(pp)]
            f_send = tmap(lambda o: jnp.where(
                f_active, o, jnp.zeros(o.shape, o.dtype)), x_out)
            b_out = tmap(lambda a, b: jnp.where(is_last, a, b),
                         dxi_last, dxi_b)
            b_send = tmap(
                lambda o: jnp.where(b_active | (is_last & f_active), o,
                                    jnp.zeros(o.shape, o.dtype)), b_out)
            f_recv = tmap(lambda o: _ppermute(o, fwd_ring, AXIS_PIPE),
                          f_send)
            b_recv = tmap(lambda o: _ppermute(o, bwd_ring, AXIS_PIPE),
                          b_send)
            return (f_recv, b_recv, stash, gacc, ge, gh, loss_acc), None

        init = (zero_act, zero_act, stash0, zlike(pl), zlike(ep), zlike(hp),
                jnp.float32(0.0))
        (_, _, _, gacc, ge, gh, loss_acc), _ = jax.lax.scan(
            tick, init, jnp.arange(T))
        # loss / embed / head grads live on one stage each → psum replicates
        loss = _psum(loss_acc, AXIS_PIPE) / M
        ge = tmap(lambda a: _psum(a, AXIS_PIPE), ge)
        gh = tmap(lambda a: _psum(a, AXIS_PIPE), gh)
        return loss, gacc, ge, gh

    # ``manual_axes`` (1F1B × TP): the tensor axis joins the manual set —
    # layer_fn then sees LOCAL tensor shards and does its own collectives
    # (decoder_layer_manual_tp) — because tensor GSPMD constraints inside
    # the partial-manual region trip the XLA partitioner CHECK the engine
    # routing documents.  ``trunk_specs`` carries the model's pipe+tensor
    # placement for the stacked layer params in that mode.
    # ``head_specs`` lets the head params enter tensor-SHARDED (the
    # vocab-parallel Megatron cross entropy, head_loss_manual_tp);
    # embed stays replicated (its per-micro gather is cheap).
    trunk_spec = (trunk_specs if trunk_specs is not None
                  else pipeline_spec(jax.tree.map(jnp.ndim, stacked_params)))
    rep = lambda tree: jax.tree.map(lambda _: P(), tree)
    head_spec = head_specs if head_specs is not None else rep(head_params)
    loss, g_trunk, g_emb, g_head = _shard_map(
        per_stage, mesh=mesh,
        in_specs=(trunk_spec, rep(embed_params), head_spec,
                  rep(microbatches)),
        out_specs=(P(), trunk_spec, rep(embed_params), head_spec),
        check_vma=False,
        axis_names={AXIS_PIPE, *manual_axes})(
            stacked_params, embed_params, head_params, microbatches)
    stats = {"stash_depth": S, "ticks": T, "gpipe_stash": M,
             "bubble_fraction": pipeline_bubble_fraction(M, pp)}
    return loss, (g_trunk, g_emb, g_head), stats


def pipeline_apply_stages(stage_fns: Any, params: Any, microbatches: Any,
                          mesh: Mesh) -> Any:
    """GPipe fill/drain for HETEROGENEOUS stages (reference: arbitrary
    ``LayerSpec`` graphs partitioned by ``PipelineModule``, SURVEY §3.5).

    ``stage_fns[i](params, x) -> x`` — stage ``i``'s chain; stage 0 receives
    a raw microbatch (so an embed front-end with a different input shape is
    fine), every OTHER boundary activation must be shape-uniform (the
    ``ppermute`` ring carries one activation type — the same constraint the
    reference's P2P buffers impose per pipeline edge).  The last stage's
    output may have its own shape (logits).  Each rank executes only its
    own stage via ``lax.switch`` on the pipe index; params enter replicated
    over ``pipe`` (generality traded for residency — homogeneous layer
    stacks should use ``pipeline_apply`` / ``pipeline_train_1f1b``, which
    shard the stack).

    Returns the last stage's outputs ``[M, ...]``.  Call inside jit.
    """
    pp = int(mesh.shape[AXIS_PIPE])
    assert len(stage_fns) == pp, (len(stage_fns), pp)
    tmap = jax.tree.map
    M = int(jax.tree.leaves(microbatches)[0].shape[0])
    T = M + pp - 1
    micro0 = tmap(lambda a: a[0], microbatches)

    # shape donors: boundary activation (stage-0 output) and final output
    hid_shape = jax.eval_shape(stage_fns[0], params, micro0)
    x = hid_shape
    for fn in stage_fns[1:]:
        x = jax.eval_shape(fn, params, x)
    fin_shape = x

    if pp == 1:
        return jax.lax.map(lambda m: stage_fns[0](params, m), microbatches)

    def per_stage(p, xs):
        stage = jax.lax.axis_index(AXIS_PIPE)
        zero_hid = tmap(lambda d: jnp.zeros(d.shape, d.dtype), hid_shape)
        zero_fin = tmap(lambda d: jnp.zeros(d.shape, d.dtype), fin_shape)
        outs0 = tmap(lambda d: jnp.zeros((M,) + d.shape, d.dtype), fin_shape)

        def branch(i):
            def run(micro, recv):
                out = stage_fns[i](p, micro if i == 0 else recv)
                if i == pp - 1:
                    return zero_hid, out
                return out, zero_fin
            return run

        branches = [branch(i) for i in range(pp)]

        def tick(carry, t):
            recv, outs = carry
            micro = tmap(lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(t, 0, M - 1), 0, keepdims=False), xs)
            ring_out, fin_out = jax.lax.switch(stage, branches, micro, recv)
            idx = t - (pp - 1)
            write = (stage == pp - 1) & (idx >= 0)
            outs = tmap(
                lambda acc, o: jnp.where(
                    write,
                    jax.lax.dynamic_update_index_in_dim(
                        acc, o, jnp.clip(idx, 0, M - 1), 0),
                    acc),
                outs, fin_out)
            nxt = tmap(lambda o: _ppermute(
                o, [(i, (i + 1) % pp) for i in range(pp)], AXIS_PIPE),
                ring_out)
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(tick, (zero_hid, outs0), jnp.arange(T))
        outs = tmap(lambda o: _psum(
            jnp.where(stage == pp - 1, o, jnp.zeros_like(o)), AXIS_PIPE),
            outs)
        return outs

    return _shard_map(
        per_stage, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(), params),
                  jax.tree.map(lambda _: P(), microbatches)),
        out_specs=jax.tree.map(lambda _: P(), jax.tree.map(
            lambda d: d, fin_shape)),
        check_vma=False,
        axis_names={AXIS_PIPE})(params, microbatches)


def pipeline_apply(layer_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any,
                   microbatches: jnp.ndarray,
                   mesh: Mesh, virtual_stages: int = 1) -> Any:
    """Run ``microbatches [M, b, ...]`` through the stage pipeline.

    ``layer_fn(layer_params, x) -> x`` applies ONE layer (leaf shapes =
    ``stacked_params`` minus the leading layer dim); stages apply their local
    slice with an inner scan.  Returns outputs ``[M, b, ...]`` (replicated
    over pipe).  M must be ≥ the pipe size to keep bubbles sane (M < P still
    computes correctly).

    The function must be called inside jit (it builds a shard_map over the
    ``pipe`` axis; every other mesh axis stays in GSPMD "auto" mode so
    ZeRO/TP/SP sharding constraints inside ``layer_fn`` keep working).
    """
    pp = int(mesh.shape[AXIS_PIPE])
    if pp == 1:
        def scan_all(x):
            def body(h, lp):
                return layer_fn(lp, h), None
            out, _ = jax.lax.scan(body, x, stacked_params)
            return out

        return jax.lax.map(scan_all, microbatches)
    if int(virtual_stages) > 1:
        L = jax.tree.leaves(stacked_params)[0].shape[0]
        if L % (pp * int(virtual_stages)):
            raise ValueError(
                f"num_layers {L} not divisible by pp*virtual_stages "
                f"{pp}*{virtual_stages}")
        return _interleaved_apply(layer_fn, stacked_params, microbatches,
                                  mesh, int(virtual_stages))

    M = jax.tree.leaves(microbatches)[0].shape[0]
    T = M + pp - 1  # fill + steady + drain ticks

    def stage_fn(params_local, x):
        """Apply this stage's L/P layers (inner scan over the local slice)."""
        def body(h, lp):
            return layer_fn(lp, h), None

        out, _ = jax.lax.scan(body, x, params_local)
        return out

    def per_stage(params_local, xs):
        stage = jax.lax.axis_index(AXIS_PIPE)
        tmap = jax.tree.map
        zero = tmap(lambda a: jnp.zeros_like(a[0]), xs)
        outs0 = tmap(jnp.zeros_like, xs)

        def tick(carry, t):
            recv, outs = carry
            # stage 0 pulls microbatch t (clipped; garbage beyond M is
            # dropped at write time), others consume the permuted input
            mb = tmap(lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(t, 0, M - 1), 0, keepdims=False), xs)
            inp = tmap(lambda m, r: jnp.where(stage == 0, m, r), mb, recv)
            out = stage_fn(params_local, inp)
            # last stage owns microbatch t-(pp-1) once t >= pp-1
            idx = t - (pp - 1)
            write = (stage == pp - 1) & (idx >= 0)
            outs = tmap(
                lambda acc, o: jnp.where(
                    write,
                    jax.lax.dynamic_update_index_in_dim(
                        acc, o, jnp.clip(idx, 0, M - 1), 0),
                    acc),
                outs, out)
            nxt = tmap(lambda o: _ppermute(
                o, [(i, (i + 1) % pp) for i in range(pp)], AXIS_PIPE), out)
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(tick, (zero, outs0), jnp.arange(T))
        # replicate the last stage's outputs across the pipe axis
        outs = tmap(lambda o: _psum(
            jnp.where(stage == pp - 1, o, jnp.zeros_like(o)), AXIS_PIPE),
            outs)
        return outs

    in_specs = (pipeline_spec(jax.tree.map(jnp.ndim, stacked_params)),
                jax.tree.map(lambda _: P(), microbatches))
    return _shard_map(per_stage, mesh=mesh,
                         in_specs=in_specs, out_specs=jax.tree.map(
                             lambda _: P(), microbatches),
                         check_vma=False,
                         axis_names={AXIS_PIPE})(stacked_params, microbatches)
