"""Device-mesh construction — the TPU-native substrate for every parallelism mode.

The reference builds process groups per parallel dimension (DP/TP/PP/EP/SP) out
of global ranks (``deepspeed/utils/groups.py``, ``deepspeed/runtime/pipe/
topology.py:ProcessTopology`` [K]).  On TPU the idiomatic equivalent is ONE
``jax.sharding.Mesh`` whose named axes are the parallel dimensions; XLA/GSPMD
inserts collectives along those axes from sharding annotations, so "creating a
subgroup" reduces to naming an axis (or tuple of axes) in a PartitionSpec.

Axis layout (outer → inner, inner axes land on ICI-adjacent chips):

    pipe    pipeline-parallel stages        (reference: pp)
    expert  expert-parallel factor of DP    (reference: ep,  divides DP)
    data    pure data-parallel replicas     (reference: dp / ep)
    seq     sequence (context) parallel     (reference: Ulysses/ALST sp)
    tensor  tensor-model parallel           (reference: tp / AutoTP)

The full data-parallel degree (what the reference calls ``dp_world_size`` and
what ZeRO shards over) is ``expert × data``; GSPMD lets a PartitionSpec name
the flattened tuple ``("expert", "data")`` so ZeRO sharding composes with MoE
for free.  Batch math (reference ``runtime/config.py``):

    train_batch_size = micro_batch × grad_accum × (world // (tp·pp·sp))
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"
AXIS_DATA = "data"
AXIS_SEQ = "seq"
AXIS_TENSOR = "tensor"

#: outer → inner; tensor innermost = most-communicating axis on fastest ICI.
MESH_AXIS_ORDER: Tuple[str, ...] = (AXIS_PIPE, AXIS_EXPERT, AXIS_DATA, AXIS_SEQ, AXIS_TENSOR)

#: Axes that together form the reference's data-parallel world (ZeRO shard axes).
DP_AXES: Tuple[str, ...] = (AXIS_EXPERT, AXIS_DATA)


@dataclasses.dataclass(frozen=True)
class MeshLayout:
    """Sizes of every parallel dimension. ``dp`` is the pure-data factor."""

    pp: int = 1
    ep: int = 1
    dp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def world_size(self) -> int:
        return self.pp * self.ep * self.dp * self.sp * self.tp

    @property
    def dp_world_size(self) -> int:
        """Reference dp_world_size = what ZeRO partitions over (= ep × dp)."""
        return self.ep * self.dp

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return {
            AXIS_PIPE: self.pp,
            AXIS_EXPERT: self.ep,
            AXIS_DATA: self.dp,
            AXIS_SEQ: self.sp,
            AXIS_TENSOR: self.tp,
        }

    @classmethod
    def infer(
        cls,
        world_size: Optional[int] = None,
        *,
        tp: int = 1,
        pp: int = 1,
        sp: int = 1,
        ep: int = 1,
        dp: Optional[int] = None,
    ) -> "MeshLayout":
        """Fill in ``dp`` so the product matches ``world_size`` (device count)."""
        if world_size is None:
            world_size = jax.device_count()
        denom = tp * pp * sp * ep
        if dp is None:
            if world_size % denom:
                raise ValueError(
                    f"world_size={world_size} not divisible by tp*pp*sp*ep={denom}")
            dp = world_size // denom
        layout = cls(pp=pp, ep=ep, dp=dp, sp=sp, tp=tp)
        if layout.world_size != world_size:
            raise ValueError(
                f"mesh {layout.axis_sizes} has size {layout.world_size}, "
                f"need {world_size}")
        return layout


def mesh_topology(mesh: Mesh) -> Dict[str, object]:
    """JSON-able description of a mesh's topology — stamped into every
    snapshot manifest (resilience reshard-on-restore keys its
    compatibility check on this) and into reshape annotations.

    ``host_coverage`` records whether a single process can see the whole
    state ("full": single-controller, device_get returns global arrays)
    or only its own shards ("partial": multi-controller — a snapshot
    taken there cannot serve a different shape without every origin
    host's shards).
    """
    devs = np.asarray(mesh.devices).ravel()
    kind = str(getattr(devs[0], "device_kind", "unknown")) if len(devs) \
        else "unknown"
    procs = int(jax.process_count())
    return {
        "axes": {str(a): int(s) for a, s in mesh.shape.items()},
        "world_size": int(devs.size),
        "device_kind": kind,
        "num_processes": procs,
        "process_index": int(jax.process_index()),
        "host_coverage": "full" if procs == 1 else "partial",
    }


def build_mesh(layout: Optional[MeshLayout] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the global Mesh with the canonical axis order.

    Uses ``mesh_utils.create_device_mesh`` so axis adjacency maps onto physical
    ICI topology on real TPU slices; falls back to a plain reshape for host
    (CPU) device sets where there is no topology to exploit.
    """
    layout = layout or MeshLayout.infer()
    if devices is None:
        devices = jax.devices()
        # A single-device layout on a multi-device host is an explicit ask
        # (tests/bench baselines); any other undercount stays a hard error so
        # misconfigured layouts don't silently train on a device subset.
        if layout.world_size == 1 and len(devices) > 1:
            devices = devices[:1]
    devices = list(devices)
    if len(devices) != layout.world_size:
        raise ValueError(f"{len(devices)} devices != layout world {layout.world_size}")
    shape = tuple(layout.axis_sizes[a] for a in MESH_AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXIS_ORDER)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    device = device or jax.devices()[0]
    return Mesh(np.asarray([device]).reshape((1, 1, 1, 1, 1)), MESH_AXIS_ORDER)


def batch_sharding(mesh: Mesh, sp_shard_sequence: bool = False) -> NamedSharding:
    """Sharding for a [batch, seq, ...] input batch.

    Batch dim shards over the full DP world; the sequence dim additionally
    shards over ``seq`` when sequence parallelism is active (reference:
    UlyssesSPDataLoaderAdapter slices the sequence per SP rank).
    """
    if sp_shard_sequence:
        return NamedSharding(mesh, PartitionSpec(DP_AXES, AXIS_SEQ))
    return NamedSharding(mesh, PartitionSpec(DP_AXES))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def global_put(value, sharding: NamedSharding):
    """``device_put`` that also works when ``sharding`` spans processes.

    Multi-controller JAX cannot ``device_put`` host data onto devices other
    processes own; ``make_array_from_callback`` sidesteps that — every
    process materializes only its ADDRESSABLE shards (the callback is
    called per local device with that device's global index), and the
    result is one global array.  Each process must pass the same logical
    ``value`` (the usual SPMD contract).  Single-process: plain
    ``device_put`` (same semantics, fewer host copies)."""
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    arr = np.asarray(value)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def global_feed(value, sharding: NamedSharding):
    """Host batch leaf → global array under ``sharding`` (THE batch-feeding
    helper — engine, streaming executor, and dataloader all route here).

    * global ``jax.Array``s pass through untouched;
    * single-process: plain ``device_put``;
    * multi-process + sharded spec: ``value`` is this process's LOCAL rows
      (the per-rank slice its dataloader produced — the reference's
      per-rank batch feeding) and
      ``make_array_from_process_local_data`` assembles the global array;
    * multi-process + replicated spec: ``value`` is the full (identical)
      array on every process — :func:`global_put` semantics.
    """
    if isinstance(value, jax.Array) and not value.is_fully_addressable:
        return value
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    if sharding.is_fully_replicated:
        return global_put(value, sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(value))


def strip_manual_axes(*entries) -> PartitionSpec:
    """PartitionSpec from ``entries`` minus any axis that is currently
    MANUAL (i.e. we are inside a ``shard_map`` over it).

    Model code places activations with ``with_sharding_constraint``; under a
    partial-manual ``shard_map`` (1-bit grad reduction, pipeline loop) a
    constraint naming a manual axis is illegal — that axis's sharding is
    already the per-device block structure.  Dropping it preserves the
    constraint for the still-GSPMD axes (tensor/seq) and is a no-op
    otherwise.
    """
    from ..utils.jax_compat import current_manual_axes

    manual = current_manual_axes()
    if not manual:
        return PartitionSpec(*entries)
    out = []
    for e in entries:
        if e is None:
            out.append(None)
        elif isinstance(e, tuple):
            kept = tuple(a for a in e if a not in manual)
            out.append(kept if kept else None)
        else:
            out.append(None if e in manual else e)
    return PartitionSpec(*out)


class ProcessTopology:
    """Coordinate ↔ rank bookkeeping over named axes.

    Mirrors the reference ``deepspeed/runtime/pipe/topology.py:ProcessTopology``
    (axes/dims ctor, ``get_rank(**coords)``, ``get_coord(rank)``,
    ``get_axis_comm_lists``) so launcher/debug tooling can reason about global
    ranks even though GSPMD itself never needs explicit rank math.
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        self.axes = list(axes)
        self.dims = list(dims)

    @classmethod
    def from_layout(cls, layout: MeshLayout) -> "ProcessTopology":
        return cls(list(MESH_AXIS_ORDER), [layout.axis_sizes[a] for a in MESH_AXIS_ORDER])

    def world_size(self) -> int:
        return int(np.prod(self.dims))

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def get_rank(self, **coords: int) -> int:
        missing = set(self.axes) - set(coords)
        if missing:
            raise ValueError(f"missing coordinates for axes {sorted(missing)}")
        rank = 0
        for axis, dim in zip(self.axes, self.dims):
            c = coords[axis]
            if not 0 <= c < dim:
                raise ValueError(f"coord {axis}={c} out of range [0,{dim})")
            rank = rank * dim + c
        return rank

    def get_coord(self, rank: int) -> Dict[str, int]:
        coords: Dict[str, int] = {}
        for axis, dim in zip(reversed(self.axes), reversed(self.dims)):
            coords[axis] = rank % dim
            rank //= dim
        return {a: coords[a] for a in self.axes}

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """All rank-groups that vary only along ``axis`` (= the reference's
        per-axis process groups, e.g. all TP groups)."""
        other_axes = [a for a in self.axes if a != axis]
        other_dims = [self.get_dim(a) for a in other_axes]
        lists = []
        for other_coords in itertools.product(*(range(d) for d in other_dims)):
            fixed = dict(zip(other_axes, other_coords))
            lists.append([self.get_rank(**{axis: i, **fixed})
                          for i in range(self.get_dim(axis))])
        return lists
