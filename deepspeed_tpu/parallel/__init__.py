from .mesh import (AXIS_DATA, AXIS_EXPERT, AXIS_PIPE, AXIS_SEQ, AXIS_TENSOR,
                   DP_AXES, MESH_AXIS_ORDER, MeshLayout, ProcessTopology,
                   batch_sharding, build_mesh, replicated, single_device_mesh)

__all__ = ["AXIS_DATA", "AXIS_EXPERT", "AXIS_PIPE", "AXIS_SEQ", "AXIS_TENSOR",
           "DP_AXES", "MESH_AXIS_ORDER", "MeshLayout", "ProcessTopology",
           "batch_sharding", "build_mesh", "replicated", "single_device_mesh"]
