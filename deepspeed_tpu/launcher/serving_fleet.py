"""Serving-worker fleet launcher (ISSUE 14 tentpole b).

Spawns ``python -m deepspeed_tpu.serving worker`` replica processes —
the serving plane's process-per-replica backends — and waits for each
one's readiness line (``DS_SERVING_WORKER id=... endpoint=...``), the
same parse-one-line contract the standalone rendezvous store uses.
The front door, ``serving bench --network``, and the chaos shard all
launch fleets through here; chaos tests then ``kill -9`` members by
``pid`` and watch the router drain them.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..utils.logging import log_dist, warn_once


@dataclasses.dataclass
class WorkerProc:
    """One launched replica worker process."""

    id: str
    role: str
    endpoint: str
    pid: int
    proc: subprocess.Popen

    def kill9(self) -> None:
        """The chaos primitive: SIGKILL, no goodbye."""
        os.kill(self.pid, signal.SIGKILL)


def _worker_cmd(worker_id: str, role: str, engine: str,
                store: Optional[str], port: int,
                extra_args: Optional[List[str]]) -> List[str]:
    cmd = [sys.executable, "-m", "deepspeed_tpu.serving", "worker",
           "--id", worker_id, "--role", role, "--engine", engine,
           "--port", str(port)]
    if store:
        cmd += ["--store", store]
    if extra_args:
        cmd += list(extra_args)
    return cmd


def spawn_serving_worker(worker_id: str, role: str = "mixed",
                         engine: str = "synthetic",
                         store: Optional[str] = None, port: int = 0,
                         env: Optional[Dict[str, str]] = None,
                         extra_args: Optional[List[str]] = None,
                         ready_timeout_s: float = 120.0) -> WorkerProc:
    """Start one worker process and block until its readiness line."""
    full_env = dict(os.environ)
    full_env.setdefault("JAX_PLATFORMS", "cpu")
    full_env.update(env or {})
    proc = subprocess.Popen(
        _worker_cmd(worker_id, role, engine, store, port, extra_args),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=full_env)
    endpoint = _await_ready(proc, worker_id, ready_timeout_s)
    log_dist(f"launched serving worker {worker_id} ({role}) pid "
             f"{proc.pid} at {endpoint}")
    return WorkerProc(id=worker_id, role=role, endpoint=endpoint,
                      pid=proc.pid, proc=proc)


def _await_ready(proc: subprocess.Popen, worker_id: str,
                 timeout_s: float) -> str:
    """Wait (bounded) for the worker's readiness line.

    Reads the RAW pipe fd with ``select`` + ``os.read`` and splits
    lines itself: a worker that wedges before printing (stuck import,
    dead store) produces no bytes and no exit, so a bare ``readline``
    would hang the launcher past any deadline — and mixing ``select``
    with the buffered text wrapper deadlocks the other way (an earlier
    ``readline`` slurps the readiness line into Python's buffer,
    leaving the OS pipe empty for ``select`` to block on forever)."""
    import select

    fd = proc.stdout.fileno()
    deadline = time.monotonic() + timeout_s
    buf = ""
    while True:
        while "\n" in buf:
            line, buf = buf.split("\n", 1)
            if line.startswith("DS_SERVING_WORKER"):
                for field in line.split():
                    if field.startswith("endpoint="):
                        return field[len("endpoint="):].strip()
                raise RuntimeError(
                    f"serving worker {worker_id} readiness line "
                    f"carries no endpoint: {line!r}")
        left = deadline - time.monotonic()
        if left <= 0:
            break
        ready, _, _ = select.select([fd], [], [], left)
        if not ready:
            break
        chunk = os.read(fd, 4096)
        if not chunk:
            rc = proc.poll()
            raise RuntimeError(
                f"serving worker {worker_id} exited (rc={rc}) before "
                f"its readiness line")
        buf += chunk.decode(errors="replace")
    proc.kill()
    raise TimeoutError(
        f"serving worker {worker_id} not ready within {timeout_s}s")


def launch_worker_fleet(n: int, prefill: int = 0,
                        engine: str = "synthetic",
                        store: Optional[str] = None,
                        env: Optional[Dict[str, str]] = None,
                        extra_args: Optional[List[str]] = None,
                        ready_timeout_s: float = 120.0
                        ) -> List[WorkerProc]:
    """``n`` serving workers (the first ``prefill`` of them dedicated
    prefill replicas, the rest mixed), spawned concurrently, each
    awaited to readiness.  Partial failures tear the fleet down."""
    specs = [(f"serving-p{i}" if i < prefill else
              f"serving-r{i - prefill}",
              "prefill" if i < prefill else "mixed")
             for i in range(int(n))]
    full_env = dict(os.environ)
    full_env.setdefault("JAX_PLATFORMS", "cpu")
    full_env.update(env or {})
    procs = [subprocess.Popen(
        _worker_cmd(wid, role, engine, store, 0, extra_args),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=full_env) for wid, role in specs]
    fleet: List[WorkerProc] = []
    try:
        for proc, (wid, role) in zip(procs, specs):
            endpoint = _await_ready(proc, wid, ready_timeout_s)
            fleet.append(WorkerProc(id=wid, role=role, endpoint=endpoint,
                                    pid=proc.pid, proc=proc))
    except Exception:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        raise
    log_dist(f"serving fleet up: {len(fleet)} worker processes "
             f"({prefill} prefill)")
    return fleet


def shutdown_fleet(fleet: List[WorkerProc],
                   timeout_s: float = 10.0) -> None:
    """SIGTERM the fleet, escalate to SIGKILL past the deadline."""
    for w in fleet:
        if w.proc.poll() is None:
            try:
                w.proc.terminate()
            except OSError as e:
                warn_once("launcher/fleet-term",
                          f"terminate failed ({e!r})")
    deadline = time.monotonic() + timeout_s
    for w in fleet:
        left = max(0.1, deadline - time.monotonic())
        try:
            w.proc.wait(timeout=left)
        except subprocess.TimeoutExpired:
            w.proc.kill()
            w.proc.wait(timeout=5.0)
        if w.proc.stdout is not None:
            w.proc.stdout.close()
