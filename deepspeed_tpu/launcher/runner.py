"""``deepspeed`` CLI — multi-host launch for TPU pods.

Reference: ``deepspeed/launcher/runner.py`` [K] — parse ``--hostfile``
(``host slots=N``), ``--include/--exclude`` filters, ``--num_nodes/
--num_gpus``, ``--master_addr/--master_port``; spawn per-rank processes with
RANK/LOCAL_RANK/WORLD_SIZE env (SURVEY §3.1).

TPU-first: libtpu enumerates all LOCAL chips in one process, so the unit of
launch is one process PER HOST (not per chip).  Single-host: exec the script
directly.  Multi-host: ssh each host (pdsh-style) exporting
``jax.distributed`` coordinator env (COORDINATOR_ADDRESS / NUM_PROCESSES /
PROCESS_ID), which ``deepspeed_tpu.comm.init_distributed`` consumes.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import Dict, List

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_hostfile(path: str) -> Dict[str, int]:
    """``hostname slots=N`` lines → {host: slots} (reference syntax)."""
    resources: Dict[str, int] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            resources[host] = slots
    return resources


def filter_hosts(resources: Dict[str, int], include: str = "",
                 exclude: str = "") -> Dict[str, int]:
    """``--include/--exclude`` host[:slot,...] filters (reference syntax;
    slot filtering selects chips on a host)."""

    def parse_filter(spec: str) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        for item in spec.split("@"):
            item = item.strip()
            if not item:
                continue
            if ":" in item:
                host, slots = item.split(":")
                out[host] = [int(s) for s in slots.split(",")]
            else:
                out[item] = []
        return out

    result = dict(resources)
    if include:
        inc = parse_filter(include)
        result = {h: (len(s) if s else resources[h])
                  for h, s in inc.items() if h in resources}
    if exclude:
        exc = parse_filter(exclude)
        for h, s in exc.items():
            if h in result:
                if s:
                    result[h] = max(result[h] - len(s), 0)
                else:
                    del result[h]
        result = {h: n for h, n in result.items() if n > 0}
    return result


def build_env(rank: int, world: int, master_addr: str, master_port: int
              ) -> Dict[str, str]:
    from .multinode_runner import rank_env

    env = dict(os.environ)
    env.update(rank_env(rank, world, master_addr, master_port))
    return env


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="deepspeed", description="deepspeed_tpu launcher")
    parser.add_argument("--hostfile", default=DLTS_HOSTFILE)
    parser.add_argument("--include", default="")
    parser.add_argument("--exclude", default="")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus")
    parser.add_argument("--master_addr", default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", default="ssh",
                        choices=["ssh", "pdsh", "openmpi", "slurm",
                                 "local", "local-multi"])
    parser.add_argument("--ssh_port", type=int, default=22)
    parser.add_argument("--autotuning", default="", choices=["", "tune",
                                                             "run"],
                        help="orchestrate short profiling runs of the "
                             "user script over the tuning space; 'run' "
                             "relaunches with the winning config")
    parser.add_argument("--autotuning_space", default="",
                        choices=["", "default", "offload"])
    parser.add_argument("--autotuning_results",
                        default="autotuning_results")
    parser.add_argument("user_script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.autotuning:
        from ..autotuning.cli import orchestrate

        rc = orchestrate(
            args, [sys.executable, args.user_script] + args.user_args)
        if args.autotuning != "run" or rc != 0:
            return rc
        # run mode: fall through to the NORMAL launch path with the
        # winning config override in the environment — the real job gets
        # the full hostfile/launcher/rank-env machinery, not a bare
        # subprocess

    hosts: Dict[str, int] = {}
    if os.path.exists(args.hostfile):
        hosts = filter_hosts(parse_hostfile(args.hostfile), args.include,
                             args.exclude)
    if args.num_nodes > 0 and hosts:
        hosts = dict(list(hosts.items())[:args.num_nodes])

    cmd = [sys.executable, args.user_script] + args.user_args

    if args.launcher == "local-multi":
        # N local processes (DistributedTest-style); hostfile not needed
        n = args.num_nodes if args.num_nodes > 0 else max(len(hosts), 2)
        hosts = {f"local{i}": 1 for i in range(n)}
    elif not hosts or len(hosts) == 1 or args.launcher == "local":
        # single host: libtpu owns every local chip in ONE process
        logger.info(f"launching single-host: {' '.join(cmd)}")
        proc = subprocess.run(
            cmd, env=build_env(0, 1, args.master_addr, args.master_port))
        return proc.returncode

    from .multinode_runner import get_runner

    kw = {"ssh_port": args.ssh_port} if args.launcher == "ssh" else {}
    runner = get_runner(args.launcher, hosts, args.master_addr,
                        args.master_port, **kw)
    logger.info(f"launching {runner.world} hosts via {runner.name}")
    return runner.launch(cmd)


if __name__ == "__main__":
    sys.exit(main())
