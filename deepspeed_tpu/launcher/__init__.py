from .runner import main

__all__ = ["main"]
