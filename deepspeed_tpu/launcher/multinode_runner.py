"""Multinode runners — pluggable remote-launch backends.

Reference: ``deepspeed/launcher/multinode_runner.py`` [K] —
``PDSHRunner``, ``OpenMPIRunner``, ``SlurmRunner``, ``MPICHRunner``
(SURVEY §2.5 "Launcher"): each turns (resource map, env, user cmd) into
the scheduler-specific launch invocation.

TPU adaptation: the launched unit is one process per HOST (libtpu owns
all local chips), and the exported env is the ``jax.distributed``
coordinator triple (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID)
alongside the reference RANK/WORLD_SIZE names.  Runners only BUILD
commands (pure, testable); ``launch`` shells out.
"""

from __future__ import annotations

import os
import shlex
import subprocess
from typing import Dict, List

from ..utils.logging import logger

def rank_env(rank: int, world: int, master_addr: str, master_port: int
             ) -> Dict[str, str]:
    return {
        "RANK": str(rank), "WORLD_SIZE": str(world), "LOCAL_RANK": "0",
        "MASTER_ADDR": master_addr, "MASTER_PORT": str(master_port),
        "COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
        "NUM_PROCESSES": str(world), "PROCESS_ID": str(rank),
    }


class MultiNodeRunner:
    name = "base"

    def __init__(self, resources: Dict[str, int], master_addr: str,
                 master_port: int, workdir: str = None):
        self.resources = dict(resources)
        self.master_addr = master_addr
        self.master_port = master_port
        self.workdir = workdir or os.getcwd()

    @property
    def world(self) -> int:
        return len(self.resources)

    def backend_exists(self) -> bool:
        return True

    def get_cmd(self, user_cmd: List[str]) -> List[List[str]]:
        """→ list of commands to spawn locally (one per remote rank, or a
        single scheduler command that fans out itself)."""
        raise NotImplementedError

    def launch(self, user_cmd: List[str]) -> int:
        procs = [subprocess.Popen(c) for c in self.get_cmd(user_cmd)]
        # wait ALL before reducing — short-circuiting would orphan the
        # still-running remote jobs when an early rank fails
        rcs = [p.wait() for p in procs]
        return next((rc for rc in rcs if rc), 0)


class SSHRunner(MultiNodeRunner):
    """Plain ssh fan-out (the default; reference PDSH role without pdsh)."""

    name = "ssh"

    def __init__(self, *a, ssh_port: int = 22, **kw):
        super().__init__(*a, **kw)
        self.ssh_port = ssh_port

    def _remote(self, rank: int, user_cmd: List[str]) -> str:
        env = rank_env(rank, self.world, self.master_addr, self.master_port)
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        return (f"cd {shlex.quote(self.workdir)} && {exports} "
                f"{' '.join(map(shlex.quote, user_cmd))}")

    def get_cmd(self, user_cmd: List[str]) -> List[List[str]]:
        return [["ssh", "-p", str(self.ssh_port), host,
                 self._remote(rank, user_cmd)]
                for rank, host in enumerate(self.resources)]


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out (reference ``PDSHRunner``): one pdsh invocation; the
    per-rank id comes from pdsh's %n substitution → PROCESS_ID."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("pdsh") is not None

    def get_cmd(self, user_cmd: List[str]) -> List[List[str]]:
        hosts = ",".join(self.resources)
        # rank = position in the hostlist; pdsh exports it via %n
        env = rank_env(0, self.world, self.master_addr, self.master_port)
        env.pop("RANK"), env.pop("PROCESS_ID")
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        remote = (f"cd {shlex.quote(self.workdir)} && {exports} "
                  f"RANK=%n PROCESS_ID=%n "
                  f"{' '.join(map(shlex.quote, user_cmd))}")
        return [["pdsh", "-R", "ssh", "-w", hosts, remote]]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun fan-out (reference ``OpenMPIRunner``): ranks from OMPI env;
    a tiny shim maps OMPI_COMM_WORLD_RANK → PROCESS_ID at startup."""

    name = "openmpi"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("mpirun") is not None

    def get_cmd(self, user_cmd: List[str]) -> List[List[str]]:
        hosts = ",".join(f"{h}:1" for h in self.resources)
        env = rank_env(0, self.world, self.master_addr, self.master_port)
        flags: List[str] = []
        for k in ("MASTER_ADDR", "MASTER_PORT", "COORDINATOR_ADDRESS",
                  "NUM_PROCESSES", "WORLD_SIZE", "LOCAL_RANK"):
            flags += ["-x", f"{k}={env[k]}"]
        shim = ("import os,sys,runpy;"
                "r=os.environ.get('OMPI_COMM_WORLD_RANK','0');"
                "os.environ['RANK']=r;os.environ['PROCESS_ID']=r;"
                "sys.argv=sys.argv[1:];runpy.run_path(sys.argv[0],"
                "run_name='__main__')")
        return [["mpirun", "-np", str(self.world), "--host", hosts,
                 *flags, user_cmd[0], "-c", shim, *user_cmd[1:]]]


class SlurmRunner(MultiNodeRunner):
    """srun fan-out (reference ``SlurmRunner``): SLURM_PROCID is the rank."""

    name = "slurm"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("srun") is not None

    def get_cmd(self, user_cmd: List[str]) -> List[List[str]]:
        env = rank_env(0, self.world, self.master_addr, self.master_port)
        exports = ",".join(
            f"{k}={env[k]}"
            for k in ("MASTER_ADDR", "MASTER_PORT", "COORDINATOR_ADDRESS",
                      "NUM_PROCESSES", "WORLD_SIZE", "LOCAL_RANK"))
        shim = ("import os,sys,runpy;"
                "r=os.environ.get('SLURM_PROCID','0');"
                "os.environ['RANK']=r;os.environ['PROCESS_ID']=r;"
                "sys.argv=sys.argv[1:];runpy.run_path(sys.argv[0],"
                "run_name='__main__')")
        return [["srun", f"--nodes={self.world}", "--ntasks-per-node=1",
                 f"--export=ALL,{exports}",
                 user_cmd[0], "-c", shim, *user_cmd[1:]]]


class LocalMultiRunner(MultiNodeRunner):
    """N local processes with the coordinator env — the DistributedTest
    analogue for REAL multi-process jax.distributed on one machine (the
    reference tests multi-node semantics exactly this way, SURVEY §4)."""

    name = "local-multi"

    def get_cmd(self, user_cmd: List[str]) -> List[List[str]]:
        # commands carry env inline via `env` so Popen needs no env= plumbing
        cmds = []
        for rank in range(self.world):
            env = rank_env(rank, self.world, self.master_addr,
                           self.master_port)
            pairs = [f"{k}={v}" for k, v in env.items()]
            cmds.append(["env", *pairs, *user_cmd])
        return cmds


RUNNERS = {r.name: r for r in (SSHRunner, PDSHRunner, OpenMPIRunner,
                               SlurmRunner, LocalMultiRunner)}


def get_runner(name: str, resources: Dict[str, int], master_addr: str,
               master_port: int, **kw) -> MultiNodeRunner:
    if name not in RUNNERS:
        raise ValueError(f"unknown launcher {name!r}; have {list(RUNNERS)}")
    runner = RUNNERS[name](resources, master_addr, master_port, **kw)
    if not runner.backend_exists():
        logger.warning(f"launcher backend {name} not found on PATH")
    return runner
