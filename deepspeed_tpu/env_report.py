"""``ds_report`` — environment + op compatibility dump.

Reference: ``deepspeed/env_report.py`` [K] — torch/cuda/nccl versions and a
per-op compatibility matrix.  TPU edition: jax/jaxlib/libtpu/flax/optax/orbax
versions, device inventory, native-op toolchain probes.
"""

from __future__ import annotations

import importlib
import shutil
import sys


def _version(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "unknown")
    except Exception:
        return "not installed"


def cli_main() -> None:
    print("-" * 60)
    print("DeepSpeed-TPU environment report")
    print("-" * 60)
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint",
                "numpy", "torch"):
        print(f"{mod:>18}: {_version(mod)}")
    try:
        import jax

        print(f"{'backend':>18}: {jax.default_backend()}")
        print(f"{'devices':>18}: {jax.devices()}")
        print(f"{'device_count':>18}: {jax.device_count()}")
    except Exception as e:
        print(f"{'jax devices':>18}: unavailable ({e})")
    print("-" * 60)
    print("native op compatibility")
    from .ops.op_builder.builder import _BUILDERS

    gxx = shutil.which("g++")
    print(f"{'g++':>18}: {gxx or 'MISSING'}")
    for name, builder in _BUILDERS.items():
        status = "compatible" if builder.is_compatible() else "INCOMPATIBLE"
        print(f"{name:>18}: {status}")
    print("-" * 60)


if __name__ == "__main__":
    cli_main()
