"""``ds_report`` — environment + op compatibility dump.

Reference: ``deepspeed/env_report.py`` [K] — torch/cuda/nccl versions and a
per-op compatibility matrix.  TPU edition: jax/jaxlib/libtpu/flax/optax/orbax
versions, device inventory, native-op toolchain probes.

:func:`collect` returns the same report as a JSON-able dict — the flight
recorder (``telemetry/flight_recorder.py``) embeds it in every debug
bundle so a post-mortem carries the exact environment it ran in.
"""

from __future__ import annotations

import importlib
import shutil
import sys
from typing import Any, Dict

_MODULES = ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint",
            "numpy", "torch")


def _version(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "unknown")
    except Exception:
        return "not installed"


def collect() -> Dict[str, Any]:
    """The environment report as a dict (what ``ds_report`` prints)."""
    out: Dict[str, Any] = {
        "python": sys.version,
        "versions": {mod: _version(mod) for mod in _MODULES},
    }
    try:
        import jax

        out["backend"] = jax.default_backend()
        out["devices"] = [str(d) for d in jax.devices()]
        out["device_count"] = jax.device_count()
        out["process_count"] = jax.process_count()
    except Exception as e:
        out["devices_error"] = str(e)
    ops: Dict[str, Any] = {"g++": shutil.which("g++") or "MISSING"}
    try:
        from .ops.op_builder.builder import _BUILDERS

        for name, builder in _BUILDERS.items():
            try:
                ops[name] = ("compatible" if builder.is_compatible()
                             else "INCOMPATIBLE")
            except Exception as e:
                ops[name] = f"probe failed: {e}"
    except Exception as e:
        ops["error"] = str(e)
    out["native_ops"] = ops
    return out


def cli_main() -> None:
    report = collect()
    print("-" * 60)
    print("DeepSpeed-TPU environment report")
    print("-" * 60)
    for mod, ver in report["versions"].items():
        print(f"{mod:>18}: {ver}")
    if "devices_error" in report:
        print(f"{'jax devices':>18}: unavailable ({report['devices_error']})")
    else:
        print(f"{'backend':>18}: {report['backend']}")
        print(f"{'devices':>18}: {report['devices']}")
        print(f"{'device_count':>18}: {report['device_count']}")
    print("-" * 60)
    print("native op compatibility")
    for name, status in report["native_ops"].items():
        print(f"{name:>18}: {status}")
    print("-" * 60)


if __name__ == "__main__":
    cli_main()
