"""Elastic training v1 — batch/worldsize compatibility envelopes.

Reference: ``deepspeed/elasticity/elasticity.py`` [K] —
``compute_elastic_config(ds_config, target_deepspeed_version, world_size)``
pre-computes (train_batch, micro_batch, GAS) triples valid across an allowed
range of accelerator counts, so a restarted job at a different scale keeps
hyperparameters fixed (SURVEY §5.3).  v2's torch-elastic agent maps to
``jax.distributed`` restart + checkpoint reshard and lives with the launcher.

The arithmetic is hardware-neutral; "gpus" in the API keeps the reference
name, meaning chips here.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import logger


class ElasticityError(Exception):
    pass


def _candidate_batches(base_list: List[int], max_batch: int,
                       prefer_larger: bool = True) -> List[int]:
    """All feasible train-batch sizes = lcm-combinations of the allowed
    micro-batches times any integer, capped at max_batch."""
    out = set()
    for mb in base_list:
        b = mb
        while b <= max_batch:
            out.add(b)
            b += mb
    return sorted(out, reverse=prefer_larger)


def get_compatible_gpus(micro_batches: List[int], max_train_batch: int,
                        min_gpus: int = 1, max_gpus: int = 1024
                        ) -> Tuple[List[int], int, int]:
    """For the best train batch ≤ max: which accelerator counts divide it
    evenly with one of the allowed micro-batches?  Returns
    (valid_gpu_counts, final_train_batch, micro_batch)."""
    for batch in _candidate_batches(micro_batches, max_train_batch):
        for mb in sorted(micro_batches, reverse=True):
            if batch % mb:
                continue
            slots = batch // mb  # = world × GAS
            valid = [g for g in range(min_gpus, min(max_gpus, slots) + 1)
                     if slots % g == 0]
            if valid:
                return valid, batch, mb
    raise ElasticityError(
        f"no (batch, world) combination exists for micro_batches="
        f"{micro_batches} max_train_batch={max_train_batch}")


def compute_elastic_config(ds_config: Dict[str, Any],
                           target_deepspeed_version: str = "",
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """Reference signature.  With ``world_size`` > 0 also resolves the final
    (train_batch, micro_batch, GAS) for that world."""
    e = ds_config.get("elasticity", {})
    if not e or not e.get("enabled", False):
        raise ElasticityError("elasticity not enabled in config")
    micro_batches = e.get("micro_batch_sizes", [2, 4, 6])
    max_batch = e.get("max_train_batch_size", 2000)
    min_gpus = e.get("min_gpus", 1)
    max_gpus = e.get("max_gpus", 10000)
    prefer_larger = e.get("prefer_larger_batch", True)

    valid_gpus, final_batch, micro = get_compatible_gpus(
        micro_batches, max_batch, min_gpus, max_gpus)
    if not prefer_larger:
        final_batch = min(_candidate_batches(micro_batches, max_batch))
    elastic = {"train_batch_size": final_batch,
               "micro_batch_sizes": micro_batches,
               "valid_gpus": valid_gpus}
    if world_size > 0:
        if world_size not in valid_gpus and final_batch % world_size:
            raise ElasticityError(
                f"world_size {world_size} incompatible with elastic batch "
                f"{final_batch} (valid counts: {valid_gpus[:16]}...)")
        slots = final_batch // micro
        gas = max(slots // world_size, 1)
        final = {"train_batch_size": final_batch,
                 "train_micro_batch_size_per_gpu": micro,
                 "gradient_accumulation_steps": gas}
        logger.info(f"elasticity: world={world_size} -> {final}")
        if return_microbatch:
            return elastic, final_batch, micro
        return elastic, final_batch
    if return_microbatch:
        return elastic, final_batch, micro
    return elastic, final_batch
