"""Cross-host elastic rendezvous — store, rounds, heartbeats.

Reference: torch-elastic's rendezvous backend (c10d TCPStore + the
etcd/c10d rendezvous state machine) that ``DSElasticAgent`` rides
(``deepspeed/elasticity/elastic_agent.py`` [K], SURVEY §5.3).  Round 2's
agent supervised an in-process worker only; this module adds the
cross-host story:

* :class:`RendezvousServer` — a tiny TCP key-value store (JSON line
  protocol: GET/SET/ADD/WAIT) playing the reference's TCPStore role for
  the CONTROL plane only (the data plane is XLA over ICI/DCN; the hot
  path never touches this).
* :class:`ElasticRendezvous` — versioned membership rounds on top of the
  store: agents join a round, barrier until ``min_nodes`` are present
  (plus a settle window up to ``max_nodes``), and receive deterministic
  ``(round, rank, world, coordinator)`` assignments — rank 0's host
  becomes the ``jax.distributed`` coordinator for that round.
* Heartbeats + round bumps: every agent heartbeats ``hb/<node>``; a
  worker failure (or a stale heartbeat noticed by any peer) bumps the
  round counter, which every other agent's monitor loop watches — they
  tear down their local workers and re-rendezvous.  Membership may differ
  in the new round; resume-at-a-different-world is the checkpoint
  reshard-on-load the runtime already provides.

Store failover (ISSUE 11 tentpole): the store itself must be killable.
Every server boot stamps a fresh ``srv/gen`` generation id; each client
keeps a bounded local **write-journal** of its own durable entries
(round counter, sealed rings, heartbeat slots, replica-index metadata)
and, on reconnecting to a server with a DIFFERENT generation, replays
the journal — so a kill -9'd-and-restarted store rebuilds its state
from the survivors, no shared disk required.  When the retry budget is
exhausted the client enters **degraded mode** instead of crashing its
caller's loop: journaled writes buffer (bounded, replayed on
reconnect), :class:`StoreUnavailableError` is raised for reads, the
outage is counted (``elasticity/store_reconnects_total``, degraded
seconds), and :func:`control_plane_status` feeds the
``control_plane_degraded`` health rule.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import debug_once, log_dist, logger, warn_once


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------

class _StoreState:
    def __init__(self):
        self.data: Dict[str, Any] = {}
        self.cond = threading.Condition()


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        # track live connections so shutdown() severs them like a real
        # process death would — in-process chaos tests must not keep
        # talking to a zombie handler thread after the "kill".  The
        # finally-deregistration keeps the set bounded by LIVE
        # connections (clients reconnect on every transient error; a
        # long store lifetime must not accumulate dead sockets).
        conns = getattr(self.server, "_conns", None)
        if conns is not None:
            with self.server._conns_lock:  # type: ignore[attr-defined]
                conns.add(self.connection)
        try:
            self._serve()
        finally:
            if conns is not None:
                with self.server._conns_lock:  # type: ignore[attr-defined]
                    conns.discard(self.connection)

    def _serve(self):
        state: _StoreState = self.server.state  # type: ignore[attr-defined]
        for raw in self.rfile:
            try:
                req = json.loads(raw)
            except Exception:
                break
            op = req.get("op")
            with state.cond:
                if op == "set":
                    state.data[req["k"]] = req["v"]
                    state.cond.notify_all()
                    out = {"ok": True}
                elif op == "get":
                    out = {"ok": True, "v": state.data.get(req["k"])}
                elif op == "add":
                    v = int(state.data.get(req["k"], 0)) + int(req["d"])
                    state.data[req["k"]] = v
                    state.cond.notify_all()
                    out = {"ok": True, "v": v}
                elif op == "max":
                    # monotonic set: journal replay after a store restart
                    # must never REGRESS a counter another survivor (or a
                    # post-restart bump) already advanced
                    v = max(int(state.data.get(req["k"], 0)),
                            int(req["v"]))
                    state.data[req["k"]] = v
                    state.cond.notify_all()
                    out = {"ok": True, "v": v}
                elif op == "keys":
                    # prefix scan (operator/chaos tooling: "prove no
                    # snapshot bytes live in the store")
                    pref = str(req.get("prefix", ""))
                    out = {"ok": True,
                           "v": sorted(k for k in state.data
                                       if k.startswith(pref))}
                elif op == "append":
                    lst = list(state.data.get(req["k"], []))
                    if req["v"] not in lst:
                        lst.append(req["v"])
                    state.data[req["k"]] = lst
                    state.cond.notify_all()
                    out = {"ok": True, "v": lst}
                elif op == "hb":
                    # heartbeat keys are stamped with the STORE's clock so
                    # staleness checks never compare two hosts' wall clocks
                    # (cross-host skew > ttl would fake peer deaths).
                    # monotonic, not wall: an NTP step on the store host
                    # must not age every heartbeat at once
                    state.data[req["k"]] = time.monotonic()
                    state.cond.notify_all()
                    out = {"ok": True}
                elif op == "now":
                    out = {"ok": True, "v": time.monotonic()}
                elif op == "wait_ge":
                    deadline = time.monotonic() + float(req.get("t", 30.0))
                    ok = True
                    while int(state.data.get(req["k"], 0)) < int(req["v"]):
                        left = deadline - time.monotonic()
                        if left <= 0:
                            ok = False
                            break
                        state.cond.wait(left)
                    out = {"ok": ok, "v": state.data.get(req["k"], 0)}
                else:
                    out = {"ok": False, "err": f"bad op {op!r}"}
            self.wfile.write((json.dumps(out) + "\n").encode())
            self.wfile.flush()


class _StoreTCPServer(socketserver.ThreadingTCPServer):
    # reuse_address: a kill -9'd store must be restartable at the SAME
    # endpoint immediately (clients dial a configured host:port), not
    # after the kernel's TIME_WAIT expires
    allow_reuse_address = True
    daemon_threads = True


class RendezvousServer:
    """Threaded TCP store; start on ONE host (usually alongside agent 0).

    Every boot stamps a fresh ``srv/gen`` generation id into the store —
    reconnecting clients compare it against the generation they first
    saw and replay their write-journals when it changed (the store was
    restarted with empty state)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = _StoreTCPServer((host, port), _Handler,
                                    bind_and_activate=True)
        self._srv.state = _StoreState()  # type: ignore[attr-defined]
        self._srv._conns = set()  # type: ignore[attr-defined]
        self._srv._conns_lock = threading.Lock()  # type: ignore[attr-defined]
        self._srv.state.data["srv/gen"] = \
            f"{os.getpid()}-{time.time_ns()}"  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        log_dist(f"rendezvous store at {self.host}:{self.port}")

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        # sever live client connections (a real store death severs them;
        # without this an in-process "kill" leaves zombie handler
        # threads answering from the dead store's state)
        with self._srv._conns_lock:  # type: ignore[attr-defined]
            conns = list(self._srv._conns)  # type: ignore[attr-defined]
            self._srv._conns.clear()  # type: ignore[attr-defined]
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


class StoreUnavailableError(ConnectionError):
    """The store did not answer within the retry budget — the control
    plane is DEGRADED.  Subclasses :class:`ConnectionError` so every
    existing ``except ConnectionError`` keeps working; loops that can
    buffer (heartbeats, replica-index publication) catch it, mark
    themselves degraded, and resume on reconnect instead of crashing
    the training step."""


#: process-wide client registry: the control-plane health rule and the
#: ``partition_node`` chaos fault act on EVERY live client at once
_registry_lock = threading.Lock()
_all_clients: "weakref.WeakSet" = weakref.WeakSet()
_degraded_clients: "weakref.WeakSet" = weakref.WeakSet()


def control_plane_status() -> Dict[str, Any]:
    """Process-wide control-plane health: ``{degraded, degraded_for_s,
    clients}`` — degraded when ANY live :class:`RendezvousClient` has
    exhausted its retry budget and not yet reconnected.  Consumed by
    the ``control_plane_degraded`` health rule (``telemetry/health.py``)
    so a store outage surfaces as a structured health event instead of
    a crashed daemon thread."""
    with _registry_lock:
        degs = [c for c in _degraded_clients]
    if not degs:
        return {"degraded": False, "degraded_for_s": 0.0, "clients": 0}
    since = min(c._degraded_since for c in degs)
    return {"degraded": True,
            "degraded_for_s": max(time.monotonic() - since, 0.0),
            "clients": len(degs)}


def partition_all(seconds: float) -> int:
    """Chaos: drop THIS process's store connectivity for ``seconds`` —
    every live client blackholes its calls (``partition_node`` fault).
    Returns the number of clients partitioned."""
    with _registry_lock:
        clients = list(_all_clients)
    for c in clients:
        c.partition(seconds)
    return len(clients)


class RendezvousClient:
    """One persistent connection to the store (reconnects on failure).

    Calls retry with bounded exponential backoff on TRANSIENT transport
    errors (ECONNRESET on a store restart, EINTR, a half-closed socket):
    a debug-bundle collector sweeping N hosts must not die because one
    request hit a reset — exactly the moment sweeps happen is the moment
    networks are unhappy.  ``retries`` bounds the extra attempts; the
    final failure raises :class:`StoreUnavailableError` and flips the
    client DEGRADED until a later call succeeds.

    **Write-journal**: callers mark durable writes (``set(...,
    journal=True)`` / :meth:`journal_note`); the journal is bounded and
    replayed whenever a reconnect lands on a server with a different
    ``srv/gen`` generation — a restarted empty store re-seeds itself
    from its surviving clients."""

    #: journal entries kept at most (each key journals once; overflow
    #: drops the NEW entry with a warning — never silently)
    JOURNAL_CAP = 512

    def __init__(self, endpoint: str, timeout: float = 60.0,
                 retries: int = 3, backoff_s: float = 0.05):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        #: {(op, key): value} — this client's durable entries, replayed
        #: on a generation change (guarded by _jlock; _lock -> _jlock
        #: is the only nesting order)
        self._journal: Dict[Tuple[str, str], Any] = {}
        self._jlock = threading.Lock()
        self._gen: Optional[str] = None
        self._ever_connected = False
        #: degraded-mode bookkeeping (see control_plane_status)
        self.degraded = False
        self._degraded_since = 0.0
        self.degraded_seconds_total = 0.0
        self.reconnects = 0
        self.journal_replays = 0
        self._partition_until = 0.0
        #: set on every outage: the next successful connection must
        #: flush the journal even when the server generation is
        #: UNCHANGED — a same-store partition/flap buffers one-shot
        #: journaled writes (endpoint publication, leave flags) that
        #: nothing else would ever re-send
        self._replay_pending = False
        with _registry_lock:
            _all_clients.add(self)

    # -- transport ---------------------------------------------------------

    def _raw(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """One request/reply on the CURRENT connection (no retry, no
        lock — callers hold ``_lock``)."""
        self._file.write((json.dumps(req) + "\n").encode())
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("store closed connection")
        return json.loads(line)

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self._addr, timeout=self._timeout)
            self._file = s.makefile("rwb")
            self._sock = s
            try:
                self._sync_generation()
            except BaseException:
                self.close()
                raise
        return self._sock

    def _sync_generation(self) -> None:
        """Fresh-connection handshake: read the server's boot generation
        and replay the write-journal when it CHANGED (the server
        restarted with empty state and this client's durable entries are
        part of rebuilding it) OR when an outage may have buffered
        journaled writes (same store, dropped route: one-shot entries
        like the replica-server endpoint or a leave flag would otherwise
        never land)."""
        gen = (self._raw({"op": "get", "k": "srv/gen"}) or {}).get("v")
        restarted = (self._gen is not None and gen is not None
                     and gen != self._gen)
        if restarted or self._replay_pending:
            n = self._replay_journal()
            self.journal_replays += 1
            self._replay_pending = False
            why = (f"restarted (generation {self._gen} -> {gen})"
                   if restarted else "reachable again after an outage")
            log_dist(f"rendezvous store {why}: re-published {n} "
                     f"journaled entries")
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                "elasticity/store_state_replays_total",
                help="write-journal replays after an observed store "
                     "restart or outage")
        if gen is not None:
            self._gen = gen
        self._ever_connected = True

    def _replay_journal(self) -> int:
        with self._jlock:
            entries = list(self._journal.items())
        for (op, k), v in entries:
            if op == "hb":
                self._raw({"op": "hb", "k": k})
            elif op == "max":
                self._raw({"op": "max", "k": k, "v": v})
            elif op == "append":
                self._raw({"op": "append", "k": k, "v": v})
            else:
                self._raw({"op": "set", "k": k, "v": v})
        return len(entries)

    def _call(self, **req) -> Dict[str, Any]:
        with self._lock:
            if self._partition_until:
                if time.monotonic() < self._partition_until:
                    self.close()
                    err = ConnectionError(
                        "store connectivity partitioned (chaos)")
                    self._mark_degraded(err)
                    raise StoreUnavailableError(
                        f"store call dropped: {err}") from err
                self._partition_until = 0.0
            last: Optional[BaseException] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    # bounded exponential backoff, capped so a long
                    # retry budget never stalls a heartbeat loop for
                    # more than ~2s per wait
                    time.sleep(min(self.backoff_s * (2 ** (attempt - 1)),
                                   2.0))
                try:
                    self._connect()
                    out = self._raw(req)
                    self._mark_healthy()
                    return out
                except (OSError, ConnectionError, ValueError) as e:
                    # ValueError: a line truncated by a mid-reply close
                    # parses as bad JSON — same transient as the reset
                    last = e
                    self.close()
            self._mark_degraded(last)
            raise StoreUnavailableError(
                f"store call failed after {self.retries + 1} attempts: "
                f"{last!r}") from last

    def close(self) -> None:
        # close the makefile() wrapper too: it holds its own reference
        # to the underlying fd, so closing only the socket object would
        # leave the connection half-open — the server's handler thread
        # would never see EOF and its connection entry would linger
        f = getattr(self, "_file", None)
        if f is not None:
            try:
                f.close()
            except (OSError, ValueError):
                pass
            self._file = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- degraded-mode bookkeeping ----------------------------------------

    def _mark_degraded(self, err: Optional[BaseException]) -> None:
        if self.degraded:
            return
        self.degraded = True
        self._replay_pending = True  # flush the journal on reconnect
        self._degraded_since = time.monotonic()
        with _registry_lock:
            _degraded_clients.add(self)
        logger.warning(f"rendezvous store unreachable ({err!r}) — "
                       f"control plane DEGRADED: journaled writes "
                       f"buffer and replay on reconnect")
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "elasticity/store_outages_total",
            help="times a store client exhausted its retry budget and "
                 "entered degraded mode")

    def _mark_healthy(self) -> None:
        if not self.degraded:
            return
        dur = max(time.monotonic() - self._degraded_since, 0.0)
        self.degraded_seconds_total += dur
        self.degraded = False
        self.reconnects += 1
        with _registry_lock:
            _degraded_clients.discard(self)
        log_dist(f"rendezvous store reachable again after {dur:.1f}s "
                 f"degraded")
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        tel.inc_counter(
            "elasticity/store_reconnects_total",
            help="store clients that recovered from degraded mode "
                 "(heartbeats resume, buffered writes replay)")
        tel.inc_counter(
            "elasticity/store_degraded_seconds_total", v=dur,
            help="cumulative wall-clock seconds store clients spent in "
                 "degraded mode")

    def partition(self, seconds: float) -> None:
        """Chaos: blackhole every call for ``seconds`` (client-side
        partition — the practical stand-in for dropping this node's
        store route)."""
        with self._lock:
            self._partition_until = time.monotonic() + float(seconds)
            self.close()

    # -- write-journal ------------------------------------------------------

    def journal_note(self, op: str, k: str, v: Any = None) -> None:
        """Record a durable entry WITHOUT writing it now (the caller
        already wrote it, or learned it from a read): replayed verbatim
        after a store restart.  ``op`` is one of ``set|max|append|hb``."""
        with self._jlock:
            if ((op, k) not in self._journal
                    and len(self._journal) >= self.JOURNAL_CAP):
                warn_once("rendezvous/journal_cap",
                          f"store write-journal full ({self.JOURNAL_CAP} "
                          f"entries) — dropping new entry {op}:{k}; a "
                          f"store restart would not replay it")
                return
            self._journal[(op, k)] = v

    def journal_forget(self, op: str, k: str) -> None:
        with self._jlock:
            self._journal.pop((op, k), None)

    def journal_size(self) -> int:
        with self._jlock:
            return len(self._journal)

    # -- ops ----------------------------------------------------------------

    def set(self, k: str, v: Any, journal: bool = False) -> None:
        """Write ``k``.  With ``journal=True`` the entry is durable: it
        replays after a store restart, and a degraded-mode failure
        BUFFERS (the journal is the buffer) instead of raising — the
        write lands on reconnect."""
        if journal:
            self.journal_note("set", k, v)
        try:
            self._call(op="set", k=k, v=v)
        except StoreUnavailableError:
            if not journal:
                raise
            debug_once("rendezvous/buffered_set",
                       f"store down — journaled write {k!r} buffered "
                       f"for replay on reconnect")

    def get(self, k: str) -> Any:
        return self._call(op="get", k=k)["v"]

    def add(self, k: str, d: int = 1) -> int:
        return int(self._call(op="add", k=k, d=d)["v"])

    def max(self, k: str, v: int, journal: bool = False) -> int:
        if journal:
            self.journal_note("max", k, int(v))
        return int(self._call(op="max", k=k, v=int(v))["v"])

    def append(self, k: str, v: Any) -> List[Any]:
        return list(self._call(op="append", k=k, v=v)["v"])

    def keys(self, prefix: str = "") -> List[str]:
        return list(self._call(op="keys", prefix=prefix)["v"])

    def wait_ge(self, k: str, v: int, timeout: float = 30.0) -> bool:
        return bool(self._call(op="wait_ge", k=k, v=v, t=timeout)["ok"])

    def hb(self, k: str, journal: bool = False) -> None:
        if journal:
            self.journal_note("hb", k)
        try:
            self._call(op="hb", k=k)
        except StoreUnavailableError:
            if not journal:
                raise
            debug_once("rendezvous/buffered_hb",
                       f"store down — heartbeat {k!r} buffered for "
                       f"replay on reconnect")

    def now(self) -> float:
        return float(self._call(op="now")["v"])


# ---------------------------------------------------------------------------
# rendezvous rounds
# ---------------------------------------------------------------------------

class ElasticRendezvous:
    """Versioned membership rounds (torch-elastic rendezvous role).

    Each agent calls :meth:`next_round` to (re-)join; the call blocks
    until ``min_nodes`` agents are present in the CURRENT round, waits a
    short settle window for late joiners (up to ``max_nodes``), then
    returns ``(round_id, rank, world, coordinator_address)``.  Ranks are
    the sorted order of node ids — deterministic across agents.
    """

    def __init__(self, client: RendezvousClient, node_id: str,
                 min_nodes: int = 1, max_nodes: int = 64,
                 coordinator_port: int = 9876, settle_s: float = 0.3,
                 timeout_s: float = 60.0):
        self.c = client
        self.node_id = node_id
        self.min_nodes = int(min_nodes)
        self.max_nodes = int(max_nodes)
        self.coordinator_port = int(coordinator_port)
        self.settle_s = float(settle_s)
        self.timeout_s = float(timeout_s)
        # grace bookkeeping for peers that sealed a round but have not yet
        # written their first heartbeat (store-clock first-missing stamps);
        # reset whenever we join a new round — stale notices from an old
        # round must not shortcut the new round's grace window
        self._hb_missing: Dict[str, float] = {}
        # store-clock time our current round formed: heartbeat stamps older
        # than this are leftovers from a previous round (a slow-rejoining
        # peer that sealed but hasn't beaten yet) and get the same grace as
        # a missing stamp instead of an instant death
        self._round_start: float = 0.0
        #: latched by next_round when this node had to bump a SEALED
        #: round to get in — i.e. it is joining a gang that was already
        #: running (scale-up).  The agent exports it so the worker's
        #: resume path knows to bootstrap from a peer replica instead of
        #: starting at step 0.
        self.joined_running: bool = False

    # round bookkeeping keys
    @staticmethod
    def _members_key(r: int) -> str:
        return f"rdzv/round/{r}/members"

    @staticmethod
    def _sealed_key(r: int) -> str:
        return f"rdzv/round/{r}/sealed"

    def current_round(self) -> int:
        r = int(self.c.get("rdzv/round") or 0)
        if r:
            # journal the highest round this node has OBSERVED: after a
            # store restart the replayed `max` keeps the counter from
            # regressing past what any survivor saw (a regressed counter
            # would read as "round moved" and tear every worker down)
            self.c.journal_note("max", "rdzv/round", r)
        return r

    def bump_round(self, reason: str = "") -> int:
        r = self.c.add("rdzv/round", 1)
        self.c.journal_note("max", "rdzv/round", r)
        log_dist(f"rendezvous round bumped to {r} ({reason})")
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "elastic/round_bumps",
            help="rendezvous round counter bumps (membership churn)")
        return r

    def next_round(self) -> Tuple[int, int, int, str]:
        """(Re-)join; blocks until a round seals with this node inside —
        telemetry: the wait is one span, a sealed join bumps
        ``elastic/rounds_joined`` and sets the ``elastic/world`` gauge."""
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        with tel.span("elastic/next_round", args={"node": self.node_id}):
            out = self._next_round_impl()
        tel.inc_counter("elastic/rounds_joined",
                        help="rendezvous rounds this node sealed into")
        tel.set_gauge("elastic/world", out[2],
                      help="world size of the current round")
        tel.set_gauge("elastic/round", out[0],
                      help="current rendezvous round id")
        return out

    def _next_round_impl(self) -> Tuple[int, int, int, str]:
        deadline = time.monotonic() + self.timeout_s
        my_host = _my_host(self.c._addr)
        # re-armed per join attempt: a node that ONCE joined mid-run is
        # not forever a joiner — only this attempt's sealed-round bump
        # latches it
        self.joined_running = False
        while True:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"rendezvous: no stable round within {self.timeout_s}s")
            r = self.current_round()
            if self.c.get(self._sealed_key(r)):
                # SCALE-UP: this round's gang already formed and is
                # running; joining its member list would give us a world
                # the running peers don't share.  Bump so everyone
                # (their monitors watch the counter) re-forms with us.
                # rejoin immediately — the running peers need a monitor
                # tick to notice the bump, so our append lands well inside
                # the new round's settle window
                sealed = self.c.get(self._sealed_key(r)) or [[]]
                if self.node_id not in list(sealed[0]):
                    # joining a gang that was ALREADY running without us:
                    # latch so the agent/worker resume path knows to
                    # bootstrap mid-run state instead of step 0
                    self.joined_running = True
                self.bump_round(f"node {self.node_id} joining a sealed "
                                f"round")
                continue
            members = self.c.append(self._members_key(r),
                                    [self.node_id, my_host])
            if len(members) < self.min_nodes:
                # block until enough peers have joined THIS round (or the
                # round moves on under us)
                while (time.monotonic() < deadline
                       and self.current_round() == r
                       and len(members) < self.min_nodes):
                    time.sleep(0.05)
                    members = self.c.append(self._members_key(r),
                                            [self.node_id, my_host])
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"rendezvous round {r}: {len(members)} of "
                        f"{self.min_nodes} nodes after {self.timeout_s}s")
            if self.current_round() != r:
                continue  # round moved while we waited — rejoin
            time.sleep(self.settle_s)  # late joiners up to max_nodes
            if self.current_round() != r:
                continue  # bumped during the settle window — rejoin
            members = sorted(self.c.get(self._members_key(r)) or [],
                             key=lambda m: m[0])[:self.max_nodes]
            ids = [m[0] for m in members]
            # SEAL via atomic append: the FIRST returner's membership list
            # freezes the gang — every agent (however racy its own view)
            # adopts element 0, so no two members ever compute different
            # worlds for the same round
            frozen = self.c.append(self._sealed_key(r), ids)[0]
            if self.node_id not in frozen:
                if self.node_id in ids:
                    # arrived inside the settle window after the freeze:
                    # force a re-formation that includes us
                    self.bump_round(f"node {self.node_id} arrived after "
                                    f"round {r} sealed")
                    continue
                # squeezed out by max_nodes: park as STANDBY — the round
                # composition cannot change until the counter moves
                while (time.monotonic() < deadline
                       and self.current_round() == r):
                    time.sleep(self.settle_s)
                continue
            rank = frozen.index(self.node_id)
            world = len(frozen)
            # store-failover journal: this node vouches for the round it
            # sealed into — a restarted store gets the counter AND the
            # frozen ring back from any survivor (append replay is
            # idempotent: every member re-appends the SAME frozen list).
            # Sealed-ring history older than the adoption lookback is
            # pruned so the journal stays bounded.
            self.c.journal_note("max", "rdzv/round", r)
            self.c.journal_note("append", self._sealed_key(r), frozen)
            for p in range(max(0, r - 64), r - 8):
                self.c.journal_forget("append", self._sealed_key(p))
            # Each round publishes a FRESH coordinator endpoint through the
            # store: rank 0 binds an ephemeral port on its own host (the
            # only host that can know what's free there) so a hung
            # coordinator from an earlier round can never collide with the
            # new round's jax.distributed.initialize (ports never recycle
            # round-mod-N style).
            coord_key = f"rdzv/round/{r}/coord"
            if rank == 0:
                self.c.set(
                    coord_key,
                    f"{my_host}:{_free_port(self.coordinator_port)}")
            coord = self.c.get(coord_key)
            # bounded wait: if rank 0 died between sealing and publishing,
            # nothing else would ever bump this round (monitors only run
            # after next_round returns) — so WE bump and re-form instead
            # of burning the whole rendezvous deadline waiting
            coord_deadline = min(deadline,
                                 time.monotonic() + 5 * self.settle_s + 2.0)
            while coord is None and time.monotonic() < coord_deadline:
                if self.current_round() != r:
                    break
                time.sleep(0.02)
                coord = self.c.get(coord_key)
            if coord is None:
                if self.current_round() == r:
                    self.bump_round(f"round {r}: rank 0 never published "
                                    f"a coordinator")
                continue  # re-form without rank 0's corpse
            self.c.set(f"rdzv/left/{self.node_id}", False,
                       journal=True)  # (re)joined
            self._hb_missing.clear()
            self._round_start = self.c.now()
            self.heartbeat()
            return r, rank, world, coord

    # -- failure detection -------------------------------------------------

    def heartbeat(self, payload: Optional[Dict[str, Any]] = None) -> None:
        # stamped by the STORE's clock (op=hb), not this host's — see
        # stale_peers: all staleness math happens on one clock.
        # Both writes are JOURNALED: with the store down they buffer
        # (the beat resumes on reconnect instead of dying in the daemon
        # thread), and after a store restart the replay re-stamps this
        # node's liveness before any peer can mistake it for dead.
        self.c.hb(f"rdzv/hb/{self.node_id}", journal=True)
        if payload:
            # liveness summary riding the heartbeat (the watchdog's step
            # index / step-time EWMA): rank 0 folds every peer's payload
            # into straggler-skew gauges (publish_straggler_stats)
            self.c.set(f"rdzv/hbinfo/{self.node_id}", payload,
                       journal=True)

    def peer_heartbeat_ages(self, peer_ids: List[str]
                            ) -> Dict[str, Dict[str, Any]]:
        """Per-node last-heartbeat age (store clock) + the last payload —
        embedded in watchdog debug bundles so a hang dump distinguishes
        "my host stalled" from "a peer died"."""
        now = self.c.now()
        out: Dict[str, Dict[str, Any]] = {}
        for pid in peer_ids:
            ts = self.c.get(f"rdzv/hb/{pid}")
            out[pid] = {
                "age_s": None if ts is None else round(now - float(ts), 3),
                "left": bool(self.c.get(f"rdzv/left/{pid}")),
                "info": self.c.get(f"rdzv/hbinfo/{pid}"),
            }
        return out

    def publish_straggler_stats(self, peer_ids: List[str]
                                ) -> Dict[str, float]:
        """Rank 0 only: fold every peer's heartbeat payload into skew
        gauges — ``elastic/straggler_step_skew`` (max-min step index
        across hosts) and ``elastic/straggler_ewma_ratio`` (slowest
        host's step-time EWMA over the median's)."""
        infos = [self.c.get(f"rdzv/hbinfo/{pid}") for pid in peer_ids]
        steps = [int(i["step"]) for i in infos
                 if isinstance(i, dict) and "step" in i]
        ewmas = [float(i["step_time_ewma_ms"]) for i in infos
                 if isinstance(i, dict) and i.get("step_time_ewma_ms")]
        stats: Dict[str, float] = {}
        from ..telemetry import get_telemetry

        tel = get_telemetry()
        if len(steps) >= 2:
            stats["step_skew"] = float(max(steps) - min(steps))
            tel.set_gauge("elastic/straggler_step_skew", stats["step_skew"],
                          help="max-min per-host step index across the gang")
        if len(ewmas) >= 2:
            med = sorted(ewmas)[len(ewmas) // 2]
            stats["ewma_ratio"] = max(ewmas) / max(med, 1e-9)
            tel.set_gauge(
                "elastic/straggler_ewma_ratio", stats["ewma_ratio"],
                help="slowest host step-time EWMA over the median host's")
        # per-host rolling goodput rides the same payload
        # (telemetry/perf/goodput.py): publish the cluster view — the
        # worst host bounds the gang (every collective waits for it)
        gps = [float(i["goodput"]) for i in infos
               if isinstance(i, dict) and i.get("goodput") is not None]
        if gps:
            stats["goodput_min"] = min(gps)
            stats["goodput_mean"] = sum(gps) / len(gps)
            tel.set_gauge("elastic/cluster_goodput_min", stats["goodput_min"],
                          help="worst per-host rolling goodput fraction")
            tel.set_gauge("elastic/cluster_goodput_mean",
                          stats["goodput_mean"],
                          help="mean per-host rolling goodput fraction")
        # per-host HBM high-water + headroom ride the same payload
        # (telemetry/memory): the fullest host is the one the next shape
        # bump OOMs, and the smallest headroom bounds what autotuning
        # may safely try cluster-wide
        hbms = [float(i["hbm_frac"]) for i in infos
                if isinstance(i, dict) and i.get("hbm_frac") is not None]
        if hbms:
            stats["hbm_max"] = max(hbms)
            tel.set_gauge("elastic/cluster_hbm_max", stats["hbm_max"],
                          help="fullest per-host HBM used fraction")
        rooms = [float(i["hbm_headroom"]) for i in infos
                 if isinstance(i, dict)
                 and i.get("hbm_headroom") is not None]
        if rooms:
            stats["hbm_headroom_min"] = min(rooms)
            tel.set_gauge("elastic/cluster_hbm_headroom_min",
                          stats["hbm_headroom_min"],
                          help="smallest per-host HBM headroom fraction "
                               "(1 - peak/limit)")
        return stats

    def left_peers(self, peer_ids: List[str]) -> List[str]:
        """Peers that marked a GRACEFUL departure (``leave()``).  The
        agent's settle-window classifier needs this: a leaver never goes
        stale (``stale_peers`` skips left nodes by design), but its bump
        is still a capacity LOSS — survivors must re-form promptly, not
        wait out the scale-up settle window."""
        return [pid for pid in peer_ids
                if pid != self.node_id
                and bool(self.c.get(f"rdzv/left/{pid}"))]

    def sealed_ring(self, r: Optional[int] = None) -> List[str]:
        """The FROZEN gang of round ``r`` (default: current round) —
        empty when that round never sealed.  Sealed keys are never
        deleted, so the ring history survives in the store for
        :meth:`ring_diff` to walk."""
        if r is None:
            r = self.current_round()
        sealed = self.c.get(self._sealed_key(int(r)))
        return list(sealed[0]) if sealed else []

    def ring_diff(self, lookback: int = 50) -> Dict[str, Any]:
        """Diff the CURRENT sealed ring against the most recent
        PREVIOUS sealed round (scanning back up to ``lookback`` rounds —
        churn bumps rounds without sealing them, so r-1 is often empty).
        Returns ``{round, prev_round, cur, prev, joined, left}`` — the
        replacement-node adoption path reads ``left`` (dead peers whose
        tier-2 replicas are orphaned) and ``joined`` (who adopts)."""
        r = self.current_round()
        cur = self.sealed_ring(r)
        for p in range(r - 1, max(-1, r - 1 - int(lookback)), -1):
            prev = self.sealed_ring(p)
            if prev:
                return {"round": r, "prev_round": p, "cur": cur,
                        "prev": prev,
                        "joined": [n for n in cur if n not in prev],
                        "left": [n for n in prev if n not in cur]}
        return {"round": r, "prev_round": None, "cur": cur, "prev": [],
                "joined": list(cur), "left": []}

    def buddy(self) -> Optional[str]:
        """This node's snapshot buddy: the NEXT node id in the current
        round's sealed ring (deterministic on every host — same sorted
        gang), or None when the gang has a single member.  Tier-2
        replication uploads this node's snapshot into the rendezvous
        store under ITS OWN node id; the buddy is the peer expected to
        ADOPT that slot when this host dies (a gang of one has nobody
        to adopt anything, so replication is skipped)."""
        r = self.current_round()
        sealed = self.c.get(self._sealed_key(r))
        gang = list(sealed[0]) if sealed else []
        if self.node_id not in gang or len(gang) < 2:
            return None
        return gang[(gang.index(self.node_id) + 1) % len(gang)]

    def leave(self) -> None:
        """Graceful departure: a finished node stops heartbeating but must
        not be mistaken for a death — peers skip left nodes in
        :meth:`stale_peers` and keep their own attempts running."""
        self.c.set(f"rdzv/left/{self.node_id}", True, journal=True)

    def stale_peers(self, peer_ids: List[str], ttl_s: float) -> List[str]:
        # one clock for everything: heartbeats are store-stamped (op=hb)
        # and "now" is the store's clock too, so cross-host skew cannot
        # fake a death
        now = self.c.now()
        stale = []
        for pid in peer_ids:
            if pid == self.node_id:
                continue
            if self.c.get(f"rdzv/left/{pid}"):
                continue  # graceful leave, not a death
            ts = self.c.get(f"rdzv/hb/{pid}")
            if ts is None or float(ts) < self._round_start:
                # no heartbeat for THIS round yet (never beaten, or the
                # stamp is a leftover from a previous round — a slow
                # rejoiner that sealed but hasn't beaten) — grace it for a
                # full ttl from when WE first noticed, instead of
                # declaring it dead on our first monitor tick
                first = self._hb_missing.setdefault(pid, now)
                if now - first > ttl_s:
                    stale.append(pid)
                continue
            self._hb_missing.pop(pid, None)
            if now - float(ts) > ttl_s:
                stale.append(pid)
        if stale:
            from ..telemetry import get_telemetry

            get_telemetry().inc_counter(
                "elastic/stale_peers_detected", v=len(stale),
                help="peers whose heartbeat went stale (suspected deaths)")
        return stale


def _free_port(base: Optional[int] = None) -> int:
    """A currently-free TCP port.  With ``base``, scan a small window
    starting there (operators firewall a known range around the
    configured coordinator_port) and fall back to an OS ephemeral port
    only if the whole window is busy.  Bind-testing is what fixes the
    original bug: a hung coordinator still bound on a port is SKIPPED
    instead of collided with.  (The tiny close→reuse window is the
    standard ephemeral-port trade.)"""
    candidates = list(range(base, base + 64)) if base else []
    for port in candidates + [0]:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.bind(("", port))
            return int(s.getsockname()[1])
        except OSError:
            continue
        finally:
            s.close()
    raise OSError("no free TCP port")


def _my_host(store_addr: Optional[Tuple[str, int]] = None) -> str:
    """This node's address as PEERS can reach it.  ``DS_ELASTIC_HOST``
    overrides; otherwise the outbound-interface IP toward the store (a
    connected UDP socket reads the route without sending anything) — the
    address that reaches the store is the one peers can dial for the
    ``jax.distributed`` coordinator.  Loopback only as a last resort."""
    env = os.environ.get("DS_ELASTIC_HOST")
    if env:
        return env
    if store_addr is not None:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((store_addr[0], int(store_addr[1])))
                ip = s.getsockname()[0]
            finally:
                s.close()
            if ip and not ip.startswith("0."):
                return ip
        except OSError:
            pass
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"
