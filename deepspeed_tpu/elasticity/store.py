"""Standalone rendezvous store process.

``python -m deepspeed_tpu.elasticity.store --host H --port P`` runs the
:class:`~.rendezvous.RendezvousServer` as its OWN process — the shape
production deployments and the process-level chaos harness need: a
store you can ``kill -9`` and restart at the same endpoint, watching
the surviving clients re-seed its state from their write-journals
(`rendezvous.py` docstring, ISSUE 11 tentpole).

The ``restart_store`` fault (``resilience/faults.py``) spawns this
module detached when no harness callback is registered.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from typing import List, Optional

from .rendezvous import RendezvousServer


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.elasticity.store",
        description="run a rendezvous store as a standalone process "
                    "(kill -9-able; surviving clients re-seed a restart "
                    "from their write-journals)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--endpoint", default=None,
                   help="host:port shorthand (overrides --host/--port)")
    p.add_argument("--pid_file", default=None,
                   help="write this process's pid here (chaos harnesses "
                        "kill -9 it)")
    args = p.parse_args(argv)
    host, port = args.host, args.port
    if args.endpoint:
        h, _, pt = args.endpoint.rpartition(":")
        host, port = h or host, int(pt)
    srv = RendezvousServer(host, port)
    if args.pid_file:
        with open(args.pid_file, "w") as fh:
            fh.write(str(os.getpid()))
    # one parseable readiness line, flushed — harnesses wait on it
    print(f"DS_RDZV_ENDPOINT={srv.endpoint}", flush=True)
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
