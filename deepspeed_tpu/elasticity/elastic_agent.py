"""Elastic agent v2 — restart/rendezvous supervision.

Reference: ``deepspeed/elasticity/elastic_agent.py:DSElasticAgent`` [K]
(SURVEY §5.3): subclasses torch-elastic's agent — rendezvous store, worker
monitoring, restart on membership change or failure, each restart
re-initializing the process group and resuming from checkpoint.

TPU mapping (SURVEY §5.3's plan): the rendezvous/process-group piece is
``jax.distributed.initialize`` driven by coordinator env vars, and "resume
at a different world size" is the checkpoint reshard-on-load the runtime
already provides (orbax restores into whatever mesh the restarted world
builds).  What the agent owns is the supervision loop: run the training
function, catch worker failure, tear down the distributed client,
re-rendezvous (env may now describe a different world), and relaunch from
the latest checkpoint — up to ``max_restarts``.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Optional

from ..utils.logging import log_dist, logger


class WorkerSpec:
    """Reference-shaped description of the elastic worker."""

    def __init__(self, fn: Callable[..., Any], args: tuple = (),
                 max_restarts: int = 3, monitor_interval: float = 0.1,
                 checkpoint_dir: Optional[str] = None):
        self.fn = fn
        self.args = args
        self.max_restarts = int(max_restarts)
        self.monitor_interval = float(monitor_interval)
        self.checkpoint_dir = checkpoint_dir


class DSElasticAgent:
    """Supervise an elastic training function.

    ``fn(restart_count, checkpoint_dir, *args)`` runs one training
    attempt; raising marks the attempt failed.  Between attempts the agent
    re-reads the coordinator env (COORDINATOR_ADDRESS / NUM_PROCESSES /
    PROCESS_ID — the jax.distributed discovery the launcher sets) and
    re-initializes the distributed client, so a changed membership simply
    yields a different mesh on relaunch; state continuity comes from the
    checkpoint dir (reshard-on-load handles the new layout).
    """

    def __init__(self, spec: WorkerSpec, start_method: str = "inproc"):
        self.spec = spec
        self.start_method = start_method
        self.restart_count = 0
        self.last_result: Any = None

    # -- rendezvous --------------------------------------------------------

    def _rendezvous(self) -> None:
        """(Re-)join the jax.distributed world described by the env.
        No-op when no coordinator is configured (single process)."""
        import jax

        coord = os.environ.get("COORDINATOR_ADDRESS")
        if not coord:
            return
        try:
            jax.distributed.shutdown()
        except Exception:
            pass  # not initialized yet
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("PROCESS_ID", "0")))
        log_dist(f"elastic rendezvous: world={os.environ.get('NUM_PROCESSES')}"
                 f" process={os.environ.get('PROCESS_ID')}")

    # -- supervision loop --------------------------------------------------

    def run(self) -> Any:
        spec = self.spec
        while True:
            try:
                self._rendezvous()
                self.last_result = spec.fn(self.restart_count,
                                           spec.checkpoint_dir, *spec.args)
                log_dist(f"elastic worker finished after "
                         f"{self.restart_count} restart(s)")
                return self.last_result
            except SystemExit as e:
                # scripts commonly end via sys.exit(main()); code 0/None is
                # success, anything else is a worker failure to supervise
                if e.code in (0, None):
                    return self.last_result
                e = RuntimeError(f"worker exited with code {e.code}")
                self._maybe_restart(e)
            except Exception as e:  # worker failure → restart or give up
                self._maybe_restart(e)

    def _maybe_restart(self, e: BaseException) -> None:
        spec = self.spec
        self.restart_count += 1
        if self.restart_count > spec.max_restarts:
            logger.error(f"elastic agent: giving up after "
                         f"{spec.max_restarts} restarts ({e!r})")
            raise e
        logger.warning(f"elastic agent: worker failed ({e!r}); restart "
                       f"{self.restart_count}/{spec.max_restarts}")
        time.sleep(spec.monitor_interval)


def launch_elastic(fn: Callable[..., Any], args: tuple = (),
                   max_restarts: int = 3,
                   checkpoint_dir: Optional[str] = None) -> Any:
    """Convenience wrapper (reference ``ds_elastic`` entry role)."""
    spec = WorkerSpec(fn, args=args, max_restarts=max_restarts,
                      checkpoint_dir=checkpoint_dir)
    return DSElasticAgent(spec).run()


def cli_main(argv=None) -> int:
    """``ds_elastic`` CLI: supervise a user script under the agent."""
    import argparse
    import runpy
    import sys

    parser = argparse.ArgumentParser(prog="ds_elastic")
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--checkpoint_dir", default=None)
    parser.add_argument("user_script")
    parser.add_argument("user_args", nargs="*")
    args = parser.parse_args(argv)

    def worker(restart_count, ckpt_dir):
        os.environ["DS_ELASTIC_RESTART_COUNT"] = str(restart_count)
        if ckpt_dir:
            os.environ["DS_ELASTIC_CHECKPOINT_DIR"] = ckpt_dir
        sys.argv = [args.user_script] + list(args.user_args)
        runpy.run_path(args.user_script, run_name="__main__")
        return 0

    launch_elastic(worker, max_restarts=args.max_restarts,
                   checkpoint_dir=args.checkpoint_dir)
    return 0
